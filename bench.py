"""Benchmark: BERT-large phase-1 pretraining throughput on the local chip(s).

Runs the full jitted training step (microbatch scan, bf16 forward/backward,
LAMB with poly-warmup schedule) on synthetic phase-1-shaped data
(seq 128, max_pred 20) and reports sequences/second — the reference's
``training_seq_per_sec`` headline metric (run_pretraining.py:597-599).

Prints ONE JSON line:
  {"metric": "bert_large_phase1_seq_per_sec", "value": N,
   "unit": "seq/s/chip", "vs_baseline": N, "mfu": N}

The reference repo publishes no numbers (BASELINE.md); ``vs_baseline``
normalizes against the NVIDIA DeepLearningExamples BERT-large phase-1
per-A100 throughput (~360 seq/s, fp16 + LAMB) that the reference's configs
are tuned for — the closest external anchor the reference offers. ``mfu``
(model-FLOPs utilisation, utils/flops.py) is the hardware-normalised
number that does not depend on that anchor.

Capture hardening: the TPU backend behind the tunnel can hang or fail
transiently at init (round 1 lost its entire perf capture to exactly
that). The parent process therefore never touches JAX itself: it probes
the backend in a short-timeout subprocess, runs the real benchmark in a
second subprocess (so a hung init is killed, not waited on), retries
with backoff, and on final failure still prints the one-line JSON with
an ``"error"`` field so the driver always records something parseable.
Set BENCH_CHILD=1 to run the benchmark body directly (what the parent
spawns); knobs: BENCH_ATTEMPTS, BENCH_BACKOFF_S, BENCH_PROBE_TIMEOUT_S,
BENCH_ATTEMPT_TIMEOUT_S, BENCH_BUDGET_S. BENCH_PACK=1 (or
``--pack_sequences``) benches the sequence-packed step on synthetic
mixed-length data and stamps padding_efficiency into the result
(docs/packing.md).

Cold-start survival (the round-1/round-2 failure mode): a BERT-large
compile through the tunnel can take 10-30 min, far beyond any one attempt
window, so a cold cache on a freshly started round could never produce a
number. Three mitigations, in order:
  1. The persistent XLA compile cache defaults to ``.jax_cache/`` INSIDE
     the repo, and the capture harness commits the populated entries for
     exactly the bench shapes — a later round starts warm and the full
     bench completes in a couple of minutes.
  2. When the cache directory is empty (truly cold), the parent spends
     its whole budget — minus the degraded-fallback tail reserve below —
     on ONE long attempt instead of three short ones: a killed compile
     writes no cache entry, so one long window is the only configuration
     that can make *progress* across retries.
  3. If the full-model attempts fail with the backend alive and
     BENCH_DEGRADE != 0 (default auto), a last attempt runs BERT-base at
     the same phase-1 shape (BENCH_DEGRADED=1): a smaller-but-real
     measurement (metric name says ``bert_base``, ``"degraded": true``,
     vs_baseline uses a FLOP-scaled anchor) beats another zero. The
     fallback runs whether or not any cache is warm — on a live tunnel a
     cold BERT-base compile plausibly fits a few-minute tail window,
     unlike BERT-large's 10-30 min — and the tail reserve is sized on the
     DEGRADED config's own warm marker (its cache entry is what makes the
     fallback fast), not the normal config's.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

A100_PHASE1_SEQ_PER_SEC = 360.0
# Phase-2 anchor: same NVIDIA recipe at seq 512 runs ~72 seq/s/A100 (the
# published phase-2:phase-1 per-GPU ratio is ~1:5).
A100_PHASE2_SEQ_PER_SEC = 72.0

# Per-chip microbatch. The phase-1 recipe uses 96/GPU on 40GB A100s
# (BASELINE.md); tuned for a 16GB v5e chip with fp32 master params.
# Measured on v5e (seq 128, max_pred 20, dropout on):
#   batch 32, remat none, threefry: 281 seq/s   (fits without remat)
#   batch 32, remat none, rbg:      327 seq/s   (hardware RNG for dropout)
#   batch 64, remat dots, rbg:      382 seq/s   (remat unlocks 2x batch)
#   batch 56, remat dots, rbg:      396 seq/s   (batch sweep peak: 48→388,
#                                                52→385, 56→396, 60→392, 64→382)
# NB: 56 is the single-chip BENCH shape. The shipped recipe configs keep
# local_batch_size 64: the recipes' global batch (65536 = 2^16) must divide
# by local_batch x data_shards for the accumulation split, and 56 doesn't;
# 64 is the fastest gbs-compatible per-chip batch (~3.5% below the peak).
# 'dots' remat keeps matmul outputs and recomputes elementwise ops in the
# backward; with the TPU hardware RNG ('rbg') that recompute is cheap, so the
# larger microbatch wins. With threefry the same config is SLOWER than
# batch 32 (recompute regenerates every dropout mask in ALU ops).
# BENCH_PHASE=2 switches to the phase-2 recipe shape (seq 512, max_pred 80)
# where the fused Pallas attention kernel is the winning backend
# (ops/attention.py: 84 vs ~52 seq/s); the driver's headline stays phase-1.
# Phase-2 batch sweep (pallas, remat dots, rbg): 24→81.7, 28→82.4, 32→82.2
# seq/s with 512-wide tiles; bh-batched tiles (G=8/program) lift 28 to
# 84.3. (The original 256x256 single-bh tiles measured 70.7.)
# BENCH_KFAC=1 preconditions with distributed K-FAC at the runner's default
# cadence (factors every 10 steps, inverses every 100): the measured window
# holds 2 factor passes + 1 Cholesky inverse update in 20 steps, so the
# reported number is steady-state throughput with the inverse amortization
# ~5x pessimistic. Measured (round 2, stats capture): 236 seq/s/chip vs
# 397 first-order (1.7x per-step cost: every-step preconditioning solves
# on the MXU + a 16-seq stats fwd/bwd every 10 steps + a Cholesky inverse
# update). BENCH_KFAC_CAPTURE selects the factor-capture mode: 'train'
# (default) harvests factors from microbatch 0 of the step's own backward
# (the fused hook-parity path, pretrain.make_train_step; CPU proxy at
# factor_interval=1: 0.83x the step cost of an equal-statistics stats
# pass, i.e. full-microbatch factor quality at the 16-row subsampled
# pass's price — KFAC_CAPTURE_BENCH_r04.jsonl); 'stats' keeps the
# round-3 decoupled stats pass for comparability with the round-2 number.
# BENCH_PACK=1 (or passing --pack_sequences on the command line) benches
# SEQUENCE PACKING (docs/packing.md): synthetic mixed-length samples are
# greedily packed into full rows (sequence_ids + per-sequence NSP heads +
# block-diagonal attention), and the result carries padding_efficiency —
# the fraction of the token budget that is real work. Compare against the
# default full-row run: rows/s stays ~flat while real tokens/s roughly
# doubles at Wikipedia-like length spreads (Krell 2021, arXiv:2107.02027).
# BENCH_SERVE=1 switches to the ONLINE-INFERENCE leg (docs/serving.md):
# instead of the training step, the child replays a synthetic request
# trace (tools/make_synthetic_data.py --requests shape) through the
# serve/ engine — AOT bucket warmup, dynamic batching, optional packing
# (BENCH_SERVE_PACK=1) — and stamps latency p50/p95/p99 (ms), requests/s,
# batch occupancy, and the trace-derived latency decomposition
# (queue_wait_share + per-phase p95s, serve/tracing.py — so the perf
# trajectory records WHERE serve time goes) into the result JSON. Knobs:
# BENCH_SERVE_REQUESTS (default 256), BENCH_SERVE_BATCH (default 8),
# BENCH_SERVE_BUCKETS (default "32,64,128"), BENCH_SERVE_RATE (req/s
# arrival rate; 0 = saturation replay, the default),
# BENCH_SERVE_TRACE_RATE (serve_trace head-sampling fraction, default
# 0.1), BENCH_SERVE_SLO_MS (p99 SLO target; 0 = disabled, the default
# — over-SLO requests are always traced), BENCH_SERVE_SLO_BUDGET
# (error-budget fraction for the report's burn verdict, default 0.01). BENCH_SERVE_QUANT=1 runs the
# INFERENCE-FAST-PATH comparison instead: fp32 vs quantized
# (BENCH_SERVE_QUANT_MODE, default int8) on the SAME trace, stamping
# per-leg p50/p95 + cold_start_s + weight bytes, the p50 speedup, and
# the warm-restart proof (a fresh engine against the persisted AOT
# compile cache must report zero cold compiles via the cache counter
# events — docs/serving.md "Inference fast path").
# BENCH_ASYNC=1 switches to the ASYNC-CHECKPOINT leg (docs/telemetry.md
# "checkpoint-step p95"): a deliberately large synthetic train state is
# saved on a fixed cadence during a paced step loop, once with blocking
# writes and once with async device-snapshot writes
# (utils/checkpoint.py save_checkpoint(async_write=True)), and the result
# stamps both checkpoint-step p95s against the steady-state step p95 —
# async should collapse the ratio toward 1x while blocking holds it at a
# multiple. Knobs: BENCH_ASYNC_STATE_MB (default 128), BENCH_ASYNC_STEPS
# (default 30), BENCH_ASYNC_STEP_MS (default 50), BENCH_ASYNC_CKPT_EVERY
# (default 5).
# Defaults keep two invariants on a throttled CPU box: the inter-save
# interval (step_ms * ckpt_every) exceeds the background write time (else
# saves legitimately join their predecessor — the designed backpressure),
# and the step time dwarfs the snapshot memcpy (on CPU the "device copy"
# is a real memcpy; on TPU it is a sub-ms D2D dispatch).
ASYNC = os.environ.get("BENCH_ASYNC", "0") == "1"
ASYNC_STATE_MB = int(os.environ.get("BENCH_ASYNC_STATE_MB", "96"))
ASYNC_STEPS = int(os.environ.get("BENCH_ASYNC_STEPS", "24"))
ASYNC_STEP_MS = float(os.environ.get("BENCH_ASYNC_STEP_MS", "400"))
ASYNC_CKPT_EVERY = int(os.environ.get("BENCH_ASYNC_CKPT_EVERY", "6"))
SERVE = os.environ.get("BENCH_SERVE", "0") == "1"
SERVE_PACK = os.environ.get("BENCH_SERVE_PACK", "0") == "1"
SERVE_REQUESTS = int(os.environ.get("BENCH_SERVE_REQUESTS", "256"))
SERVE_BATCH = int(os.environ.get("BENCH_SERVE_BATCH", "8"))
SERVE_BUCKETS = os.environ.get("BENCH_SERVE_BUCKETS", "32,64,128")
SERVE_RATE = float(os.environ.get("BENCH_SERVE_RATE", "0"))
# The serving dispatch plane the in-process serve legs drive
# (docs/serving.md "Continuous batching"): pipelined (default) or serial
# — the same A/B knob run_server.py exposes as --dispatch_mode.
SERVE_DISPATCH = os.environ.get("BENCH_SERVE_DISPATCH", "pipelined")
# BENCH_SERVE_SATURATION=1 runs the ROADMAP saturation curve instead
# (docs/serving.md "Continuous batching"): a closed-loop req/s vs p99
# sweep through the REAL fleet — supervisor-owned run_server.py replica
# subprocesses behind the router — at 1 and 2 replicas, pipelined vs
# serial dispatch legs replaying the same trace, every
# (replicas, mode, workers) point stamped into the result JSON. Knobs:
# BENCH_SERVE_SAT_REPLICAS ("1,2"), BENCH_SERVE_SAT_MODES
# ("pipelined,serial"), BENCH_SERVE_SAT_WORKERS ("2,6" — closed-loop
# client concurrency sweep), BENCH_SERVE_SAT_REQUESTS (per point,
# default 48), BENCH_SERVE_SAT_WARMUP_S (replica warmup budget, 240).
SERVE_SATURATION = os.environ.get("BENCH_SERVE_SATURATION", "0") == "1"
# BENCH_KERNELS=1 runs the RAW-SPEED KERNEL comparison (docs/serving.md
# "Raw-speed kernels"): the SAME synthetic trace replays through four
# engines — baseline (xla, unfused) -> fused epilogues -> int8 attention
# (pallas_infer_int8) -> measured-autotune int8 attention — stamping
# per-leg latency p50/p95, the fill_mask forward's output/accessed bytes
# from the joined compile_cost records (the epilogue-fusion win that is
# provable on CPU), weight bytes, and the warm-restart proof with the
# autotune winners file present: a fresh engine against the persisted
# AOT cache + winners JSON must report zero cold compiles. On this CPU
# box the Pallas legs run interpret-mode (their latency ranks kernel
# emulation, not the MXU) — bytes and zero-cold are the CPU-provable
# invariants; latency rides the on-chip capture harness. Knobs:
# BENCH_KERNELS_REQUESTS (default 32), BENCH_KERNELS_BATCH (default 4),
# BENCH_KERNELS_BUCKETS (default "32"), BENCH_KERNELS_VOCAB (model vocab,
# default 8192 — the tokenizer keeps the small covering trace vocab).
KERNELS = os.environ.get("BENCH_KERNELS", "0") == "1"
KERNELS_REQUESTS = int(os.environ.get("BENCH_KERNELS_REQUESTS", "32"))
KERNELS_BATCH = int(os.environ.get("BENCH_KERNELS_BATCH", "4"))
KERNELS_BUCKETS = os.environ.get("BENCH_KERNELS_BUCKETS", "32")
KERNELS_VOCAB = int(os.environ.get("BENCH_KERNELS_VOCAB", "8192"))
# BENCH_MESH=1 runs the STRATEGY-PRODUCT sweep (docs/parallelism.md): the
# SAME tiny model steps under several composed mesh specs on a forced-host
# 8-device CPU mesh (the one-mesh MeshSpec path end to end — spec parse,
# derived rules, composed collectives), stamping per-product step-time
# p50, seq/s/chip, and MFU. Products are only comparable WITHIN a spec,
# so each appends its own perf-ledger entry under a distinct config
# digest (CONFIG_DIGEST + the product's canonical spec). A product whose
# engine cannot run on this jax (gpipe needs the jax>=0.5 shard_map
# typing on CPU) is recorded as skipped with the reason, not a failure.
# Knobs: BENCH_MESH_SPECS (';'-separated spec strings), BENCH_MESH_STEPS
# (default 8), BENCH_MESH_WARMUP (default 2).
MESH_SWEEP = os.environ.get("BENCH_MESH", "0") == "1"
MESH_SPECS = os.environ.get(
    "BENCH_MESH_SPECS", "dp=8;dp=4,fsdp=2;dp=2,fsdp=4;dp=4,pipe=2")
MESH_STEPS = int(os.environ.get("BENCH_MESH_STEPS", "8"))
MESH_WARMUP = int(os.environ.get("BENCH_MESH_WARMUP", "2"))
PACK = (os.environ.get("BENCH_PACK", "0") == "1"
        or "--pack_sequences" in sys.argv[1:])
PACK_K = int(os.environ.get("BENCH_PACK_K", "8"))
KFAC = os.environ.get("BENCH_KFAC", "0") == "1"
KFAC_CAPTURE = os.environ.get("BENCH_KFAC_CAPTURE", "train")
if KFAC_CAPTURE not in ("train", "stats"):
    raise ValueError(
        f"BENCH_KFAC_CAPTURE must be train|stats, got {KFAC_CAPTURE!r}")
PHASE = int(os.environ.get("BENCH_PHASE", "1"))
_P2 = PHASE == 2
# Degraded fallback (see module docstring): BERT-base geometry at the
# phase-1 shape. Only meaningful for the driver's default invocation.
DEGRADED = os.environ.get("BENCH_DEGRADED", "0") == "1"
REPO_ROOT = os.path.dirname(os.path.abspath(__file__))


def _load_retry_module():
    """The shared backoff policy (bert_pytorch_tpu/utils/retry.py), loaded
    by FILE PATH: the parent process must stay jax-free (module
    docstring), and importing through the package ``__init__`` chain would
    drag jax in. The module is stdlib-only by contract."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_bench_retry",
        os.path.join(REPO_ROOT, "bert_pytorch_tpu", "utils", "retry.py"))
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


_retry = _load_retry_module()
CACHE_DIR = os.environ.get("BENCH_COMPILE_CACHE_DIR",
                           os.path.join(REPO_ROOT, ".jax_cache"))
# Optional telemetry sink (docs/telemetry.md): the child appends its
# compile events (fn/shapes digest/compile seconds/cache hit-miss), a
# run_summary (seq/s + MFU), and — on backends with allocator stats — a
# device-memory watermark record as schema-versioned JSONL, so capture
# passes record cold-vs-warm AND cost/memory evidence. When a baseline
# artifact exists (BENCH_TELEMETRY_BASELINE, default the committed
# repo-root BENCH_TELEMETRY.jsonl), the parent additionally runs
# tools/telemetry_report.py over the pair and attaches its regression
# verdict to the result JSON — the bench trajectory becomes
# machine-checkable instead of eyeballed.
TELEMETRY_JSONL = os.environ.get("BENCH_TELEMETRY_JSONL", "")
TELEMETRY_BASELINE = os.environ.get(
    "BENCH_TELEMETRY_BASELINE",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "BENCH_TELEMETRY.jsonl"))
# Longitudinal perf ledger (telemetry/ledger.py, docs/telemetry.md "Perf
# ledger"): every successful capture appends one schema-linted
# ledger_entry (headline metrics + this config's digest), so the bench
# trajectory accumulates — the rolling-median drift gate
# (telemetry-report --ledger / tools/perf_ledger.py check) catches the
# slow regressions a single hand-picked baseline walks past.
# BENCH_LEDGER=0 disables; any other value overrides the path.
LEDGER_PATH = os.environ.get(
    "BENCH_LEDGER", os.path.join(REPO_ROOT, "PERF_LEDGER.jsonl"))
if LEDGER_PATH == "0":
    LEDGER_PATH = ""


def _config_digest(degraded=None, local_batch=None):
    """Stable digest of every knob that changes the compiled program (and
    therefore the compile-cache entry this config needs). ``degraded`` /
    ``local_batch`` override the module constants so the parent can name
    the degraded-fallback child's marker without re-deriving the key
    tuple (the two digests must never drift)."""
    import hashlib

    key = repr((PHASE, KFAC,
                DEGRADED if degraded is None else degraded, LONG_SEQ,
                LOCAL_BATCH if local_batch is None else local_batch, REMAT,
                RNG_IMPL, ATTN, N_DEVICES,
                # kernel-tuning env knobs also change the compiled program
                os.environ.get("PALLAS_ATTN_BH_BLOCK", ""),
                # kfac capture mode changes the train-step program; keep
                # the digest stable for non-kfac configs
                KFAC_CAPTURE if KFAC else ""))
    if PACK:
        # Packing changes the compiled step (extra arrays, packed heads).
        # Appended OUTSIDE the tuple so non-packed digests stay
        # byte-identical to the committed warm markers of earlier rounds.
        key += f"+pack{PACK_K}"
    if SERVE:
        # The serve leg compiles inference forwards, not the train step;
        # appended outside the tuple for the same marker-stability reason.
        key += (f"+serve{SERVE_BATCH}x{SERVE_BUCKETS}"
                + ("+spack" if SERVE_PACK else ""))
    if SERVE_SATURATION:
        # The saturation leg compiles inside its replica subprocesses
        # (their own shared cache); keyed so its marker never collides.
        key += "+servesat"
    if KERNELS:
        # The kernels leg compiles serve forwards (four engine variants),
        # not the train step; keyed so its warm marker never tells the
        # training bench parent a cold train-step cache is warm.
        key += f"+kernels{KERNELS_BATCH}x{KERNELS_BUCKETS}v{KERNELS_VOCAB}"
    if ASYNC:
        # The async-checkpoint leg compiles nothing heavy (the snapshot
        # identity only); keyed so its marker never collides with a
        # training config's.
        key += f"+async{ASYNC_STATE_MB}"
    if MESH_SWEEP:
        # The mesh sweep compiles one tiny train step per product on a
        # forced-host mesh; keyed on the product list so its marker and
        # ledger digests never collide with a real training config's.
        key += f"+mesh{MESH_SPECS}"
    return hashlib.sha1(key.encode()).hexdigest()[:12]


def _warm_marker_path():
    return os.path.join(CACHE_DIR, f"warm_{CONFIG_DIGEST}")


def _degraded_digest():
    """Digest the degraded-fallback child would compute: same knobs, but
    DEGRADED=True and the degraded LOCAL_BATCH default (the child
    re-derives LOCAL_BATCH from env, so an explicit BENCH_LOCAL_BATCH
    carries through to it)."""
    return _config_digest(
        degraded=True,
        local_batch=int(os.environ.get("BENCH_LOCAL_BATCH", "64")))


def _cache_is_warm():
    """True if a completed bench run of THIS config has populated the
    cache (the child drops a per-config marker after measuring — JAX's
    entries are content-keyed, so the directory being non-empty proves
    nothing about the shapes this config compiles). The distinction
    drives the retry strategy: one long attempt cold (a killed compile
    caches nothing) vs several short ones warm."""
    return os.path.exists(_warm_marker_path())
# BENCH_SEQ overrides the sequence length for long-context runs (the
# reference hard-caps at max_position_embeddings=512; this framework's
# fused attention is O(S) memory, and 'sp' ring attention shards S across
# chips). vs_baseline then uses a FLOP-proportional courtesy scaling of the
# phase-2 anchor (72 * 512/S) — the reference cannot run the shape at all.
LONG_SEQ = int(os.environ.get("BENCH_SEQ", "0"))
LOCAL_BATCH = int(os.environ.get(
    "BENCH_LOCAL_BATCH",
    "64" if DEGRADED
    else str(max(1, 28 * 512 // LONG_SEQ)) if LONG_SEQ
    else ("28" if _P2 else "56")))
REMAT = os.environ.get("BENCH_REMAT", "dots")
RNG_IMPL = os.environ.get("BENCH_RNG_IMPL", "rbg")
ATTN = os.environ.get("BENCH_ATTN", "pallas" if (_P2 or LONG_SEQ) else "xla")
if PHASE not in (1, 2):
    raise ValueError(f"BENCH_PHASE must be 1|2, got {PHASE}")
if REMAT not in ("none", "dots", "full"):
    raise ValueError(f"BENCH_REMAT must be none|dots|full, got {REMAT!r}")
if ATTN not in ("xla", "pallas", "ring"):
    raise ValueError(f"BENCH_ATTN must be xla|pallas|ring, got {ATTN!r}")
if RNG_IMPL not in ("rbg", "threefry2x32"):
    raise ValueError(f"BENCH_RNG_IMPL must be rbg|threefry2x32, got {RNG_IMPL!r}")
if PACK and ATTN == "ring":
    raise ValueError(
        "BENCH_PACK does not compose with BENCH_ATTN=ring (the block-"
        "diagonal mask is not implemented over the sharded seq axis)")
if LONG_SEQ and (LONG_SEQ < 128 or LONG_SEQ % 128 != 0):
    raise ValueError(
        f"BENCH_SEQ must be a positive multiple of 128 (tile alignment for "
        f"the fused attention kernel), got {LONG_SEQ}")
SEQ_LEN = LONG_SEQ or (512 if _P2 else 128)
MAX_PRED = (max(20, SEQ_LEN * 80 // 512) if LONG_SEQ
            else (80 if _P2 else 20))  # max_predictions_per_seq (BASELINE.md)
ACCUM = 1
WARMUP_STEPS = int(os.environ.get("BENCH_WARMUP_STEPS", "3"))
MEASURE_STEPS = int(os.environ.get("BENCH_MEASURE_STEPS", "20"))
# BENCH_DEVICES=N restricts the mesh to the first N devices of a
# SINGLE-PROCESS run (an intra-host sweep; multi-host pods sweep by
# launching with fewer hosts), giving the BASELINE.md scaling-efficiency
# curve (seq/s/chip at N vs at the base size). 0 = all devices.
N_DEVICES = int(os.environ.get("BENCH_DEVICES", "0"))
CONFIG_DIGEST = _config_digest()  # all digest inputs are defined above


def _child_main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_default_prng_impl", RNG_IMPL)
    # Persistent compilation cache: a retry (or a later capture pass) after
    # a drop that happens once compilation has COMPLETED reuses the cached
    # executable instead of recompiling. An interrupted compile writes no
    # entry — long-seq legs additionally scale the parent's attempt timeout
    # so the first compile gets to finish at all.
    from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(CACHE_DIR)
    from bert_pytorch_tpu import optim, pretrain
    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.parallel import MeshConfig, create_mesh, logical_axis_rules

    config = BertConfig.from_json_file(
        os.path.join(REPO_ROOT, "configs",
                     "bert_base_config.json" if DEGRADED
                     else "bert_large_uncased_config.json"))
    if config.vocab_size % 8 != 0:
        config.vocab_size += 8 - (config.vocab_size % 8)
    if LONG_SEQ:
        config.max_position_embeddings = SEQ_LEN

    devices = jax.devices()
    if N_DEVICES:
        # Config errors print a marker and exit 2 so the parent stops
        # retrying immediately (they are deterministic, unlike backend
        # failures).
        if N_DEVICES < 0 or N_DEVICES > len(devices):
            print(f"BENCH_CONFIG_ERROR: BENCH_DEVICES={N_DEVICES} outside "
                  f"[1, {len(devices)}]")
            sys.exit(2)
        if jax.process_count() > 1:
            # Slicing the global device list would hand some processes a
            # mesh with none of their addressable chips; pod scaling
            # sweeps should vary the JOB size (hosts) instead.
            print("BENCH_CONFIG_ERROR: BENCH_DEVICES only supports "
                  "single-process runs; on a multi-host pod, sweep by "
                  "launching with fewer hosts")
            sys.exit(2)
        devices = devices[:N_DEVICES]
    n_chips = len(devices)
    if ATTN == "ring":
        # Context parallelism: the sequence axis shards across the chips
        # and K/V blocks rotate over ICI (ops/ring.py). Single-chip runs
        # can't exercise the rotation — require a real seq axis.
        if n_chips < 2:
            raise ValueError(
                "BENCH_ATTN=ring needs >=2 chips (the sequence axis shards "
                "across the mesh); on one chip use the fused 'pallas' kernel")
        mesh = create_mesh(MeshConfig(data=1, seq=n_chips), devices=devices)
        rules = logical_axis_rules("sp")
    else:
        mesh = create_mesh(MeshConfig(data=-1), devices=devices)
        rules = logical_axis_rules("dp")
    model = BertForPreTraining(config, dtype=jnp.bfloat16, remat=REMAT,
                               attention_backend=ATTN)
    schedule = (optim.warmup_poly_schedule(4e-3, 0.128, 1563) if _P2
                else optim.warmup_poly_schedule(6e-3, 0.2843, 7038))
    tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)

    # Batch scales with the DATA shards only (under 'ring' the chips hold
    # sequence shards, not batch shards).
    data_shards = mesh.shape["data"] * mesh.shape["fsdp"]
    global_batch = LOCAL_BATCH * data_shards * ACCUM
    sample = (jnp.zeros((1, SEQ_LEN), jnp.int32),) * 3
    rng = np.random.default_rng(0)
    eff_max_pred = MAX_PRED * PACK_K if PACK else MAX_PRED
    if PACK:
        # Mixed-length synthetic samples FFD-packed into exactly
        # global_batch full rows (the runner's on-the-fly path,
        # data/packing.py) — what a Wikipedia-style shard looks like to
        # the train step after packing.
        from bert_pytorch_tpu.data.packing import first_fit_decreasing

        lengths: list = []
        while True:
            lengths.extend(
                int(x) for x in rng.integers(8, SEQ_LEN + 1, 512))
            packs = first_fit_decreasing(lengths, SEQ_LEN, PACK_K)
            if len(packs) >= global_batch:
                break
        packs = packs[:global_batch]
        host = {
            "input_ids": np.zeros((global_batch, SEQ_LEN), np.int32),
            "segment_ids": np.zeros((global_batch, SEQ_LEN), np.int32),
            "input_mask": np.zeros((global_batch, SEQ_LEN), np.int32),
            "masked_lm_labels": np.full(
                (global_batch, SEQ_LEN), -1, np.int32),
            "next_sentence_labels": np.full(
                (global_batch, PACK_K), -1, np.int32),
            "sequence_ids": np.zeros((global_batch, SEQ_LEN), np.int32),
            "cls_positions": np.zeros((global_batch, PACK_K), np.int32),
        }
        for r, pack in enumerate(packs):
            offset = 0
            for k, i in enumerate(pack):
                n = min(lengths[i], SEQ_LEN - offset)
                span = slice(offset, offset + n)
                host["input_ids"][r, span] = rng.integers(
                    0, config.vocab_size, n)
                host["segment_ids"][r, span] = rng.integers(0, 2, n)
                host["input_mask"][r, span] = 1
                host["masked_lm_labels"][r, span] = np.where(
                    rng.random(n) < 0.15,
                    rng.integers(0, config.vocab_size, n), -1)
                host["sequence_ids"][r, span] = k + 1
                host["next_sentence_labels"][r, k] = int(rng.integers(0, 2))
                host["cls_positions"][r, k] = offset
                offset += n
        pack_efficiency = float(host["input_mask"].sum()) / (
            global_batch * SEQ_LEN)
    else:
        host = {
            "input_ids": rng.integers(
                0, config.vocab_size, (global_batch, SEQ_LEN)).astype(np.int32),
            "segment_ids": rng.integers(0, 2, (global_batch, SEQ_LEN)).astype(np.int32),
            "input_mask": np.ones((global_batch, SEQ_LEN), np.int32),
            "masked_lm_labels": np.where(
                rng.random((global_batch, SEQ_LEN)) < 0.15,
                rng.integers(0, config.vocab_size, (global_batch, SEQ_LEN)),
                -1).astype(np.int32),
            "next_sentence_labels": rng.integers(0, 2, (global_batch,)).astype(np.int32),
        }
        pack_efficiency = None

    batch_spec = {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
                  "masked_lm_labels": 3,
                  "next_sentence_labels": 3 if PACK else 2}
    if PACK:
        batch_spec.update({"sequence_ids": 3, "cls_positions": 3})
    with mesh:
        shardings = pretrain.state_shardings(mesh, model, rules, sample)
        b_shardings = pretrain.batch_shardings(
            mesh, batch_spec, seq_sharded=ATTN == "ring")
        state = pretrain.make_init_fn(model, tx, sample, shardings)(
            jax.random.PRNGKey(0))

        kfac_obj = kfac_state = kfac_shardings = None
        kfac_fused = KFAC and KFAC_CAPTURE == "train"
        if KFAC:
            # The fused-capture twin keeps the bench remat (its microbatch-0
            # backward shares the training step's memory budget); the
            # stats-pass twin runs a small decoupled batch.
            tapped = BertForPreTraining(
                config, dtype=jnp.bfloat16,
                remat=REMAT if kfac_fused else "none",
                attention_backend=ATTN, kfac_tap=True)
            apply_loss, tap_shape_fn = pretrain.make_kfac_fns(
                tapped, next_sentence=True, max_pred_per_seq=eff_max_pred)
            kfac_obj = optim.KFAC(apply_loss, tap_shape_fn)
            _st = max(1, global_batch // 16)
            stats_mb = {k: v[::_st][:16] for k, v in host.items()}
            kfac_state = kfac_obj.init(state.params, stats_mb)
            kfac_shardings = optim.kfac_state_shardings(mesh, kfac_state)
            kfac_state = jax.device_put(kfac_state, kfac_shardings)

        step = pretrain.make_train_step(
            model, tx, schedule=schedule, next_sentence=True,
            shardings=shardings, batch_shardings_=b_shardings,
            max_pred_per_seq=eff_max_pred,
            kfac=kfac_obj, kfac_shardings=kfac_shardings,
            kfac_capture_model=tapped if kfac_fused else None,
            kfac_factor_interval=10,
            kfac_inv_interval=100 if kfac_fused else 0)

        # Compile observability (telemetry/compile_events.py): the warmup
        # compile is attributed to the bench step, so the result can state
        # whether this run was cold (real XLA compile) or warm (persistent
        # cache hit) — the ambiguity that zeroed BENCH_r01-r03.
        from bert_pytorch_tpu.telemetry import CompileMonitor
        sink = None
        if TELEMETRY_JSONL:
            from bert_pytorch_tpu.utils.logging import JSONLHandler
            sink = JSONLHandler(TELEMETRY_JSONL, overwrite=False)
        # Static cost attribution only when there is a sink to keep it:
        # 'auto' never pays an un-cached extra backend compile
        # (telemetry/memory.py), and the bench always enables the
        # persistent cache, so memory_analysis costs a deserialize. An
        # unknown env value degrades to 'off' — a typo must not kill a
        # bench attempt after the compile already ran.
        from bert_pytorch_tpu.telemetry.memory import COST_MODES
        cost_mode = os.environ.get(
            "BENCH_COST_ANALYSIS", "auto" if sink else "off")
        if cost_mode not in COST_MODES:
            print(f"BENCH_COST_ANALYSIS={cost_mode!r} unknown; "
                  "disabling cost attribution", file=sys.stderr)
            cost_mode = "off"
        monitor = CompileMonitor(
            emit=sink.write_record if sink else lambda rec: None,
            cost_analysis=cost_mode)
        step = monitor.instrument(step, "bench_step")

        batch = pretrain.put_batch(
            pretrain.stack_microbatches(host, ACCUM), b_shardings)

        def run_one(state, kfac_state, global_step):
            if kfac_fused:
                # Factor capture rides microbatch 0's backward; both the
                # factor and inverse cadences are cond-gated in-jit.
                state, metrics, kfac_state = step(state, batch, kfac_state)
            elif kfac_obj is not None:
                if global_step % 10 == 0:
                    # Strided rows so every data shard contributes to the
                    # statistics (the runner's pattern; a [:16] head-slice
                    # would sample only shard 0's data on multi-chip runs).
                    stride = max(1, batch["input_ids"].shape[1] // 16)
                    kfac_state = kfac_obj.update_factors(
                        kfac_state, state.params,
                        {k: v[0][::stride][:16] for k, v in batch.items()},
                        jax.random.fold_in(jax.random.PRNGKey(17), global_step))
                if global_step % 100 == 0:
                    kfac_state = kfac_obj.update_inverses(kfac_state)
                state, metrics = step(state, batch, kfac_state)
            else:
                state, metrics = step(state, batch)
            return state, kfac_state, metrics

        for i in range(WARMUP_STEPS):
            state, kfac_state, metrics = run_one(state, kfac_state, i + 100)
            _ = float(metrics["loss"])

        # Chained dispatch: each step consumes the previous step's donated
        # state, so fetching only the FINAL loss forces the whole chain to
        # have executed (a value dependent on every step can't be returned
        # early — unlike block_until_ready, which has been observed to
        # return early through the axon remote-execution tunnel). Per-step
        # value fetches would serialize a host<->device round-trip into
        # every step and understate steady-state throughput by ~35%.
        start = time.perf_counter()
        for i in range(MEASURE_STEPS):
            state, kfac_state, metrics = run_one(state, kfac_state, i)
        _ = float(metrics["loss"])
        elapsed = time.perf_counter() - start

    seq_per_sec = MEASURE_STEPS * global_batch / elapsed
    seq_per_sec_chip = seq_per_sec / n_chips
    from bert_pytorch_tpu.utils import flops as flops_util
    flops_per_seq = flops_util.bert_train_flops_per_seq(
        config, SEQ_LEN, eff_max_pred, next_sentence=True)
    model_flops_util = flops_util.mfu(
        seq_per_sec_chip, flops_per_seq, devices[0].device_kind)
    # Compile + measurement done => the cache holds this config's entries;
    # drop the per-config marker the parent's warm/cold strategy reads.
    try:
        with open(_warm_marker_path(), "w") as f:
            f.write("ok\n")
    except OSError:
        pass
    anchor = None
    if DEGRADED:
        # The A100 anchor is a BERT-large number; scale it by the exact
        # train-FLOP ratio so vs_baseline still compares like with like.
        large = BertConfig.from_json_file(os.path.join(
            REPO_ROOT, "configs", "bert_large_uncased_config.json"))
        if large.vocab_size % 8 != 0:
            large.vocab_size += 8 - (large.vocab_size % 8)
        anchor = A100_PHASE1_SEQ_PER_SEC * flops_util.bert_train_flops_per_seq(
            large, SEQ_LEN, MAX_PRED, next_sentence=True) / flops_per_seq
    result = _result_json(
        seq_per_sec_chip, mfu=model_flops_util, n_chips=n_chips,
        anchor_override=anchor)
    if PACK:
        # Padding-aware accounting (docs/telemetry.md): rows/s barely
        # moves under packing; real tokens/s is the number that ~doubles.
        result["padding_efficiency"] = round(pack_efficiency, 4)
        result["real_tokens_per_sec_chip"] = round(
            seq_per_sec_chip * SEQ_LEN * pack_efficiency, 2)
    compile_events = [e for e in monitor.events if e["kind"] == "compile"]
    if compile_events:
        result["compile"] = {
            "events": len(compile_events),
            "cache": compile_events[0]["cache"],
            "compile_s": round(
                sum(e["compile_s"] for e in compile_events), 2),
        }
    if sink is not None:
        # Summary + memory watermark records so the offline regression
        # gate (tools/telemetry_report.py) can diff seq/s, MFU, and peak
        # device memory between this artifact and a committed baseline.
        from bert_pytorch_tpu.telemetry.memory import MemorySampler

        sampler = MemorySampler(emit=sink.write_record)
        sampler.sample(MEASURE_STEPS)
        sampler.flush(MEASURE_STEPS)
        sink.write_record({
            "kind": "run_summary", "tag": "telemetry",
            "step": MEASURE_STEPS, "steps": MEASURE_STEPS,
            "metric": result["metric"],
            "training_seq_per_sec": round(seq_per_sec, 2),
            "seq_per_sec_chip": round(seq_per_sec_chip, 2),
            "mfu": round(model_flops_util, 4),
        })
        sink.close()
    print(json.dumps(result))


def _serve_child_main():
    """BENCH_SERVE leg: replay a synthetic request trace through the
    online-inference engine (docs/serving.md) and print one JSON line with
    latency percentiles, request throughput, and batch occupancy.

    BENCH_SERVE_QUANT=1 switches to the INFERENCE-FAST-PATH comparison
    (docs/serving.md "Inference fast path"): the SAME trace replays twice
    — an fp32 engine, then a quantized one (BENCH_SERVE_QUANT_MODE,
    default int8) — and the result stamps per-leg p50/p95 + cold_start_s
    + weight bytes, the p50 ratio, and the warm-restart proof: a THIRD
    engine start against the now-populated persistent compile cache must
    perform ZERO cold compiles, measured by the cache counter events
    (telemetry/compile_events.py — wall clock proves nothing). On this
    CPU CI box XLA has no fast s8 GEMM, so int8 p50 typically LOSES here;
    the latency win is an MXU property stamped by on-chip captures, while
    the weight-bytes ratio and the zero-cold-restart hold anywhere.
    """
    import json as _json
    import tempfile
    import threading

    from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache

    # min_compile_secs=0: persist the seconds-scale serve executables too
    # (the warm-restart leg depends on every forward being cached).
    enable_compile_cache(CACHE_DIR, min_compile_secs=0.0)
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.data.tokenization import BertTokenizer
    from bert_pytorch_tpu.serve import (Batcher, InferenceEngine,
                                        ServeTelemetry, ServingService)
    from bert_pytorch_tpu.telemetry import CompileMonitor
    from bert_pytorch_tpu.tools.make_synthetic_data import (
        make_request_trace, write_trace_vocab)

    config = BertConfig.from_json_file(
        os.path.join(REPO_ROOT, "configs", "bert_base_config.json"))
    if config.vocab_size % 8 != 0:
        config.vocab_size += 8 - (config.vocab_size % 8)

    tmp = tempfile.mkdtemp(prefix="bench_serve_")
    # Trace text uses the small covering vocab (token ids stay tiny); the
    # MODEL keeps its real 30k vocab, so per-request FLOPs are realistic.
    vocab = write_trace_vocab(os.path.join(tmp, "vocab.txt"))
    trace = make_request_trace(
        os.path.join(tmp, "requests.jsonl"), SERVE_REQUESTS, seed=0,
        rate_rps=SERVE_RATE)
    tokenizer = BertTokenizer(vocab, do_lower_case=True)

    sink = None
    if TELEMETRY_JSONL:
        from bert_pytorch_tpu.utils.logging import JSONLHandler

        sink = JSONLHandler(TELEMETRY_JSONL, overwrite=False)
    emit = sink.write_record if sink else (lambda rec: None)
    buckets = [int(b) for b in SERVE_BUCKETS.split(",")]
    pack_k = int(os.environ.get("BENCH_SERVE_PACK_K", "4"))
    lines = [_json.loads(line) for line in open(trace)]

    def build_service(quantize, monitor):
        import argparse

        from bert_pytorch_tpu.serve.cli import build_tracer

        engine = InferenceEngine(
            config, tokenizer,
            tasks={"fill_mask": {}, "classify": {"labels": ["0", "1"]},
                   "squad": {}, "ner": {"labels": ["O", "B-LOC", "B-PER"]}},
            buckets=buckets, max_batch_size=SERVE_BATCH,
            max_requests_per_pack=pack_k if SERVE_PACK else 1,
            dtype=jnp.bfloat16, monitor=monitor, quantize=quantize)
        telemetry = ServeTelemetry(emit=emit, window=64)
        # Request tracing rides every serve leg so the perf trajectory
        # records WHERE serve time goes (queue vs execute vs postprocess),
        # not just how much (docs/serving.md "Request tracing & metrics").
        tracer = build_tracer(
            argparse.Namespace(
                trace_sample_rate=float(
                    os.environ.get("BENCH_SERVE_TRACE_RATE", "0.1")),
                slo_p99_ms=float(
                    os.environ.get("BENCH_SERVE_SLO_MS", "0")),
                slo_error_budget=float(
                    os.environ.get("BENCH_SERVE_SLO_BUDGET", "0.01"))),
            emit=emit, window=64)
        return ServingService(
            engine,
            Batcher(max_batch_size=SERVE_BATCH, max_wait_ms=5.0,
                    max_requests_per_pack=engine.max_requests_per_pack),
            telemetry, tracer=tracer, dispatch_mode=SERVE_DISPATCH)

    def replay(service):
        t_warm = time.perf_counter()
        service.start()  # warms every (task, bucket[, packed]) forward
        warmup_s = time.perf_counter() - t_warm
        errors: list = []
        t0 = time.perf_counter()

        def worker(chunk):
            for line in chunk:
                if SERVE_RATE > 0:
                    delay = t0 + line["arrival_s"] - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                try:
                    service.submit(line["task"], line["payload"],
                                   timeout=300)
                except Exception as exc:  # stamped, not fatal
                    errors.append(f"{type(exc).__name__}: {exc}")

        n_workers = min(32, max(4, SERVE_BATCH * 4))
        threads = [threading.Thread(target=worker,
                                    args=(lines[i::n_workers],),
                                    daemon=True)
                   for i in range(n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        # include_phases=False: the phase rollup is taken ONCE below,
        # after stop() — computing it here too would sort the tracer's
        # whole sample history while the dispatch thread still runs.
        snap = service.telemetry.snapshot(include_phases=False)
        service.stop()
        # After stop(): run-level phase rollup survives the drain, and
        # the tracer's partial serve_phase windows are flushed by it.
        snap["phases"] = service.tracer.phase_snapshot() or {}
        return snap, wall, warmup_s, errors

    def phase_stamp(snap):
        """Trace-derived latency-decomposition stamp for the result JSON:
        queue-wait share + per-phase p95s (serve/tracing.py)."""
        phases = snap.get("phases") or {}
        return {
            "queue_wait_share": phases.get("queue_wait_share"),
            "phase_p95_ms": {
                name: phases.get(f"{name}_p95_ms")
                for name in ("queue", "assembly", "execute", "postprocess")
            },
        }

    quant_mode = os.environ.get("BENCH_SERVE_QUANT_MODE", "int8")
    if os.environ.get("BENCH_SERVE_QUANT", "0") == "1":
        legs = {}
        for mode in (None, quant_mode):
            tag = mode or "fp32"
            monitor = CompileMonitor(emit=emit)
            service = build_service(mode, monitor)
            snap, wall, _, errors = replay(service)
            startup = service.engine.startup or {}
            legs[tag] = {
                "latency_p50_ms": snap.get("latency_p50_ms"),
                "latency_p95_ms": snap.get("latency_p95_ms"),
                "req_per_sec": round(SERVE_REQUESTS / wall, 2),
                "cold_start_s": startup.get("cold_start_s"),
                "compiles_cold": startup.get("compiles_cold"),
                "compiles_warm": startup.get("compiles_warm"),
                "weight_bytes": startup.get("weight_bytes"),
                "serve_errors": len(errors),
            }
            legs[tag].update(phase_stamp(snap))
        # Warm-restart proof: a fresh engine against the persisted AOT
        # cache — the cache counter events must report zero cold
        # compiles (every forward is a persistent-cache hit).
        monitor = CompileMonitor(emit=emit)
        warm_engine = build_service(quant_mode, monitor).engine
        warm_engine.warmup()
        warm_startup = warm_engine.startup or {}
        fp32_leg, quant_leg = legs["fp32"], legs[quant_mode]
        p50_ratio = None
        if fp32_leg["latency_p50_ms"] and quant_leg["latency_p50_ms"]:
            p50_ratio = round(
                fp32_leg["latency_p50_ms"] / quant_leg["latency_p50_ms"], 3)
        bytes_ratio = None
        if fp32_leg["weight_bytes"] and quant_leg["weight_bytes"]:
            bytes_ratio = round(
                fp32_leg["weight_bytes"] / quant_leg["weight_bytes"], 2)
        result = {
            "metric": f"bert_base_serve_{quant_mode}_p50_ms",
            "value": quant_leg["latency_p50_ms"],
            "unit": "ms",
            "n_requests": SERVE_REQUESTS,
            "quant_mode": quant_mode,
            "fp32": fp32_leg,
            quant_mode: quant_leg,
            # >1 = the quantized leg is faster at the median (expected on
            # TPU; on this CPU box s8 GEMMs lose — documented above).
            "p50_speedup": p50_ratio,
            "weight_bytes_ratio": bytes_ratio,
            "second_start_cold_compiles": warm_startup.get("compiles_cold"),
            "second_start_warm_compiles": warm_startup.get("compiles_warm"),
            "second_start_cold_start_s": warm_startup.get("cold_start_s"),
            "buckets": buckets,
            "batch_size": SERVE_BATCH,
            # ok = the CPU-provable invariants: zero-cold warm restart +
            # the quantized weights actually shrank.
            "ok": bool(warm_startup.get("compiles_cold") == 0
                       and (bytes_ratio or 0) > 1.5),
        }
        if sink is not None:
            sink.write_record({
                "kind": "run_summary", "tag": "telemetry",
                "step": SERVE_REQUESTS, "steps": SERVE_REQUESTS,
                "metric": result["metric"]})
            sink.close()
        try:
            with open(_warm_marker_path(), "w") as f:
                f.write("ok\n")
        except OSError:
            pass
        print(_json.dumps(result))
        return

    monitor = CompileMonitor(emit=emit)
    service = build_service(None, monitor)
    telemetry = service.telemetry
    engine = service.engine
    snap, wall, warmup_s, errors = replay(service)

    metric = "bert_base_serve{}_req_per_sec".format(
        "_packed" if SERVE_PACK else "")
    result = {
        "metric": metric,
        "value": round(SERVE_REQUESTS / wall, 2),
        "unit": "req/s",
        "n_requests": SERVE_REQUESTS,
        "latency_p50_ms": snap.get("latency_p50_ms"),
        "latency_p95_ms": snap.get("latency_p95_ms"),
        "latency_p99_ms": snap.get("latency_p99_ms"),
        "device_p50_ms": snap.get("device_p50_ms"),
        "batch_occupancy": snap.get("batch_occupancy"),
        **phase_stamp(snap),
        "warmup_s": round(warmup_s, 2),
        "cold_start_s": (engine.startup or {}).get("cold_start_s"),
        "serve_errors": len(errors),
        "buckets": buckets,
        "batch_size": SERVE_BATCH,
        "pack": pack_k if SERVE_PACK else 1,
    }
    if SERVE_RATE > 0:
        result["arrival_rate_rps"] = SERVE_RATE
    if errors:
        result["error_sample"] = errors[0][:200]
    compile_events = [e for e in monitor.events if e["kind"] == "compile"]
    if compile_events:
        result["compile"] = {
            "events": len(compile_events),
            "cache": compile_events[0]["cache"],
            "compile_s": round(
                sum(e["compile_s"] for e in compile_events), 2),
        }
    try:
        with open(_warm_marker_path(), "w") as f:
            f.write("ok\n")
    except OSError:
        pass
    if sink is not None:
        # The metric stamp lets the regression gate refuse diffing a serve
        # artifact against a training baseline (_attach_regression).
        sink.write_record({
            "kind": "run_summary", "tag": "telemetry",
            "step": SERVE_REQUESTS, "steps": SERVE_REQUESTS,
            "metric": metric})
        sink.close()
    print(_json.dumps(result))


def _kernels_child_main():
    """BENCH_KERNELS leg: baseline vs fused-epilogue vs int8-attention
    vs measured-autotune engines on one trace (docs/serving.md
    "Raw-speed kernels").

    Four engines replay the same synthetic request trace through the
    direct plan/stage/execute/demux/postprocess path (no HTTP/batcher —
    the kernels are the thing under test, not the dispatch plane), each
    with cost attribution on, so every leg stamps: latency p50/p95 per
    dispatched batch, the fill_mask forward's output/accessed bytes
    from its compile_cost record (fused engines must move fewer bytes
    off the device), and cold-start/weight stats. The autotuned leg
    measures geometry at warmup and persists the winners JSON next to
    the AOT compile cache; a FIFTH engine start then proves the warm
    restart: winners loaded + every forward a persistent-cache hit —
    ``second_start_cold_compiles == 0`` with autotune winners present.
    """
    import json as _json
    import tempfile

    from bert_pytorch_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(CACHE_DIR, min_compile_secs=0.0)
    import jax.numpy as jnp

    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.data.tokenization import BertTokenizer
    from bert_pytorch_tpu.serve import InferenceEngine
    from bert_pytorch_tpu.serve.batcher import Request
    from bert_pytorch_tpu.telemetry import CompileMonitor
    from bert_pytorch_tpu.tools.make_synthetic_data import (
        make_request_trace, write_trace_vocab)

    tmp = tempfile.mkdtemp(prefix="bench_kernels_")
    vocab_path = write_trace_vocab(os.path.join(tmp, "vocab.txt"))
    trace = make_request_trace(os.path.join(tmp, "requests.jsonl"),
                               KERNELS_REQUESTS, seed=0)
    tokenizer = BertTokenizer(vocab_path, do_lower_case=True)
    lines = [_json.loads(line) for line in open(trace)]
    buckets = [int(b) for b in KERNELS_BUCKETS.split(",")]
    # Small-but-real model: the trace tokenizer's tiny covering vocab
    # keeps token ids valid while the MODEL vocab stays large enough
    # that the fill_mask [B, S, V] plane is the dominant output (the
    # bytes the fused epilogue exists to not move).
    config = BertConfig(
        vocab_size=KERNELS_VOCAB, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=max(buckets), type_vocab_size=2,
        next_sentence=True, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    tasks = {"fill_mask": {}, "classify": {"labels": ["0", "1"]},
             "squad": {}, "ner": {"labels": ["O", "B-LOC", "B-PER"]}}
    winners_path = os.path.join(CACHE_DIR, "pallas_autotune.json")

    sink = None
    if TELEMETRY_JSONL:
        from bert_pytorch_tpu.utils.logging import JSONLHandler

        sink = JSONLHandler(TELEMETRY_JSONL, overwrite=False)
    emit = sink.write_record if sink else (lambda rec: None)

    def build(**kw):
        monitor = CompileMonitor(emit=emit, cost_analysis="auto")
        engine = InferenceEngine(
            config, tokenizer, tasks, buckets=buckets,
            max_batch_size=KERNELS_BATCH, dtype=jnp.float32,
            monitor=monitor, **kw)
        engine.warmup()
        return engine

    def fill_mask_cost(engine):
        """output/accessed bytes of the fill_mask forward the engine
        actually dispatches, joined from the compile_cost records the
        monitor attributed at warmup. Fused engines also warm the
        unfused slot-overflow fallback — the comparison wants the
        steady-state (fused) variant, not the sum of both."""
        costs = {e["fn"]: e for e in engine.monitor.events
                 if e.get("kind") == "compile_cost"
                 and e.get("fn", "").startswith("serve_fill_mask_")}
        fused = {fn: e for fn, e in costs.items() if "_fused" in fn}
        chosen = fused or costs
        out_bytes = sum(int(e.get("output_bytes", 0))
                        for e in chosen.values())
        accessed = sum(int(e.get("bytes_accessed", 0))
                       for e in chosen.values())
        return out_bytes, accessed

    def replay(engine):
        lats = []
        by_task = {}
        for line in lines:
            by_task.setdefault(line["task"], []).append(line["payload"])
        for task, payloads in by_task.items():
            spec = engine.tasks[task]
            todo = [Request(task,
                            spec.handler.prepare(p, engine.max_len()), p)
                    for p in payloads]
            while todo:
                t0 = time.perf_counter()
                plan = engine.plan_batch(todo[:KERNELS_BATCH],
                                         packed=False)
                outputs, info = engine.execute(task, plan)
                for req, out in zip(plan.requests, outputs):
                    spec.handler.postprocess(req.features, out,
                                             req.payload)
                wall = time.perf_counter() - t0
                lats.extend([wall * 1000.0] * len(plan.requests))
                todo = todo[KERNELS_BATCH:] + list(plan.leftover)
        lats.sort()

        def pctl(q):
            return round(lats[min(len(lats) - 1,
                                  int(q * len(lats)))], 2) if lats else None

        return {"latency_p50_ms": pctl(0.50), "latency_p95_ms": pctl(0.95)}

    legs = {}
    # The winners registry is process-global (ops/pallas/autotune.py):
    # start clean, and keep the heuristic int8 leg BEFORE the autotuned
    # one — a populated registry would silently retune it.
    from bert_pytorch_tpu.ops.pallas import autotune as autotune_lib

    autotune_lib.clear_winners()
    plans = (
        ("baseline", {}),
        ("fused", {"fuse_epilogues": True}),
        ("int8_attn", {"fuse_epilogues": True,
                       "attention_backend": "pallas_infer_int8"}),
        ("autotuned", {"fuse_epilogues": True,
                       "attention_backend": "pallas_infer_int8",
                       "autotune": "measure",
                       "autotune_cache": winners_path}),
    )
    for tag, kw in plans:
        engine = build(**kw)
        leg = replay(engine)
        startup = engine.startup or {}
        out_bytes, accessed = fill_mask_cost(engine)
        leg.update({
            "cold_start_s": startup.get("cold_start_s"),
            "compiles_cold": startup.get("compiles_cold"),
            "compiles_warm": startup.get("compiles_warm"),
            "weight_bytes": startup.get("weight_bytes"),
            "fill_mask_output_bytes": out_bytes or None,
            "fill_mask_bytes_accessed": accessed or None,
        })
        legs[tag] = leg

    # Warm-restart proof WITH autotune winners present: same settings as
    # the autotuned leg, winners loaded from the persisted file — every
    # forward must be a persistent-cache hit (counter events, not wall
    # clock: the PR-8 authority).
    warm_engine = build(fuse_epilogues=True,
                        attention_backend="pallas_infer_int8",
                        autotune="load", autotune_cache=winners_path)
    warm = warm_engine.startup or {}

    def bytes_ratio(a, b):
        if legs[a]["fill_mask_output_bytes"] and \
                legs[b]["fill_mask_output_bytes"]:
            return round(legs[a]["fill_mask_output_bytes"]
                         / legs[b]["fill_mask_output_bytes"], 2)
        return None

    ratio = bytes_ratio("baseline", "fused")
    result = {
        "metric": "serve_kernels_fill_mask_output_bytes_ratio",
        "value": ratio,
        "unit": "x (unfused/fused output bytes)",
        "n_requests": KERNELS_REQUESTS,
        "buckets": buckets,
        "batch_size": KERNELS_BATCH,
        "model_vocab": KERNELS_VOCAB,
        "legs": legs,
        "autotune_winners_file": winners_path,
        "second_start_cold_compiles": warm.get("compiles_cold"),
        "second_start_warm_compiles": warm.get("compiles_warm"),
        # ok = the CPU-provable invariants: the fused epilogue moved
        # measurably fewer bytes AND the autotuned warm restart was
        # entirely cache-served.
        "ok": bool((ratio or 0) > 1.5 and warm.get("compiles_cold") == 0),
    }
    if sink is not None:
        sink.write_record({
            "kind": "run_summary", "tag": "telemetry",
            "step": KERNELS_REQUESTS, "steps": KERNELS_REQUESTS,
            "metric": result["metric"]})
        sink.close()
    try:
        with open(_warm_marker_path(), "w") as f:
            f.write("ok\n")
    except OSError:
        pass
    print(_json.dumps(result))


def _serve_saturation_child_main():
    """BENCH_SERVE_SATURATION leg: the ROADMAP saturation curve — a
    closed-loop req/s vs p99 sweep through the REAL fleet (supervisor-
    owned ``run_server.py`` replica subprocesses behind the router), at
    1 and 2 supervised replicas, pipelined vs serial dispatch legs
    replaying the same trace (docs/serving.md "Continuous batching").

    The parent stays jax-free (supervisor/router/synthetic-data load by
    file path, like tools/chaos_serve.py): all compilation happens
    inside the replica subprocesses, which share one persistent AOT
    cache — the first replica of the first fleet compiles, every later
    fleet warms from the cache, so four fleets cost one warmup. A small
    2-layer model keeps each point dispatch-bound, which is the thing
    under test: the curve separates the dispatch planes, not the model.
    """
    import http.client
    import importlib.util
    import json as _json
    import socket
    import tempfile
    import threading
    import urllib.parse

    def _load(name, *parts):
        spec = importlib.util.spec_from_file_location(
            name, os.path.join(REPO_ROOT, *parts))
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        return module

    supervisor_mod = _load("_sat_supervisor",
                           "bert_pytorch_tpu", "serve", "supervisor.py")
    router_mod = _load("_sat_router",
                       "bert_pytorch_tpu", "serve", "router.py")
    synth = _load("_sat_synth",
                  "bert_pytorch_tpu", "tools", "make_synthetic_data.py")

    replicas_list = [int(n) for n in os.environ.get(
        "BENCH_SERVE_SAT_REPLICAS", "1,2").split(",") if n.strip()]
    modes = [m.strip() for m in os.environ.get(
        "BENCH_SERVE_SAT_MODES", "pipelined,serial").split(",")
        if m.strip()]
    workers_list = [int(n) for n in os.environ.get(
        "BENCH_SERVE_SAT_WORKERS", "2,6").split(",") if n.strip()]
    point_requests = int(os.environ.get("BENCH_SERVE_SAT_REQUESTS", "48"))
    warmup_s = float(os.environ.get("BENCH_SERVE_SAT_WARMUP_S", "240"))

    workdir = tempfile.mkdtemp(prefix="bench_servesat_")
    cache_dir = os.path.join(workdir, "compile_cache")
    vocab_path = synth.write_trace_vocab(os.path.join(workdir, "vocab.txt"))
    vocab = 5 + len(synth.TRACE_WORDS)
    vocab += (8 - vocab % 8) % 8
    config_path = os.path.join(workdir, "model.json")
    with open(config_path, "w") as f:
        _json.dump({
            "vocab_size": vocab, "hidden_size": 64, "num_hidden_layers": 2,
            "num_attention_heads": 4, "intermediate_size": 128,
            "max_position_embeddings": 64, "type_vocab_size": 2,
            "next_sentence": True, "mask_token_id": 4,
            "hidden_dropout_prob": 0.0,
            "attention_probs_dropout_prob": 0.0,
        }, f)

    phrases = ("paris is big", "the river runs through london",
               "william shakespeare wrote hamlet", "england is old",
               "the capital of france is paris")

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def spawn(spec):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("BENCH_CHILD", None)  # the replica is run_server, not us
        if spec.env:
            env.update(spec.env)
        log = open(os.path.join(
            workdir, f"replica_{spec.index}.log"), "ab")
        return subprocess.Popen(spec.cmd, env=env, stdout=log,
                                stderr=subprocess.STDOUT)

    def post(url, payload, timeout_s):
        parsed = urllib.parse.urlsplit(url)
        conn = http.client.HTTPConnection(parsed.hostname, parsed.port,
                                          timeout=timeout_s)
        try:
            conn.request("POST", "/v1/classify",
                         body=_json.dumps(payload).encode("utf-8"),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            resp.read()
            return resp.status
        finally:
            conn.close()

    def burst(url, total, workers):
        """Closed-loop burst: ``workers`` concurrent clients, each
        firing its next request the moment the previous answers —
        offered load scales with the worker count, which is the sweep
        axis of the saturation curve."""
        lock = threading.Lock()
        issued = [0]
        outcomes = []

        def worker():
            while True:
                with lock:
                    if issued[0] >= total:
                        return
                    issued[0] += 1
                    seq = issued[0]
                payload = {"text": phrases[seq % len(phrases)]}
                t0 = time.monotonic()
                try:
                    status = post(url, payload, timeout_s=30.0)
                except Exception:
                    status = None
                with lock:
                    outcomes.append(
                        (status, time.monotonic() - t0))

        t0 = time.monotonic()
        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return outcomes, time.monotonic() - t0

    def pctl(sorted_vals, frac):
        if not sorted_vals:
            return None
        idx = min(len(sorted_vals) - 1,
                  int(frac * (len(sorted_vals) - 1) + 0.5))
        return sorted_vals[idx]

    legs = []
    for n_replicas in replicas_list:
        for mode in modes:
            shared_args = [
                "--model_config_file", config_path,
                "--vocab_file", vocab_path,
                "--tasks", "classify", "--classify_labels", "neg,pos",
                "--buckets", "16,32", "--max_batch_size", "4",
                "--max_wait_ms", "5", "--dtype", "float32",
                "--compile_cache_dir", cache_dir,
                "--trace_sample_rate", "0", "--telemetry_window", "32",
                "--request_timeout_s", "20",
                "--dispatch_mode", mode,
            ]
            specs = []
            for i in range(n_replicas):
                out_dir = os.path.join(
                    workdir, f"fleet_{n_replicas}{mode[0]}_replica_{i}")
                os.makedirs(out_dir, exist_ok=True)
                port = free_port()
                specs.append(supervisor_mod.ReplicaSpec(
                    index=i, port=port,
                    cmd=supervisor_mod.run_server_command(
                        port, out_dir, shared_args),
                    heartbeat_file=os.path.join(out_dir, "heartbeat.json")))
            sup = supervisor_mod.Supervisor(
                specs, emit=lambda rec: None, spawn=spawn,
                startup_grace_s=warmup_s, poll_interval_s=0.25,
                drain_grace_s=15.0)
            router = router_mod.Router(
                [s.url for s in specs], emit=lambda rec: None,
                scrape_interval_s=0.25, deadline_s=20.0,
                brownout_queue_depth=4096)
            router_server = router_mod.make_router_server(router, port=0)
            url = "http://%s:%d" % router_server.server_address[:2]
            leg = {"replicas": n_replicas, "dispatch_mode": mode,
                   "points": []}
            try:
                sup.start()
                router.start()
                threading.Thread(target=router_server.serve_forever,
                                 daemon=True).start()
                deadline = time.monotonic() + warmup_s
                while time.monotonic() < deadline and \
                        router.healthy_count() < n_replicas:
                    time.sleep(0.25)
                if router.healthy_count() < n_replicas:
                    leg["error"] = "fleet never became healthy"
                    legs.append(leg)
                    continue
                for workers in workers_list:
                    outcomes, wall = burst(url, point_requests, workers)
                    ok = [lat for status, lat in outcomes
                          if status is not None and 200 <= status < 300]
                    lat = sorted(lat * 1000.0 for lat in ok)
                    leg["points"].append({
                        "workers": workers,
                        "requests": len(outcomes),
                        "ok": len(ok),
                        "failures": len(outcomes) - len(ok),
                        # Goodput, not offered load: a failure-heavy
                        # point must not outscore an all-ok one in the
                        # headline max (failures ride alongside).
                        "req_per_sec": round(len(ok) / wall, 2),
                        "p50_ms": round(pctl(lat, 0.50), 2) if lat else None,
                        "p99_ms": round(pctl(lat, 0.99), 2) if lat else None,
                    })
            finally:
                # Each teardown step gets its own guard: a replica that
                # wedges sup.stop() must not leak the previous leg's
                # router server + scrape thread into the later legs.
                for teardown in (sup.stop, router_server.shutdown,
                                 router.stop):
                    try:
                        teardown()
                    except Exception:
                        pass
            legs.append(leg)

    # The headline value: best pipelined req/s at the largest sweep
    # point; the serial twin rides alongside so the curve carries its
    # own A/B (pipelined should hold lower p99 at equal offered load).
    def best(mode):
        points = [p for leg in legs if leg["dispatch_mode"] == mode
                  for p in leg.get("points", []) if p["ok"]]
        return max((p["req_per_sec"] for p in points), default=None)

    result = {
        "metric": "serve_saturation_req_per_sec",
        "value": best("pipelined"),
        "unit": "req/s",
        "requests_per_point": point_requests,
        "workers_sweep": workers_list,
        "serial_best_req_per_sec": best("serial"),
        "legs": legs,
    }
    print(json.dumps(result))


def _async_child_main():
    """BENCH_ASYNC leg: checkpoint-step p95 vs steady-state p95, blocking
    vs async device-snapshot saves, on an injected large synthetic state.

    The stall under test is host-side (D2H fetch + msgpack + disk), so the
    leg is meaningful on any backend — the CPU-reproducible counterpart of
    the production win, measured through the same StepTimer/ckpt_step
    telemetry the runners emit (docs/telemetry.md). Steps are paced
    sleeps: the point is the ratio between a step that carried a save and
    one that didn't, not the step time itself.
    """
    import json as _json
    import shutil
    import tempfile

    import jax.numpy as jnp

    from bert_pytorch_tpu.telemetry.report import summarize_records
    from bert_pytorch_tpu.telemetry.step_timer import StepTimer
    from bert_pytorch_tpu.utils import checkpoint as ckpt

    n_leaves = 8
    leaf_elems = ASYNC_STATE_MB * (1 << 20) // 4 // n_leaves
    state = {"model": {f"w{i}": jnp.ones((leaf_elems,), jnp.float32)
                       for i in range(n_leaves)},
             "epoch": 0}

    def run_mode(async_write: bool):
        tmp = tempfile.mkdtemp(prefix="bench_async_")
        timer = StepTimer(window=10, sync_every=0)
        records = []
        try:
            for step in range(1, ASYNC_STEPS + 1):
                timer.data_start()
                timer.data_end()
                time.sleep(ASYNC_STEP_MS / 1000.0)
                timer.dispatch_end()
                rec = timer.step_done(step)
                if rec:
                    records.append(rec)
                if step % ASYNC_CKPT_EVERY == 0:
                    t0 = time.perf_counter()
                    ckpt.save_checkpoint(tmp, step, state, keep=2,
                                         async_write=async_write)
                    timer.note_ckpt_stall(time.perf_counter() - t0)
            ckpt.wait_for_pending_save(tmp)
            rec = timer.flush(ASYNC_STEPS)
            if rec:
                records.append(rec)
        finally:
            ckpt.wait_for_pending_save()
            shutil.rmtree(tmp, ignore_errors=True)
        for rec in records:
            rec.update({"kind": "step_window", "tag": "telemetry"})
        return records

    sync_records = run_mode(async_write=False)
    async_records = run_mode(async_write=True)
    sync_sum = summarize_records(sync_records)
    async_sum = summarize_records(async_records)
    steady = async_sum.get("step_p95_s") or 1e-9
    sync_ratio = (sync_sum.get("ckpt_step_p95_s") or 0.0) / (
        sync_sum.get("step_p95_s") or 1e-9)
    async_ratio = (async_sum.get("ckpt_step_p95_s") or 0.0) / steady
    metric = "ckpt_step_p95_over_steady_async"
    result = {
        "metric": metric,
        "value": round(async_ratio, 3),
        "unit": "x steady-state step p95",
        "sync_ratio": round(sync_ratio, 3),
        "sync_ckpt_step_p95_s": sync_sum.get("ckpt_step_p95_s"),
        "async_ckpt_step_p95_s": async_sum.get("ckpt_step_p95_s"),
        "step_p95_s": async_sum.get("step_p95_s"),
        "state_mb": ASYNC_STATE_MB,
        "steps": ASYNC_STEPS,
        "ckpt_every": ASYNC_CKPT_EVERY,
        # The acceptance shape: async within 20% of steady state while
        # blocking stays a clear multiple (tests/test_async_hotpath.py
        # asserts the same through the report gating path).
        "ok": bool(async_ratio <= 1.2 < sync_ratio),
    }
    if TELEMETRY_JSONL:
        from bert_pytorch_tpu.utils.logging import JSONLHandler

        sink = JSONLHandler(TELEMETRY_JSONL, overwrite=False)
        for rec in async_records:
            sink.write_record(rec)
        sink.write_record({
            "kind": "run_summary", "tag": "telemetry",
            "step": ASYNC_STEPS, "steps": ASYNC_STEPS, "metric": metric,
            "ckpt_step_p95_s": async_sum.get("ckpt_step_p95_s")})
        sink.close()
    try:
        with open(_warm_marker_path(), "w") as f:
            f.write("ok\n")
    except OSError:
        pass
    print(_json.dumps(result))


def _mesh_child_main():
    """BENCH_MESH leg: step-time/MFU across composed strategy products on
    a forced-host 8-device CPU mesh (docs/parallelism.md).

    Every product steps the SAME tiny model with the SAME global batch
    through the one-mesh path — ``MeshSpec.parse`` -> derived rules ->
    composed collectives — so the numbers rank the parallelism overhead,
    not the model. Each captured product appends its own perf-ledger
    entry under a distinct config digest (products are only comparable
    with themselves across time); the printed result carries the full
    per-product table.
    """
    import hashlib
    import json as _json

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bert_pytorch_tpu import optim, pretrain
    from bert_pytorch_tpu.config import BertConfig
    from bert_pytorch_tpu.models import BertForPreTraining
    from bert_pytorch_tpu.parallel import (
        MeshSpec,
        MeshSpecError,
        create_mesh,
        logical_axis_rules,
    )
    from bert_pytorch_tpu.telemetry import ledger as ledger_mod
    from bert_pytorch_tpu.utils import flops as flops_util

    seq, global_batch, n_mb, max_pred = 128, 16, 4, 20
    config = BertConfig.from_dict({
        "vocab_size": 1024, "hidden_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "intermediate_size": 512,
        "max_position_embeddings": seq, "type_vocab_size": 2,
        "hidden_dropout_prob": 0.1, "attention_probs_dropout_prob": 0.1,
        "next_sentence": True,
    })
    model = BertForPreTraining(config, dtype=jnp.float32)
    schedule = optim.warmup_poly_schedule(1e-3, 0.25, 1000)
    sample = (jnp.zeros((1, seq), jnp.int32),) * 3
    rng = np.random.default_rng(0)
    host_flat = {
        "input_ids": rng.integers(
            0, config.vocab_size, (global_batch, seq)).astype(np.int32),
        "segment_ids": rng.integers(0, 2, (global_batch, seq)).astype(np.int32),
        "input_mask": np.ones((global_batch, seq), np.int32),
        "masked_lm_labels": np.where(
            rng.random((global_batch, seq)) < 0.15,
            rng.integers(0, config.vocab_size, (global_batch, seq)),
            -1).astype(np.int32),
        "next_sentence_labels": rng.integers(
            0, 2, (global_batch,)).astype(np.int32),
    }
    batch_dims = {"input_ids": 3, "segment_ids": 3, "input_mask": 3,
                  "masked_lm_labels": 3, "next_sentence_labels": 2}

    products, captured = [], 0
    for text in [s.strip() for s in MESH_SPECS.split(";") if s.strip()]:
        try:
            spec = MeshSpec.parse(text)
            spec.validate(n_devices=len(jax.devices()))
        except MeshSpecError as e:
            products.append({"spec": text, "skipped": f"invalid spec: {e}"})
            continue
        entry = {"spec": spec.canonical()}
        try:
            mesh = create_mesh(spec.mesh_config())
            rules = logical_axis_rules(spec)
            tx = optim.lamb(schedule, weight_decay_mask=optim.no_decay_mask)
            pipe = spec.pipe > 1
            # pp consumes explicit microbatches; dp/fsdp take one stacked
            # macrobatch (ACCUM=1) — same sequences per optimizer step.
            accum = n_mb if pipe else 1
            with mesh:
                shardings = pretrain.state_shardings(
                    mesh, model, rules, sample)
                b_shardings = pretrain.batch_shardings(
                    mesh, batch_dims, seq_sharded=spec.seq > 1)
                state = pretrain.make_init_fn(model, tx, sample, shardings)(
                    jax.random.PRNGKey(0))
                if pipe:
                    step = pretrain.make_pp_train_step(
                        model, tx, mesh, schedule=schedule,
                        next_sentence=True, shardings=shardings,
                        batch_shardings_=b_shardings,
                        max_pred_per_seq=max_pred)
                else:
                    step = pretrain.make_train_step(
                        model, tx, schedule=schedule, next_sentence=True,
                        shardings=shardings, batch_shardings_=b_shardings,
                        max_pred_per_seq=max_pred)
                batch = pretrain.put_batch(
                    pretrain.stack_microbatches(host_flat, accum),
                    b_shardings)
                for _ in range(MESH_WARMUP):
                    state, metrics = step(state, batch)
                    _ = float(metrics["loss"])
                start = time.perf_counter()
                for _ in range(MESH_STEPS):
                    state, metrics = step(state, batch)
                _ = float(metrics["loss"])  # forces the chained dispatch
                elapsed = time.perf_counter() - start
        except Exception as e:  # per-product: record, keep sweeping
            entry["skipped"] = f"{type(e).__name__}: {e}"
            products.append(entry)
            continue
        step_s = elapsed / MESH_STEPS
        seq_per_sec_chip = global_batch / step_s / len(jax.devices())
        mfu = flops_util.mfu(
            seq_per_sec_chip,
            flops_util.bert_train_flops_per_seq(
                config, seq, max_pred, next_sentence=True),
            jax.devices()[0].device_kind)
        entry.update({
            "step_ms_p50": round(step_s * 1000, 2),
            "seq_per_sec_chip": round(seq_per_sec_chip, 2),
            "mfu": round(mfu, 6),
        })
        products.append(entry)
        captured += 1
        if LEDGER_PATH:
            # Distinct digest per product: entries are only comparable
            # within one (config, product) pair across time.
            digest = hashlib.sha1(
                f"{CONFIG_DIGEST}|{spec.canonical()}".encode()
            ).hexdigest()[:12]
            try:
                ledger_mod.append_entry(
                    LEDGER_PATH, "mesh",
                    {"step_ms_p50": entry["step_ms_p50"],
                     "mfu": entry["mfu"],
                     "seq_per_sec_per_chip": entry["seq_per_sec_chip"]},
                    digest=digest,
                    extra={"metric": "mesh_product_step",
                           "mesh_spec": spec.canonical()})
                print(f"perf ledger: appended mesh [{digest}] "
                      f"{spec.canonical()}", file=sys.stderr)
            except Exception as exc:  # advisory, like the parent's append
                print(f"perf ledger append failed: {exc}", file=sys.stderr)

    if not captured:
        print("BENCH_CONFIG_ERROR: no mesh product captured: "
              + "; ".join(f"{p['spec']}: {p.get('skipped')}"
                          for p in products))
        sys.exit(2)
    best = max(p["seq_per_sec_chip"] for p in products
               if "seq_per_sec_chip" in p)
    try:
        with open(_warm_marker_path(), "w") as f:
            f.write("ok\n")
    except OSError:
        pass
    print(_json.dumps({
        "metric": "mesh_products_seq_per_sec_chip",
        "value": round(best, 2),
        "unit": "seq/s/chip (best product)",
        "vs_baseline": 1.0,
        "products": products,
        "captured": captured,
        "steps": MESH_STEPS,
        "global_batch": global_batch,
    }))


def _metric_name_and_anchor():
    kfac_tag = "_kfac" if KFAC else ""
    pack_tag = "_packed" if PACK else ""
    if MESH_SWEEP:
        # No external anchor: products are compared against each other
        # (and longitudinally via the per-product ledger entries).
        return ("mesh_products_seq_per_sec_chip", 1.0)
    if KERNELS:
        # Anchor 1.0 like the serve legs: no external baseline exists;
        # the child prints its own richer result.
        return ("serve_kernels_fill_mask_output_bytes_ratio", 1.0)
    if SERVE:
        # No external anchor exists for the serve leg; anchor 1.0 keeps
        # the parent's error-path JSON shape parseable (vs_baseline ==
        # value). The child prints its own richer result.
        if os.environ.get("BENCH_SERVE_QUANT", "0") == "1":
            mode = os.environ.get("BENCH_SERVE_QUANT_MODE", "int8")
            return (f"bert_base_serve_{mode}_p50_ms", 1.0)
        return ("bert_base_serve{}_req_per_sec".format(
            "_packed" if SERVE_PACK else ""), 1.0)
    if DEGRADED:
        # Parent-side estimate only (error paths); the child overrides the
        # anchor with the exactly FLOP-scaled value.
        return ("bert_base_phase1_seq_per_sec",
                A100_PHASE1_SEQ_PER_SEC * 3.0)
    if LONG_SEQ:
        return (f"bert_large_seq{SEQ_LEN}{kfac_tag}{pack_tag}_seq_per_sec",
                A100_PHASE2_SEQ_PER_SEC * 512.0 / SEQ_LEN)
    return (f"bert_large_phase{PHASE}{kfac_tag}{pack_tag}_seq_per_sec",
            A100_PHASE2_SEQ_PER_SEC if _P2 else A100_PHASE1_SEQ_PER_SEC)


def _result_json(seq_per_sec_chip, mfu=None, error=None, n_chips=None,
                 anchor_override=None):
    name, anchor = _metric_name_and_anchor()
    if anchor_override is not None:
        anchor = anchor_override
    out = {
        "metric": name,
        "value": round(seq_per_sec_chip, 2),
        "unit": "seq/s/chip",
        "vs_baseline": round(seq_per_sec_chip / anchor, 4),
    }
    if DEGRADED:
        out["degraded"] = True
        out["note"] = ("BERT-base fallback at the phase-1 shape — NOT the "
                       "headline BERT-large metric")
    if KFAC:
        out["kfac_capture"] = KFAC_CAPTURE
    if mfu is not None:
        out["mfu"] = round(mfu, 4)
    if n_chips is not None:
        out["n_chips"] = n_chips  # scaling sweeps (BENCH_DEVICES) read this
    if error is not None:
        out["error"] = error
    return out


def _telemetry_offset():
    """Byte size of the append-mode telemetry sink RIGHT NOW — taken
    immediately before each child attempt, so a failed earlier attempt's
    partial records (cold windows, near-OOM watermarks) never leak into
    the tail the regression gate scores for the attempt that succeeded."""
    if TELEMETRY_JSONL and os.path.exists(TELEMETRY_JSONL):
        try:
            return os.path.getsize(TELEMETRY_JSONL)
        except OSError:
            return 0
    return 0


def _attach_regression(result, offset=0):
    """Offline regression gate: when this run wrote a telemetry artifact
    and a previous committed one exists, diff them with
    tools/telemetry_report.py and attach the verdict. The bench result
    must always print, so the report's nonzero exit becomes a field
    (CI/the capture harness gate on it), never a bench failure.

    ``offset`` is the artifact's byte size when this invocation started:
    the sink is append-mode (capture passes accumulate evidence across
    runs), so the verdict must be computed over THIS invocation's records
    only — older runs' windows/memory records would otherwise pollute the
    maxima."""
    if not TELEMETRY_JSONL or not os.path.exists(TELEMETRY_JSONL):
        return result
    baseline = TELEMETRY_BASELINE
    if (not baseline or not os.path.exists(baseline)
            or os.path.abspath(baseline) == os.path.abspath(TELEMETRY_JSONL)):
        return result
    tool = os.path.join(REPO_ROOT, "tools", "telemetry_report.py")
    try:
        run_path = TELEMETRY_JSONL
        tmp_tail = None
        if offset:
            import tempfile

            with open(TELEMETRY_JSONL, "rb") as f:
                f.seek(offset)
                tail = f.read()
            fd, tmp_tail = tempfile.mkstemp(suffix=".jsonl")
            with os.fdopen(fd, "wb") as f:
                f.write(tail)
            run_path = tmp_tail
        try:
            # --last-run: both artifacts are append-mode accumulations
            # (this invocation's tail can hold several attempts; the
            # committed baseline can hold several legs) — score each
            # side's final run only.
            proc = subprocess.run(
                [sys.executable, tool, run_path, baseline, "--json",
                 "--last-run"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                timeout=120)
        finally:
            if tmp_tail:
                os.unlink(tmp_tail)
        verdict = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as exc:  # the gate is advisory; never break the bench
        print(f"telemetry regression gate failed: {exc}", file=sys.stderr)
        return result
    # Different bench legs (phase2, seq2048, kfac, degraded fallback)
    # share the default baseline path; diffing step time or peak memory
    # across configurations is meaningless — refuse, don't flag.
    run_metric = verdict.get("run", {}).get("metric")
    base_metric = verdict.get("baseline", {}).get("metric")
    if run_metric and base_metric and run_metric != base_metric:
        result["regression"] = {
            "verdict": "n/a",
            "baseline": os.path.basename(baseline),
            "note": f"baseline is {base_metric}, this run is "
                    f"{run_metric}; not comparable",
        }
        return result
    result["regression"] = {
        "verdict": verdict.get("verdict"),
        "baseline": os.path.basename(baseline),
        "regressions": [
            {k: r.get(k) for k in ("metric", "base", "new", "change")}
            for r in verdict.get("regressions", [])],
    }
    if verdict.get("verdict") == "regression":
        names = ", ".join(
            r.get("metric", "?") for r in verdict.get("regressions", []))
        print(f"bench REGRESSION vs {baseline}: {names}", file=sys.stderr)
    return result


def _ledger_leg():
    """Ledger leg name for the active bench configuration — entries are
    only comparable within a leg, so each child flavor gets its own."""
    if SERVE_SATURATION:
        return "serve_saturation"
    if SERVE:
        return "serve"
    if KERNELS:
        return "kernels"
    if ASYNC:
        return "async"
    if MESH_SWEEP:
        return "mesh"
    if DEGRADED:
        return "train_degraded"
    return "train"


def _append_ledger(result):
    """Append this capture's headline metrics to the perf ledger
    (advisory like the regression gate: a ledger failure must never
    break the bench result line). The ledger module is stdlib-only and
    loaded by file path — the parent stays jax-free."""
    if not LEDGER_PATH or result.get("error"):
        return
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "_bench_ledger",
            os.path.join(REPO_ROOT, "bert_pytorch_tpu", "telemetry",
                         "ledger.py"))
        ledger = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(ledger)
        metrics = {}
        for src, dst, scale in (
                ("mfu", "mfu", 1.0),
                ("latency_p50_ms", "serve_p50_ms", 1.0),
                ("latency_p99_ms", "serve_p99_ms", 1.0),
                ("cold_start_s", "cold_start_s", 1.0),
                ("padding_efficiency", "padding_efficiency", 1.0),
                # Direction-less extras: recorded for the trajectory
                # (perf_ledger.py show), not gated by the drift check.
                ("value", "headline", 1.0),
                ("seq_per_sec_per_chip", "seq_per_sec_per_chip", 1.0)):
            v = result.get(src)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                metrics[dst] = float(v) * scale
        rec = ledger.append_entry(
            LEDGER_PATH, _ledger_leg(), metrics,
            digest=_config_digest(),
            extra={"metric": result.get("metric")})
        if rec is not None:
            print(f"perf ledger: appended {rec['leg']} "
                  f"[{rec['config_digest']}] to {LEDGER_PATH}",
                  file=sys.stderr)
    except Exception as exc:
        print(f"perf ledger append failed: {exc}", file=sys.stderr)


_PROBE_SRC = ("import jax; ds = jax.devices(); "
              "print('BENCH_PROBE_OK', len(ds), ds[0].device_kind)")


def _parse_metric_line(out):
    """Last JSON object with a "metric" key in ``out``, or None. The
    result line must stay findable under kilobytes of runtime teardown
    logging printed after it."""
    for line in reversed(out.splitlines()):
        try:
            cand = json.loads(line)
        except ValueError:
            continue
        if isinstance(cand, dict) and "metric" in cand:
            return cand
    return None


def _run_attempt(cmd, timeout_s, env):
    """Run ``cmd``; return (ok, full_output). A hang is killed at
    ``timeout_s``. The FULL output is returned — the JSON result line must
    stay findable even under kilobytes of runtime teardown logging after it.
    """
    try:
        proc = subprocess.run(
            cmd, env=env, timeout=timeout_s,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    except subprocess.TimeoutExpired as e:
        out = (e.stdout or b"")
        if isinstance(out, bytes):
            out = out.decode("utf-8", "replace")
        # Keep everything printed BEFORE the hang: a child that printed the
        # metric line and then hung in runtime teardown is still a capture.
        return False, out + f"\n[killed: timeout after {timeout_s}s]"
    return proc.returncode == 0, proc.stdout or ""


def main():
    """Parent: probe backend, run the benchmark child, retry, never crash.

    Default worst case (backend hung the whole time) is bounded by
    BENCH_BUDGET_S ~= 15 min: the driver that invokes bench.py has its own
    timeout, and an error JSON printed before that timeout beats a longer
    retry window that gets killed mid-wait (round 1 lost its capture to
    exactly that). A healthy backend completes on the first attempt in a
    few minutes.
    """
    # Backoff between attempts now comes from the shared policy
    # (utils/retry.py) instead of an ad-hoc flat sleep: base BENCH_BACKOFF_S
    # doubling per retry, jittered so parallel capture harnesses pointed at
    # one recovering tunnel don't re-stampede it in lockstep.
    backoff = _retry.RetryPolicy(
        attempts=64,  # the wall-clock budget below is the real bound
        base_delay_s=float(os.environ.get("BENCH_BACKOFF_S", "30")),
        max_delay_s=120.0)
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
    # Long-sequence compiles through the tunnel can alone exceed the default
    # 600s attempt window (the seq-1024 leg measured >600s), and a killed
    # compile leaves nothing in the persistent cache to resume from — scale
    # the default with the sequence length so the first compile can finish.
    seq_scale = max(1.0, (LONG_SEQ or 0) / 512.0)
    budget_s = float(os.environ.get("BENCH_BUDGET_S", str(900 * seq_scale)))
    deadline = time.monotonic() + budget_s
    warm = _cache_is_warm()
    # Cold cache: one long attempt (a killed compile caches nothing, so
    # several short attempts can never make progress). Warm cache: the
    # compiled step deserializes in seconds, so short attempts + retries
    # maximize the chance of landing in a tunnel-up window.
    attempts = int(os.environ.get("BENCH_ATTEMPTS", "3" if warm else "1"))
    attempt_timeout = float(os.environ.get(
        "BENCH_ATTEMPT_TIMEOUT_S",
        str(600 * seq_scale if warm else max(600.0, budget_s - 60))))
    # Reserve a tail window for the degraded (BERT-base) fallback. NOT
    # gated on cache warmth (round-3 verdict: a cold round with a LIVE
    # tunnel must never print 0.0 — a cold BERT-large attempt cannot fit
    # any plausible window, so spending part of the budget on a cold
    # BERT-base compile that plausibly CAN fit strictly improves the
    # worst case). The reserve is sized on the DEGRADED config's own warm
    # marker: warm, the compiled step deserializes in seconds and a short
    # tail suffices; cold, the tail must hold a small-model compile.
    degrade_ok = (os.environ.get("BENCH_DEGRADE", "auto") != "0"
                  and not DEGRADED and PHASE == 1 and not KFAC
                  and not LONG_SEQ and not N_DEVICES and not PACK
                  and not SERVE and not ASYNC and not SERVE_SATURATION
                  and not KERNELS and not MESH_SWEEP)
    degraded_warm = degrade_ok and os.path.exists(
        os.path.join(CACHE_DIR, f"warm_{_degraded_digest()}"))
    if not degrade_ok:
        reserve = 0.0
    elif degraded_warm:
        reserve = min(240.0, 0.25 * budget_s)
    else:
        reserve = min(420.0, 0.45 * budget_s)
    normal_deadline = deadline - reserve
    print(f"bench plan: warm={warm} degraded_warm={degraded_warm} "
          f"attempts={attempts} reserve={reserve:.0f}s "
          f"degrade_ok={degrade_ok}", file=sys.stderr)

    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    if PACK:
        # The child is respawned WITHOUT argv, so the --pack_sequences
        # command-line spelling must be forwarded as the env knob — the
        # parent's digest/degrade gating already assumed the packed config.
        env["BENCH_PACK"] = "1"
        env.setdefault("BENCH_PACK_K", str(PACK_K))
    last_err = "no attempts ran"
    for attempt in range(1, attempts + 1):
        remaining = normal_deadline - time.monotonic()
        if remaining <= 5:
            last_err += " (wall-clock budget exhausted)"
            break
        if attempt > 1:
            # Cheap short-timeout probe before each RETRY: after a failure,
            # don't burn the long child timeout on a backend that is still
            # down. Attempt 1 skips it — on a healthy backend the probe
            # would just double the (tens of seconds) TPU init cost, and
            # the child has its own kill timeout anyway.
            ok, out = _run_attempt(
                [sys.executable, "-c", _PROBE_SRC],
                min(probe_timeout, remaining), env)
            if not ok or "BENCH_PROBE_OK" not in out:
                last_err = (f"backend probe failed (attempt {attempt}): "
                            f"{out[-400:]}")
                print(last_err, file=sys.stderr)
                if attempt < attempts:
                    time.sleep(min(
                        backoff.backoff_s(attempt - 1),
                        max(0, normal_deadline - time.monotonic())))
                continue
            remaining = normal_deadline - time.monotonic()
            if remaining <= 5:
                last_err = "backend probe ok but wall-clock budget exhausted"
                break
        tele_offset = _telemetry_offset()
        ok, out = _run_attempt(
            [sys.executable, os.path.abspath(__file__)],
            min(attempt_timeout, remaining), env)
        result = _parse_metric_line(out)
        if "BENCH_CONFIG_ERROR" in out:
            # Deterministic misconfiguration: retrying cannot help.
            last_err = out[out.index("BENCH_CONFIG_ERROR"):][:400]
            print(last_err, file=sys.stderr)
            break
        if result is not None:
            # A parsed metric line is a successful capture even if the
            # child's rc is non-zero (e.g. the TPU runtime crashing during
            # process TEARDOWN, after the measurement printed). One
            # dedicated key for that signal on both paths ('note' is the
            # degraded disclaimer and must not be overloaded).
            if not ok:
                result.setdefault(
                    "child_exit", "non-zero after printing result")
            result = _attach_regression(result, tele_offset)
            _append_ledger(result)
            print(json.dumps(result))
            return
        last_err = f"bench child failed (attempt {attempt}): {out[-400:]}"
        print(last_err, file=sys.stderr)
        if attempt < attempts:
            time.sleep(min(
                backoff.backoff_s(attempt - 1),
                max(0, normal_deadline - time.monotonic())))
    # The entry gate must agree with the reserve sizing: for budgets small
    # enough that the reserve is under 60s, a flat 60s gate would shave
    # the normal window AND then never run the fallback it paid for.
    if degrade_ok and deadline - time.monotonic() > min(60.0, 0.5 * reserve):
        # Last rung: BERT-base at the phase-1 shape. Probe first — a dead
        # backend fails the small model exactly like the big one.
        print("degraded fallback: probing backend", file=sys.stderr)
        ok, out = _run_attempt(
            [sys.executable, "-c", _PROBE_SRC],
            min(probe_timeout, deadline - time.monotonic()), env)
        if not ok or "BENCH_PROBE_OK" not in out:
            print("degraded fallback: backend probe failed; skipping",
                  file=sys.stderr)
        if ok and "BENCH_PROBE_OK" in out:
            denv = dict(env)
            denv["BENCH_DEGRADED"] = "1"
            tele_offset = _telemetry_offset()
            ok, out = _run_attempt(
                [sys.executable, os.path.abspath(__file__)],
                max(30, deadline - time.monotonic()), denv)
            result = _parse_metric_line(out)
            if result is not None:
                if not ok:
                    result.setdefault(
                        "child_exit", "non-zero after printing result")
                result = _attach_regression(result, tele_offset)
                _append_ledger(result)
                print(json.dumps(result))
                return
            last_err = (f"degraded fallback also failed: {out[-300:]}; "
                        f"after: {last_err}")
    # Final failure: the driver still gets one parseable JSON line on
    # stdout; the non-zero exit preserves the shell-level failure signal
    # for ``set -e`` callers (scripts/smoke_tpu.sh).
    print(json.dumps(_result_json(0.0, error=last_err[-500:])))
    sys.exit(1)


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD") == "1":
        if ASYNC:
            _async_child_main()
        elif MESH_SWEEP:
            _mesh_child_main()
        elif KERNELS:
            _kernels_child_main()
        elif SERVE_SATURATION:
            _serve_saturation_child_main()
        elif SERVE:
            _serve_child_main()
        else:
            _child_main()
    else:
        main()
