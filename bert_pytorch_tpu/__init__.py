"""bert_pytorch_tpu — a TPU-native (JAX/XLA/Pallas/pjit) BERT pretraining and
finetuning framework with the capability set of gpauloski/BERT-PyTorch.

Built TPU-first: SPMD over a `jax.sharding.Mesh`, one jitted train step with
microbatch `lax.scan` accumulation, bf16 compute / fp32 params, Pallas kernels
for the fused ops the reference delegated to NVIDIA Apex, and a C++ tokenizer
core replacing the HuggingFace Rust tokenizers.

Layout (mirrors SURVEY.md §2's component inventory):
  config      — BertConfig + CLI > JSON > defaults config system
  models/     — BERT encoder + every task head of the reference model library
  ops/        — Pallas/XLA kernels: LayerNorm, bias-GELU, attention, global-norm
  optim/      — LAMB, AdamW, BertAdam, warmup schedules, K-FAC preconditioner
  parallel/   — device mesh, sharding rules, collectives, multi-host launcher
  data/       — HDF5 sharded dataset, dynamic masking, samplers, tokenization
  utils/      — logging (stream/file/CSV/TB), checkpointing, dist helpers
  tools/      — offline pipeline: download / format / shard / vocab / encode
"""

__version__ = "0.1.0"

from bert_pytorch_tpu.config import BertConfig  # noqa: F401
