"""jaxlint — a pure-AST, jax-free static analyzer for TPU-hazard patterns
(docs/static_analysis.md).

Six PRs of jit-compiled hot paths and background-thread subsystems created
a failure surface the runtime tooling only *observes* after the fact: a
stray ``.item()`` in a step loop silently costs a host sync every step
(the CompileMonitor and StepTimer would show the symptom, not the line),
an unhashable static arg turns into a recompile storm, and the lock
discipline of the five background-thread subsystems (async checkpoint
writer, DevicePrefetcher, watchdog, JSONL sink, serve dispatch) was
enforced only by review memory — PR 5 and PR 6 each shipped a review-pass
fix for exactly such a bug. This package makes those invariants
machine-checked, BEFORE the code runs.

Design constraints:

* **Pure AST** — files are parsed, never imported. Scanning a module that
  imports jax/h5py/matplotlib costs milliseconds and no dependencies.
* **jax-free** — importing ``bert_pytorch_tpu.analysis`` pulls only the
  stdlib (the package ``__init__`` chain is stdlib-only by design), so
  the linter runs on pre-commit hooks and CI boxes without the
  accelerator stack, and inside the tier-1 budget on the 2-core box.
* **Stable check IDs** — every finding carries an ID (HS101, RC201, ...)
  suppressible inline with ``# jaxlint: disable=ID`` and grandfatherable
  in a committed baseline file (``jaxlint_baseline.json``).

Check families (one module each):

* ``host_sync``       HS101 — blocking host transfers in step-loop hot paths
  (cross-module via the program graph: an imported helper called from a
  timed loop is a hot region too)
* ``recompile``       RC201/RC202/RC203 — jit recompile / retrace hazards
* ``rng``             RN301/RN302 — PRNG key reuse and wall-clock seeds
* ``tracer_leak``     TL401 — traced values assigned to self/globals in jit
* ``lock_discipline`` LK501/LK502/LK503 — accesses of registered shared
  state outside its declared guard (``analysis/concurrency.py``)

The **shardlint** tier (whole-program: ``graph.Program`` parses every
target plus the canonical context set and the checks walk the
cross-module symbol/call graph):

* ``sharding``  SD601/SD602/SD603 — collective-axis discipline, logical
  rule coverage, and raw mesh-axis literals, all against the axes
  registry (``analysis/axes.py``, the jax-free mirror of
  ``parallel/mesh.py``) — the safety net under the "one mesh" refactor
* ``donation``  DN701 — a buffer donated to a jitted call
  (``donate_argnums``) and read after it
* ``contracts`` CT801/CT802 — telemetry kinds emitted off the
  ``telemetry/schema.py`` registry; argparse flags declared-but-never-
  read / read-but-never-declared
"""

from bert_pytorch_tpu.analysis.core import (  # noqa: F401
    ALL_CHECK_IDS,
    Finding,
    run_files,
    run_paths,
)
from bert_pytorch_tpu.analysis.baseline import (  # noqa: F401
    apply_baseline,
    load_baseline,
    write_baseline,
)
