"""The axes registry: mesh-axis names and per-strategy logical rules,
the declarative authority the sharding checks (SD601/SD602/SD603)
enforce against.

This mirrors ``parallel/mesh.py`` (``MESH_AXES``, the ``AXIS_*``
constants, ``_BASE_RULES``/``_STRATEGY_RULES``) the same way
``analysis/concurrency.py`` mirrors the lock discipline: the analysis
package must stay stdlib-only and jax-free (files are parsed, never
imported), so it cannot import the real tables — instead this module
restates them and ``tests/test_jaxlint.py`` pins the two copies
together by PARSING mesh.py's AST. Drift fails tier-1, not a refactor
three PRs later.

Why a registry at all: the "one mesh" refactor (ROADMAP) rewrites every
collective/PartitionSpec/axis-rule site in the codebase. A collective
over a typo'd axis name traces fine and crashes (or silently
mis-reduces) only under the mesh shape that exercises it; a logical
name with no rule under some strategy silently REPLICATES the parameter
— the exact fsdp bug class the ZeRO lineage warns about. With the
registry, both become lint findings at commit time, and the refactor
updates ONE table (mesh.py) plus its mirror here.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Optional, Tuple

# -- mesh axes (mirror of parallel/mesh.py MESH_AXES + AXIS_*) -----------

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_MODEL = "model"

MESH_AXES: Tuple[str, ...] = (
    AXIS_DATA, AXIS_FSDP, AXIS_PIPE, AXIS_SEQ, AXIS_MODEL)

# The constant spellings model/runner code must import instead of raw
# literals (the SD603 contract). Name -> axis value, for messages and
# the mesh.py mirror test.
AXIS_CONSTANTS: Dict[str, str] = {
    "AXIS_DATA": AXIS_DATA,
    "AXIS_FSDP": AXIS_FSDP,
    "AXIS_PIPE": AXIS_PIPE,
    "AXIS_SEQ": AXIS_SEQ,
    "AXIS_MODEL": AXIS_MODEL,
}

# -- logical-axis rules (mirror of mesh.py _BASE_RULES/_RULE_TEMPLATE/
# _STRATEGY_AXES) --
# Values are mesh axes (or None = replicated); only the KEY COVERAGE is
# what SD602 enforces — an unmatched logical name silently replicates —
# but the mirror keeps the values too so the consistency test can pin
# the whole table.

BASE_RULES: Tuple[Tuple[str, object], ...] = (
    ("batch", (AXIS_DATA, AXIS_FSDP)),
    ("seq_act", AXIS_SEQ),
    ("pos", None),
    ("types", None),
    ("classes", None),
    ("layers", None),
)

# Mirror of mesh.py _RULE_TEMPLATE: per param logical axis, the mesh axis
# that controls it when active in the mesh spec (else replicated). The
# one-mesh refactor derives EVERY strategy product's rules from this one
# table; a new logical name in model code must land here (or in
# BASE_RULES) or SD602 flags it as silently replicating.
RULE_TEMPLATE: Tuple[Tuple[str, object], ...] = (
    ("embed", AXIS_FSDP),
    ("embed_out", AXIS_MODEL),
    ("vocab", AXIS_MODEL),
    ("heads", AXIS_MODEL),
    ("kv", None),
    ("mlp", AXIS_MODEL),
)

# Mirror of mesh.py _STRATEGY_AXES: legacy alias -> activated mesh axes.
STRATEGY_AXES: Dict[str, Tuple[str, ...]] = {
    "dp": (),
    "sp": (AXIS_SEQ,),
    "fsdp": (AXIS_FSDP,),
    "tp": (AXIS_MODEL,),
    "tp_fsdp": (AXIS_FSDP, AXIS_MODEL),
    "pp": (AXIS_PIPE,),
    "pp_tp": (AXIS_PIPE, AXIS_MODEL),
}


def derive_rules(active) -> Tuple[Tuple[str, object], ...]:
    """Stdlib re-derivation of mesh.derive_rules: param rules for a set
    of active mesh axes (an active 'pipe' prepends the stacked-layer
    rule; template rules resolve to their controlling axis when active,
    else None)."""
    active = frozenset(active)
    rules = []
    if AXIS_PIPE in active:
        rules.append(("layers", AXIS_PIPE))
    for name, axis in RULE_TEMPLATE:
        rules.append((name, axis if axis is not None and axis in active
                      else None))
    return tuple(rules)


# Legacy aliases, regenerated exactly like mesh.py regenerates its
# _STRATEGY_RULES (tests/test_mesh.py pins the two derivations equal).
STRATEGY_RULES: Dict[str, Tuple[Tuple[str, object], ...]] = {
    name: derive_rules(axes) for name, axes in STRATEGY_AXES.items()
}

# Every expressible strategy PRODUCT over the param-sharding axes
# (fsdp × pipe × model, with/without seq): SD602 coverage runs over
# these generated products too, so a logical name that resolves under
# the legacy aliases but not under some composed mesh is still caught.
_PRODUCT_AXES = (AXIS_FSDP, AXIS_PIPE, AXIS_SEQ, AXIS_MODEL)

PRODUCT_RULES: Dict[str, Tuple[Tuple[str, object], ...]] = {}
for _mask in range(1 << len(_PRODUCT_AXES)):
    _active = tuple(a for i, a in enumerate(_PRODUCT_AXES)
                    if _mask & (1 << i))
    _name = "dp" if not _active else "dp*" + "*".join(_active)
    PRODUCT_RULES[_name] = derive_rules(_active)
del _mask, _active, _name


def strategies() -> Tuple[str, ...]:
    """Legacy aliases plus every generated axis product."""
    return tuple(sorted(set(STRATEGY_RULES) | set(PRODUCT_RULES)))


def logical_coverage(strategy: str) -> FrozenSet[str]:
    """Logical names that RESOLVE (to a mesh axis or an explicit None =
    replicated) under ``strategy`` (a legacy alias or a generated
    product name): its own rules plus the shared base rules — the
    first-wins matching of mesh.logical_axis_rules means key membership
    in the union is exactly 'has a rule'."""
    rules = (STRATEGY_RULES.get(strategy)
             if strategy in STRATEGY_RULES else PRODUCT_RULES[strategy])
    return frozenset(name for name, _ in rules + BASE_RULES)


def uncovered_strategies(logical_name: str) -> Tuple[str, ...]:
    """Declared strategies under which ``logical_name`` has NO rule (and
    would silently replicate). Empty = fully covered."""
    return tuple(s for s in strategies()
                 if logical_name not in logical_coverage(s))


def is_mesh_axis(name: str) -> bool:
    return name in MESH_AXES


def constant_for(axis: str) -> Optional[str]:
    """The AXIS_* constant name for a mesh axis value (for messages)."""
    for const, value in AXIS_CONSTANTS.items():
        if value == axis:
            return const
    return None
