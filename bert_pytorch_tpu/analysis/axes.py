"""The axes registry: mesh-axis names and per-strategy logical rules,
the declarative authority the sharding checks (SD601/SD602/SD603)
enforce against.

This mirrors ``parallel/mesh.py`` (``MESH_AXES``, the ``AXIS_*``
constants, ``_BASE_RULES``/``_STRATEGY_RULES``) the same way
``analysis/concurrency.py`` mirrors the lock discipline: the analysis
package must stay stdlib-only and jax-free (files are parsed, never
imported), so it cannot import the real tables — instead this module
restates them and ``tests/test_jaxlint.py`` pins the two copies
together by PARSING mesh.py's AST. Drift fails tier-1, not a refactor
three PRs later.

Why a registry at all: the "one mesh" refactor (ROADMAP) rewrites every
collective/PartitionSpec/axis-rule site in the codebase. A collective
over a typo'd axis name traces fine and crashes (or silently
mis-reduces) only under the mesh shape that exercises it; a logical
name with no rule under some strategy silently REPLICATES the parameter
— the exact fsdp bug class the ZeRO lineage warns about. With the
registry, both become lint findings at commit time, and the refactor
updates ONE table (mesh.py) plus its mirror here.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Optional, Tuple

# -- mesh axes (mirror of parallel/mesh.py MESH_AXES + AXIS_*) -----------

AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_MODEL = "model"

MESH_AXES: Tuple[str, ...] = (
    AXIS_DATA, AXIS_FSDP, AXIS_PIPE, AXIS_SEQ, AXIS_MODEL)

# The constant spellings model/runner code must import instead of raw
# literals (the SD603 contract). Name -> axis value, for messages and
# the mesh.py mirror test.
AXIS_CONSTANTS: Dict[str, str] = {
    "AXIS_DATA": AXIS_DATA,
    "AXIS_FSDP": AXIS_FSDP,
    "AXIS_PIPE": AXIS_PIPE,
    "AXIS_SEQ": AXIS_SEQ,
    "AXIS_MODEL": AXIS_MODEL,
}

# -- logical-axis rules (mirror of mesh.py _BASE_RULES/_STRATEGY_RULES) --
# Values are mesh axes (or None = replicated); only the KEY COVERAGE is
# what SD602 enforces — an unmatched logical name silently replicates —
# but the mirror keeps the values too so the consistency test can pin
# the whole table.

BASE_RULES: Tuple[Tuple[str, object], ...] = (
    ("batch", (AXIS_DATA, AXIS_FSDP)),
    ("seq_act", AXIS_SEQ),
    ("pos", None),
    ("types", None),
    ("classes", None),
    ("layers", None),
)

STRATEGY_RULES: Dict[str, Tuple[Tuple[str, object], ...]] = {
    "pp": (
        ("layers", AXIS_PIPE),
        ("embed", None),
        ("embed_out", None),
        ("vocab", None),
        ("heads", None),
        ("kv", None),
        ("mlp", None),
    ),
    "sp": (
        ("embed", None),
        ("embed_out", None),
        ("vocab", None),
        ("heads", None),
        ("kv", None),
        ("mlp", None),
    ),
    "dp": (
        ("embed", None),
        ("embed_out", None),
        ("vocab", None),
        ("heads", None),
        ("kv", None),
        ("mlp", None),
    ),
    "fsdp": (
        ("embed", AXIS_FSDP),
        ("embed_out", None),
        ("vocab", None),
        ("heads", None),
        ("kv", None),
        ("mlp", None),
    ),
    "tp": (
        ("embed", None),
        ("embed_out", AXIS_MODEL),
        ("vocab", AXIS_MODEL),
        ("heads", AXIS_MODEL),
        ("kv", None),
        ("mlp", AXIS_MODEL),
    ),
    "tp_fsdp": (
        ("embed", AXIS_FSDP),
        ("embed_out", AXIS_MODEL),
        ("vocab", AXIS_MODEL),
        ("heads", AXIS_MODEL),
        ("kv", None),
        ("mlp", AXIS_MODEL),
    ),
    "pp_tp": (
        ("layers", AXIS_PIPE),
        ("embed", None),
        ("embed_out", AXIS_MODEL),
        ("vocab", AXIS_MODEL),
        ("heads", AXIS_MODEL),
        ("kv", None),
        ("mlp", AXIS_MODEL),
    ),
}


def strategies() -> Tuple[str, ...]:
    return tuple(sorted(STRATEGY_RULES))


def logical_coverage(strategy: str) -> FrozenSet[str]:
    """Logical names that RESOLVE (to a mesh axis or an explicit None =
    replicated) under ``strategy``: its own rules plus the shared base
    rules — the first-wins matching of mesh.logical_axis_rules means key
    membership in the union is exactly 'has a rule'."""
    return frozenset(
        name for name, _ in STRATEGY_RULES[strategy] + BASE_RULES)


def uncovered_strategies(logical_name: str) -> Tuple[str, ...]:
    """Declared strategies under which ``logical_name`` has NO rule (and
    would silently replicate). Empty = fully covered."""
    return tuple(s for s in strategies()
                 if logical_name not in logical_coverage(s))


def is_mesh_axis(name: str) -> bool:
    return name in MESH_AXES


def constant_for(axis: str) -> Optional[str]:
    """The AXIS_* constant name for a mesh axis value (for messages)."""
    for const, value in AXIS_CONSTANTS.items():
        if value == axis:
            return const
    return None
