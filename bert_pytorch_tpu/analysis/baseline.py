"""Baseline file: grandfathered findings that do not fail the gate.

The committed ``jaxlint_baseline.json`` holds findings that predate the
analyzer (or are accepted debt, each with a ``justification``). Matching
is by ``(check, path, source)`` — the stripped source text of the
flagged line, NOT its line number — so unrelated edits that shift lines
do not resurrect baselined findings, while any edit to the flagged line
itself (including a fix) drops it out of the baseline. ``jaxlint
--write-baseline`` regenerates the file from the current findings;
``--prune-baseline`` (default behavior of --write-baseline) drops
entries that no longer match anything.

The gate's contract (ISSUE 7): this file starts near-empty — real
findings get FIXED, the baseline is for the rare justified exception.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, List, Tuple

from bert_pytorch_tpu.analysis.core import Finding

BASELINE_VERSION = 1
DEFAULT_BASENAME = "jaxlint_baseline.json"


def load_baseline(path: str) -> List[dict]:
    """Entries of a baseline file; [] when the file does not exist.
    Raises ValueError on a malformed file — a torn baseline must fail
    the gate loudly, not silently un-grandfather everything."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("version") != BASELINE_VERSION \
            or not isinstance(data.get("entries"), list):
        raise ValueError(
            f"{path}: not a v{BASELINE_VERSION} jaxlint baseline "
            "({'version': 1, 'entries': [...]})")
    for entry in data["entries"]:
        if not isinstance(entry, dict) \
                or not all(k in entry for k in ("check", "path", "source")):
            raise ValueError(
                f"{path}: baseline entries need check/path/source keys")
    return data["entries"]


def merge_entries(existing: List[dict], findings: Iterable[Finding],
                  linted_paths: Iterable[str],
                  justification: str = "grandfathered by --write-baseline"
                  ) -> List[dict]:
    """Baseline entries after a ``--write-baseline`` run that linted only
    ``linted_paths``: entries for UNLINTED files survive untouched (a
    subset run must never delete another file's grandfathered entry or
    its hand-written justification), entries for linted files survive
    iff they still match a finding (keeping their justification — only
    genuinely-stale ones are pruned), and findings no existing entry
    covers get fresh entries."""
    linted = set(linted_paths)
    kept = [e for e in existing if e["path"] not in linted]
    in_scope = [e for e in existing if e["path"] in linted]
    new, matched, _stale = apply_baseline(findings, in_scope)
    matched_keys = {(f.check, f.path, f.source) for f in matched}
    kept += [e for e in in_scope
             if (e["check"], e["path"], e["source"]) in matched_keys]
    kept += [{"check": f.check, "path": f.path, "source": f.source,
              "justification": justification} for f in new]
    return kept


def write_entries(path: str, entries: List[dict]) -> int:
    payload = {
        "version": BASELINE_VERSION,
        "comment": "Grandfathered jaxlint findings (docs/static_analysis"
                   ".md). Keep this near-empty: fix findings, baseline "
                   "only justified exceptions.",
        "entries": entries,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=False)
        f.write("\n")
    return len(entries)


def write_baseline(path: str, findings: Iterable[Finding],
                   justification: str = "grandfathered by --write-baseline"
                   ) -> int:
    """Write a baseline holding exactly ``findings`` (no merge — callers
    that linted a subset of the repo should go through
    :func:`merge_entries` first, as the CLI does)."""
    return write_entries(path, [
        {"check": f.check, "path": f.path, "source": f.source,
         "justification": justification}
        for f in findings
    ])


def apply_baseline(findings: Iterable[Finding], entries: List[dict]
                   ) -> Tuple[List[Finding], List[Finding], List[dict]]:
    """(new, matched, stale): findings not covered by the baseline,
    findings it covers, and entries that matched nothing (candidates
    for pruning — usually a fixed line)."""
    keys = {}
    for entry in entries:
        keys.setdefault(
            (entry["check"], entry["path"], entry["source"]), []).append(entry)
    new: List[Finding] = []
    matched: List[Finding] = []
    used = set()
    for f in findings:
        key = (f.check, f.path, f.source)
        if key in keys:
            matched.append(f)
            used.add(key)
        else:
            new.append(f)
    stale = [e for key, group in keys.items() if key not in used
             for e in group]
    return new, matched, stale
