"""bert-lint — the ONE standalone gate: jaxlint + telemetry-schema lint.

Before PR 7 the repo had two separately-invoked checkers (jaxlint did
not exist; ``tools/check_telemetry_schema.py`` linted JSONL artifacts)
and every caller — the tier-1 suite, the capture harness's
``commit_artifacts``, pre-commit hooks — had to know which to run when.
This module is the single entry point: it runs

1. **jaxlint** over the canonical code targets (the whole
   ``bert_pytorch_tpu`` package, the five repo-root runners, and
   ``tools/``), honoring the committed baseline; and
2. the **telemetry schema lint** over the given ``*.jsonl`` artifacts
   (default: every ``*.jsonl`` in the repo root — the same set tier-1
   lints and the capture harness is about to commit).

Exit 0 only when both pass. Installed as the ``bert-lint`` console
script; ``tools/check_all.py`` is the uninstalled repo-root wrapper;
``scripts/lint.sh`` is the pre-commit convenience alias.

jax-free like everything in this package: the schema engine is loaded
from ``telemetry/schema.py`` by FILE PATH (the ``tools/_bootstrap.py``
technique), never through ``bert_pytorch_tpu.telemetry.__init__``,
whose sibling imports pull jax.
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import os
import sys
from typing import List, Optional

from bert_pytorch_tpu.analysis import cli as jaxlint_cli


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


# The canonical jaxlint target set — what the tier-1 gate, the
# acceptance command, and commit hooks all mean by "lint the repo".
JAXLINT_TARGETS = ("bert_pytorch_tpu", "run_glue.py", "run_ner.py",
                   "run_pretraining.py", "run_server.py", "run_squad.py",
                   "run_swag.py", "tools")


def _load_schema_module():
    root = _repo_root()
    path = os.path.join(root, "bert_pytorch_tpu", "telemetry", "schema.py")
    spec = importlib.util.spec_from_file_location("_bert_lint_schema", path)
    module = importlib.util.module_from_spec(spec)
    sys.modules["_bert_lint_schema"] = module
    spec.loader.exec_module(module)
    return module


def _lint_jsonls(paths: List[str]) -> int:
    # Deliberately NOT delegating to tools/check_telemetry_schema.py:
    # that script is repo-root tooling (sys.path tricks, rc-2-on-missing
    # contract its own callers rely on), while this function must work
    # from an INSTALLED bert-lint console script where tools/ does not
    # exist — only the packaged schema.py does. The shared engine is
    # schema.validate_file; everything here is presentation. A missing
    # file counts as a plain failure (rc 1): one gate, one exit
    # contract.
    schema = _load_schema_module()
    root = _repo_root()
    failed = 0
    for path in paths:
        if not os.path.exists(path):
            print(f"bert-lint: {path}: no such file", file=sys.stderr)
            failed += 1
            continue
        errors = schema.validate_file(path)
        rel = os.path.relpath(path, root)
        if errors:
            failed += 1
            for lineno, err in errors:
                print(f"{rel}:{lineno}: {err}")
        else:
            print(f"{rel}: ok")
    return failed


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="bert-lint",
        description="Unified repo gate: jaxlint (docs/static_analysis.md) "
                    "over the package + runners + tools, then the "
                    "telemetry record schema over JSONL artifacts.")
    parser.add_argument(
        "jsonls", nargs="*",
        help="JSONL artifacts to schema-lint (default: <repo>/*.jsonl)")
    parser.add_argument("--skip-jaxlint", action="store_true",
                        help="only schema-lint the JSONL artifacts")
    parser.add_argument("--skip-schema", action="store_true",
                        help="only run jaxlint over the code targets")
    args = parser.parse_args(argv)

    rc = 0
    if not args.skip_jaxlint:
        print("== jaxlint ==")
        targets = [os.path.join(_repo_root(), t) for t in JAXLINT_TARGETS]
        if jaxlint_cli.main(targets) != 0:
            rc = 1
    if not args.skip_schema:
        paths = list(args.jsonls) or sorted(
            glob.glob(os.path.join(_repo_root(), "*.jsonl")))
        print("== telemetry schema ==")
        if not paths:
            print("bert-lint: no *.jsonl artifacts to lint")
        elif _lint_jsonls(paths):
            rc = 1
    print("bert-lint: " + ("OK" if rc == 0 else "FAILED"))
    return rc


if __name__ == "__main__":
    sys.exit(main())
