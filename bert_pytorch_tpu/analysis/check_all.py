"""bert-lint — the ONE standalone gate: jaxlint + telemetry-schema lint.

Before PR 7 the repo had two separately-invoked checkers (jaxlint did
not exist; ``tools/check_telemetry_schema.py`` linted JSONL artifacts)
and every caller — the tier-1 suite, the capture harness's
``commit_artifacts``, pre-commit hooks — had to know which to run when.
This module is the single entry point: it runs

1. **jaxlint** over the canonical code targets (the whole
   ``bert_pytorch_tpu`` package, the five repo-root runners, and
   ``tools/``), honoring the committed baseline; and
2. the **telemetry schema lint** over the given ``*.jsonl`` artifacts
   (default: every ``*.jsonl`` in the repo root — the same set tier-1
   lints and the capture harness is about to commit).

Exit 0 only when both pass. Installed as the ``bert-lint`` console
script; ``tools/check_all.py`` is the uninstalled repo-root wrapper;
``scripts/lint.sh`` is the pre-commit convenience alias.

jax-free like everything in this package: the schema engine is loaded
from ``telemetry/schema.py`` by FILE PATH (the ``tools/_bootstrap.py``
technique), never through ``bert_pytorch_tpu.telemetry.__init__``,
whose sibling imports pull jax.
"""

from __future__ import annotations

import argparse
import glob
import importlib.util
import json
import os
import sys
from typing import List, Optional

from bert_pytorch_tpu.analysis import cli as jaxlint_cli
# The canonical jaxlint target set — what the tier-1 gate, the
# acceptance command, and commit hooks all mean by "lint the repo".
from bert_pytorch_tpu.analysis.core import JAXLINT_TARGETS  # noqa: F401


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _load_by_path(name: str, *parts: str):
    path = os.path.join(_repo_root(), *parts)
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    spec.loader.exec_module(module)
    return module


def _load_schema_module():
    return _load_by_path("_bert_lint_schema",
                         "bert_pytorch_tpu", "telemetry", "schema.py")


def _load_autotune_module():
    # ops/pallas/autotune.py keeps its module-level imports jax-free for
    # exactly this loader: the winners-file FORMAT rules live once, next
    # to the code that writes the files, and the lint gate reaches them
    # without pulling jax through the ops package __init__.
    return _load_by_path("_bert_lint_autotune",
                         "bert_pytorch_tpu", "ops", "pallas", "autotune.py")


def _winners_results(paths: List[str]) -> List[dict]:
    """[{path, ok, errors}] per autotune winners JSON — same shape as
    the schema results so both render through one presenter."""
    autotune = _load_autotune_module()
    root = _repo_root()
    results = []
    for path in paths:
        rel = os.path.relpath(path, root) if os.path.exists(path) else path
        if not os.path.exists(path):
            results.append({"path": rel, "ok": False,
                            "errors": [{"line": 0, "error": "no such file"}]})
            continue
        errors = autotune.validate_winners_file(path)
        results.append({
            "path": rel, "ok": not errors,
            "errors": [{"line": 0, "error": err} for err in errors]})
    return results


def _schema_results(paths: List[str]) -> List[dict]:
    """[{path, ok, errors: [{line, error}]}] per artifact — the shared
    engine behind both output formats."""
    schema = _load_schema_module()
    root = _repo_root()
    results = []
    for path in paths:
        rel = os.path.relpath(path, root) if os.path.exists(path) else path
        if not os.path.exists(path):
            results.append({"path": rel, "ok": False,
                            "errors": [{"line": 0, "error": "no such file"}]})
            continue
        errors = schema.validate_file(path)
        results.append({
            "path": rel, "ok": not errors,
            "errors": [{"line": lineno, "error": err}
                       for lineno, err in errors]})
    return results


def _lint_jsonls(paths: List[str]) -> int:
    # Deliberately NOT delegating to tools/check_telemetry_schema.py:
    # that script is repo-root tooling (sys.path tricks, rc-2-on-missing
    # contract its own callers rely on), while this function must work
    # from an INSTALLED bert-lint console script where tools/ does not
    # exist — only the packaged schema.py does. The shared engine is
    # schema.validate_file; everything here is presentation. A missing
    # file counts as a plain failure (rc 1): one gate, one exit
    # contract.
    failed = 0
    for result in _schema_results(paths):
        if result["ok"]:
            print(f"{result['path']}: ok")
            continue
        failed += 1
        for err in result["errors"]:
            if err["line"] == 0 and err["error"] == "no such file":
                print(f"bert-lint: {result['path']}: no such file",
                      file=sys.stderr)
            else:
                print(f"{result['path']}:{err['line']}: {err['error']}")
    return failed


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="bert-lint",
        description="Unified repo gate: jaxlint (docs/static_analysis.md) "
                    "over the package + runners + tools, then the "
                    "telemetry record schema over JSONL artifacts.")
    parser.add_argument(
        "jsonls", nargs="*",
        help="artifacts to lint: *.jsonl files go through the telemetry "
             "record schema, *.json files through the Pallas autotune "
             "winners-cache format (ops/pallas/autotune.py). Default: "
             "<repo>/*.jsonl plus <repo>/*autotune*.json")
    parser.add_argument("--skip-jaxlint", action="store_true",
                        help="only schema-lint the JSONL artifacts")
    parser.add_argument("--skip-schema", action="store_true",
                        help="only run jaxlint over the code targets")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text",
                        help="json emits one machine-readable object "
                             "(jaxlint findings incl. suppression state "
                             "+ per-artifact schema results) so CI can "
                             "diff findings across commits")
    args = parser.parse_args(argv)

    targets = [os.path.join(_repo_root(), t) for t in JAXLINT_TARGETS]
    if args.jsonls:
        jsonls = [p for p in args.jsonls if not p.endswith(".json")]
        winners = [p for p in args.jsonls if p.endswith(".json")]
    else:
        jsonls = sorted(glob.glob(os.path.join(_repo_root(), "*.jsonl")))
        winners = sorted(
            glob.glob(os.path.join(_repo_root(), "*autotune*.json")))

    if args.format == "json":
        rc = 0
        combined: dict = {"version": 1}
        if not args.skip_jaxlint:
            try:
                payload, jaxlint_rc = jaxlint_cli.gather(targets)
            except (ValueError, FileNotFoundError) as e:
                # Same rc-2 usage-error contract as the text mode (which
                # goes through jaxlint_cli.main): a corrupt baseline must
                # yield an error line, not a traceback and no JSON.
                print(f"bert-lint: {e}", file=sys.stderr)
                return 2
            combined["jaxlint"] = payload
            rc = rc or jaxlint_rc
        if not args.skip_schema:
            results = _schema_results(jsonls)
            combined["schema"] = results
            if any(not r["ok"] for r in results):
                rc = 1
            if winners:
                wresults = _winners_results(winners)
                combined["autotune_winners"] = wresults
                if any(not r["ok"] for r in wresults):
                    rc = 1
        combined["rc"] = rc
        print(json.dumps(combined, indent=2, sort_keys=False))
        return rc

    rc = 0
    if not args.skip_jaxlint:
        print("== jaxlint ==")
        if jaxlint_cli.main(targets) != 0:
            rc = 1
    if not args.skip_schema:
        print("== telemetry schema ==")
        if not jsonls:
            print("bert-lint: no *.jsonl artifacts to lint")
        elif _lint_jsonls(jsonls):
            rc = 1
        if winners:
            print("== autotune winners ==")
            for result in _winners_results(winners):
                if result["ok"]:
                    print(f"{result['path']}: ok")
                    continue
                rc = 1
                for err in result["errors"]:
                    print(f"{result['path']}: {err['error']}")
    print("bert-lint: " + ("OK" if rc == 0 else "FAILED"))
    return rc


if __name__ == "__main__":
    sys.exit(main())
