"""jaxlint command line (``tools/jaxlint.py`` is the repo-root wrapper).

Exit codes: 0 = clean (baseline-covered findings allowed), 1 = findings,
2 = usage / missing path / malformed baseline.

Since the shardlint tier (SD6xx/DN701/CT8xx, docs/static_analysis.md)
is whole-program, a subset run (``jaxlint serve``) still parses the
CANONICAL target set as graph context — otherwise a flag declared in
``serve/cli.py`` and read in ``run_server.py`` would be falsely flagged
as dead. Findings are only ever REPORTED for the requested paths;
``--no-context`` restricts the graph to them too (fixture tests and
out-of-repo runs).

``--format json`` emits one machine-readable object (stable check id,
path, line, source text, suppression state for every finding incl.
baselined ones) so CI can diff findings across commits;
``tools/check_all.py --format json`` threads it through the unified
gate.

This module — like the whole analysis package — must never import jax:
the tier-1 gate asserts it, and the pre-commit wrapper runs on boxes
without the accelerator stack.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple

from bert_pytorch_tpu.analysis import baseline as baseline_mod
from bert_pytorch_tpu.analysis import core


def _repo_root() -> str:
    # analysis/cli.py -> analysis -> bert_pytorch_tpu -> repo root
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="jaxlint",
        description="Pure-AST TPU-hazard linter (docs/static_analysis.md): "
                    "host-sync, recompile, RNG, tracer-leak, and "
                    "lock-discipline checks per file, plus the "
                    "whole-program shardlint tier (sharding/collective "
                    "discipline, donation hazards, contract drift), all "
                    "with stable IDs.")
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories; a bare name that does not exist is "
             "retried under bert_pytorch_tpu/ (so 'serve' works)")
    parser.add_argument(
        "--baseline", default=None,
        help="baseline file (default: <repo>/jaxlint_baseline.json when "
             "present); entries suppress matching findings")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline file: report every finding")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write the current UNSUPPRESSED findings to the baseline "
             "file and exit 0 (stale entries are pruned)")
    parser.add_argument(
        "--list-checks", action="store_true",
        help="print every check ID with its description and exit")
    parser.add_argument(
        "--no-context", action="store_true",
        help="do not parse the canonical target set as whole-program "
             "context for subset runs (the program checks then see only "
             "the given paths)")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format; json emits one object with every finding "
             "(incl. baselined, with suppression state) for CI diffing")
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="suppress the summary line (findings still print)")
    return parser


def gather(paths: List[str], *, baseline: Optional[str] = None,
           no_baseline: bool = False, no_context: bool = False
           ) -> Tuple[dict, int]:
    """Run the lint and return (payload, rc) without printing — the
    shared engine behind ``main`` and ``check_all --format json``.
    Payload: {files, findings: [{check, path, line, col, message,
    source, status}], stale_baseline: [...]}; rc as the CLI exit code.
    Raises FileNotFoundError / ValueError for usage errors (rc 2 paths)
    so callers can present them."""
    repo_root = _repo_root()
    files = core.expand_paths(paths, repo_root=repo_root)
    context = None
    if not no_context:
        context = []
        for target in core.JAXLINT_TARGETS:
            candidate = os.path.join(repo_root, target)
            if os.path.exists(candidate):
                context.append(candidate)
        context = core.expand_paths(context, repo_root=repo_root) \
            if context else None
    findings = core.run_files(files, repo_root=repo_root,
                              context_paths=context)

    baseline_path = baseline or os.path.join(
        repo_root, baseline_mod.DEFAULT_BASENAME)
    entries: List[dict] = []
    if not no_baseline:
        entries = baseline_mod.load_baseline(baseline_path)
    new, matched, stale = baseline_mod.apply_baseline(findings, entries)
    linted = {os.path.relpath(p, repo_root).replace(os.sep, "/")
              for p in files}
    # Only entries for files this run actually linted can be judged
    # stale — a subset run must not advertise other files' entries as
    # prunable.
    stale = [e for e in stale if e["path"] in linted]

    def record(f, status):
        return {"check": f.check, "path": f.path, "line": f.line,
                "col": f.col, "message": f.message, "source": f.source,
                "status": status}

    payload = {
        "version": 1,
        "files": len(files),
        "findings": ([record(f, "new") for f in new]
                     + [record(f, "baselined") for f in matched]),
        "stale_baseline": stale,
    }
    return payload, (1 if new else 0)


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.list_checks:
        for check_id in sorted(core.ALL_CHECK_IDS):
            print(f"{check_id}  {core.ALL_CHECK_IDS[check_id]}")
        return 0
    if not args.paths:
        parser.error("the following arguments are required: paths")

    repo_root = _repo_root()
    if args.write_baseline:
        payload, _ = _gather_or_usage_error(args, allow_corrupt=True)
        if payload is None:
            return 2
        findings = _findings_from(payload)
        # Safe now: gather() already expanded these same paths, so a
        # missing one was reported as the usage error above.
        files = core.expand_paths(args.paths, repo_root=repo_root)
        baseline_path = args.baseline or os.path.join(
            repo_root, baseline_mod.DEFAULT_BASENAME)
        try:
            entries = baseline_mod.load_baseline(baseline_path)
        except ValueError:
            entries = []  # rewriting is the recovery path
        # MERGE, not overwrite: a subset run (jaxlint run_glue.py
        # --write-baseline) must keep other files' entries and every
        # still-matching entry's hand-written justification; only
        # stale entries for the files actually linted are pruned.
        linted = {os.path.relpath(p, repo_root).replace(os.sep, "/")
                  for p in files}
        merged = baseline_mod.merge_entries(entries, findings, linted)
        n = baseline_mod.write_entries(baseline_path, merged)
        print(f"jaxlint: wrote {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} to {baseline_path}")
        return 0

    payload, rc = _gather_or_usage_error(args)
    if payload is None:
        return 2

    if args.format == "json":
        print(json.dumps(payload, indent=2, sort_keys=False))
        return rc

    new = [f for f in payload["findings"] if f["status"] == "new"]
    matched = [f for f in payload["findings"] if f["status"] == "baselined"]
    stale = payload["stale_baseline"]
    for f in new:
        print(f"{f['path']}:{f['line']}:{f['col']}: "
              f"{f['check']} {f['message']}")
    if not args.quiet:
        parts = [f"jaxlint: {len(new)} finding"
                 f"{'' if len(new) == 1 else 's'} in "
                 f"{payload['files']} files"]
        if matched:
            parts.append(f"{len(matched)} baselined")
        if stale:
            parts.append(f"{len(stale)} stale baseline entr"
                         f"{'y' if len(stale) == 1 else 'ies'} "
                         "(run --write-baseline to prune)")
        print("; ".join(parts))
    return rc


def _gather_or_usage_error(args, allow_corrupt: bool = False):
    try:
        return gather(list(args.paths), baseline=args.baseline,
                      no_baseline=args.no_baseline or allow_corrupt,
                      no_context=args.no_context)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return None, 2
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return None, 2


def _findings_from(payload: dict) -> List[core.Finding]:
    return [core.Finding(check=f["check"], path=f["path"], line=f["line"],
                         col=f["col"], message=f["message"],
                         source=f["source"])
            for f in payload["findings"]]


if __name__ == "__main__":
    sys.exit(main())
