"""jaxlint command line (``tools/jaxlint.py`` is the repo-root wrapper).

Exit codes: 0 = clean (baseline-covered findings allowed), 1 = findings,
2 = usage / missing path / malformed baseline.

This module — like the whole analysis package — must never import jax:
the tier-1 gate asserts it, and the pre-commit wrapper runs on boxes
without the accelerator stack.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from bert_pytorch_tpu.analysis import baseline as baseline_mod
from bert_pytorch_tpu.analysis import core


def _repo_root() -> str:
    # analysis/cli.py -> analysis -> bert_pytorch_tpu -> repo root
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="jaxlint",
        description="Pure-AST TPU-hazard linter (docs/static_analysis.md): "
                    "host-sync, recompile, RNG, tracer-leak, and "
                    "lock-discipline checks with stable IDs.")
    parser.add_argument(
        "paths", nargs="*",
        help="files or directories; a bare name that does not exist is "
             "retried under bert_pytorch_tpu/ (so 'serve' works)")
    parser.add_argument(
        "--baseline", default=None,
        help="baseline file (default: <repo>/jaxlint_baseline.json when "
             "present); entries suppress matching findings")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline file: report every finding")
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="write the current UNSUPPRESSED findings to the baseline "
             "file and exit 0 (stale entries are pruned)")
    parser.add_argument(
        "--list-checks", action="store_true",
        help="print every check ID with its description and exit")
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="suppress the summary line (findings still print)")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.list_checks:
        for check_id in sorted(core.ALL_CHECK_IDS):
            print(f"{check_id}  {core.ALL_CHECK_IDS[check_id]}")
        return 0
    if not args.paths:
        parser.error("the following arguments are required: paths")

    repo_root = _repo_root()
    try:
        files = core.expand_paths(args.paths, repo_root=repo_root)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    findings = core.run_files(files, repo_root=repo_root)

    baseline_path = args.baseline or os.path.join(
        repo_root, baseline_mod.DEFAULT_BASENAME)
    entries: List[dict] = []
    if not args.no_baseline:
        try:
            entries = baseline_mod.load_baseline(baseline_path)
        except ValueError as e:
            if not args.write_baseline:
                print(str(e), file=sys.stderr)
                return 2
            # Rewriting is the recovery path for a corrupt baseline.
            entries = []

    if args.write_baseline:
        # MERGE, not overwrite: a subset run (jaxlint run_glue.py
        # --write-baseline) must keep other files' entries and every
        # still-matching entry's hand-written justification; only
        # stale entries for the files actually linted are pruned.
        linted = {os.path.relpath(p, repo_root).replace(os.sep, "/")
                  for p in files}
        merged = baseline_mod.merge_entries(entries, findings, linted)
        n = baseline_mod.write_entries(baseline_path, merged)
        print(f"jaxlint: wrote {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} to {baseline_path}")
        return 0

    new, matched, stale = baseline_mod.apply_baseline(findings, entries)
    # Only entries for files this run actually linted can be judged
    # stale — a subset run must not advertise other files' entries as
    # prunable.
    linted = {os.path.relpath(p, repo_root).replace(os.sep, "/")
              for p in files}
    stale = [e for e in stale if e["path"] in linted]
    for f in new:
        print(f.format())
    if not args.quiet:
        parts = [f"jaxlint: {len(new)} finding"
                 f"{'' if len(new) == 1 else 's'} in {len(files)} files"]
        if matched:
            parts.append(f"{len(matched)} baselined")
        if stale:
            parts.append(f"{len(stale)} stale baseline entr"
                         f"{'y' if len(stale) == 1 else 'ies'} "
                         "(run --write-baseline to prune)")
        print("; ".join(parts))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
