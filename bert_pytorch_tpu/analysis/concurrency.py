"""The concurrency registry: every shared mutable attribute in the
background-thread subsystems, with its declared guard.

This is the single place the codebase states its lock discipline. PR 5
and PR 6 each shipped a review-pass fix for a violation nobody's tests
caught (the unsynchronized JSONL writes; the stale module-global
``_pending_save`` slot) — review memory does not scale, a registry the
linter enforces does. ``analysis/lock_discipline.py`` reads these
entries and flags, per owning module, any access that breaks the
declared discipline (LK501/LK502/LK503).

Three guard kinds:

* ``lock`` — the attribute may only be read or written inside a
  ``with <lock>:`` block (any of the ``locks`` names; a Condition
  wraps its lock, so either spelling of the same mutex is accepted).
  ``allow`` lists functions where unguarded access is fine —
  ``__init__`` (no other thread can hold a reference yet) being the
  canonical case.

* ``frozen`` — the attribute is bound once in ``__init__`` and never
  reassigned; cross-thread sharing is safe because the *binding* is
  immutable (the object it names does its own locking). Any later
  assignment is a violation: it would race every reader.

* ``confined`` — the attribute belongs to ONE thread. ``forbidden_in``
  names the functions that run on *other* threads (thread targets);
  any access there is a violation. This documents single-owner state
  honestly instead of wrapping it in a lock it does not need on the
  hot path.

Entries are keyed by module path suffix (repo-relative, '/'-separated)
and, for instance attributes, the owning class. Keep this registry in
sync with the modules it names — a registered attribute that disappears
costs nothing, an unregistered shared attribute is invisible to the
checker.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple


@dataclass(frozen=True)
class Entry:
    module: str                    # path suffix, e.g. "utils/checkpoint.py"
    attr: str                      # global name, or self.<attr> with cls
    kind: str                      # "lock" | "frozen" | "confined"
    cls: str = ""                  # owning class ("" = module global)
    locks: Tuple[str, ...] = ()    # accepted guard names (kind="lock")
    allow: Tuple[str, ...] = ()    # functions where unguarded access is OK
    forbidden_in: Tuple[str, ...] = field(default=())  # kind="confined"
    why: str = ""                  # one-line rationale (docs + messages)


REGISTRY: Tuple[Entry, ...] = (
    # -- utils/checkpoint.py: async checkpoint writer ----------------------
    # The per-directory pending-write registry is touched by the train
    # loop (save/join) and by every background writer thread's error
    # handler; PR 6's review pass fixed a stale shared slot here.
    Entry("bert_pytorch_tpu/utils/checkpoint.py", "_pending_saves",
          kind="lock", locks=("_pending_lock",),
          why="train loop + background writer threads mutate the "
              "per-directory pending-write map"),
    Entry("bert_pytorch_tpu/utils/checkpoint.py", "_pending_errors",
          kind="lock", locks=("_pending_lock",),
          why="background writer threads append errors the next save/join "
              "raises"),

    # -- data/device_prefetch.py: double-buffered H2D staging --------------
    # The gauges and iterator state are CONSUMER-thread property; the
    # producer communicates only through the Queue and the stop Event
    # (both internally synchronized). Confinement, not locking, is the
    # discipline — a per-step lock on the hot path would buy nothing.
    Entry("bert_pytorch_tpu/data/device_prefetch.py", "_stats",
          cls="DevicePrefetcher", kind="confined",
          forbidden_in=("_produce",),
          why="telemetry gauges read/reset by the consumer (snapshot/"
              "_observe); the producer must stay off them"),
    Entry("bert_pytorch_tpu/data/device_prefetch.py", "_last_h2d_wait_s",
          cls="DevicePrefetcher", kind="confined",
          forbidden_in=("_produce",),
          why="h2d attribution handoff between __next__ and "
              "pop_h2d_wait_s, both consumer-side"),
    Entry("bert_pytorch_tpu/data/device_prefetch.py", "_done",
          cls="DevicePrefetcher", kind="confined",
          forbidden_in=("_produce",),
          why="iterator-exhausted latch owned by the consumer; the "
              "producer signals completion via the queue sentinel"),
    Entry("bert_pytorch_tpu/data/device_prefetch.py", "_thread",
          cls="DevicePrefetcher", kind="confined",
          forbidden_in=("_produce",),
          why="created in __next__ and joined in close(), both "
              "consumer-side; the thread never touches its own handle"),

    # -- telemetry/runner.py: the facade shared with the watchdog ----------
    # The watchdog daemon thread calls self.emit -> self.sink.write_record
    # concurrently with the train loop; safety rests on these bindings
    # never changing after __init__ (JSONLHandler locks its own file).
    Entry("bert_pytorch_tpu/telemetry/runner.py", "sink",
          cls="TrainTelemetry", kind="frozen",
          why="read concurrently by the watchdog thread via emit(); a "
              "rebind would race every background emitter"),
    Entry("bert_pytorch_tpu/telemetry/runner.py", "watchdog",
          cls="TrainTelemetry", kind="frozen",
          why="step_done notes liveness on it from the train loop while "
              "its own thread polls; the binding must be stable"),
    Entry("bert_pytorch_tpu/telemetry/runner.py", "introspect",
          cls="TrainTelemetry", kind="frozen",
          why="emit() tees records into the hub from the train loop AND "
              "background emitters (watchdog) while debug-server HTTP "
              "threads read snapshots; the hub locks itself, the binding "
              "must not move"),
    Entry("bert_pytorch_tpu/telemetry/runner.py", "flight_recorder",
          cls="TrainTelemetry", kind="frozen",
          why="emit() notes records into the ring from every emitting "
              "thread; the recorder locks itself, the binding must not "
              "move"),
    Entry("bert_pytorch_tpu/telemetry/runner.py", "capture",
          cls="TrainTelemetry", kind="frozen",
          why="debug-plane HTTP threads arm it while the train loop "
              "ticks it every step boundary; the controller locks its "
              "own state, the binding must not move"),

    # -- telemetry/introspect.py: train loop vs debug-plane HTTP threads ---
    # The hub's single state dict is the debug plane's ONLY shared
    # mutable state: note_step (train loop) and observe_record (any
    # emitting thread, incl. the watchdog) write while /healthz /statsz
    # /metricsz handlers snapshot it from HTTP worker threads.
    Entry("bert_pytorch_tpu/telemetry/introspect.py", "_state",
          cls="IntrospectionHub", kind="lock", locks=("_lock",),
          why="train loop + background emitters write the live snapshot "
              "while debug-server HTTP threads render it"),
    Entry("bert_pytorch_tpu/telemetry/introspect.py", "capture",
          cls="IntrospectionHub", kind="frozen",
          why="attached once at wiring (TrainTelemetry.__init__, before "
              "the debug server starts) and then read by /profilez and "
              "/statsz HTTP threads; the controller locks its own state"),

    # -- telemetry/sampler.py: the host sampler + capture controller -------
    # The sampler's tallies are written by its own daemon thread per tick
    # and folded by result() after stop() joins — but stop() may race one
    # final in-flight tick, so every touch locks; _sample_once_locked
    # runs with the lock held (the suffix contract). The controller's
    # phase dict is the arm/disarm handshake: any HTTP worker arms it
    # while the owning boundary loop (train step / serve dispatch) ticks
    # it — only the phase state is shared, the trace begin/end and the
    # sampler lifecycle are serialized by boundary-loop ownership.
    Entry("bert_pytorch_tpu/telemetry/sampler.py", "_samples",
          cls="ThreadSampler", kind="lock", locks=("_lock",),
          allow=("_sample_once_locked",),
          why="tick counter bumped by the sampler thread, read by "
              "result() and the _run bound check"),
    Entry("bert_pytorch_tpu/telemetry/sampler.py", "_counts",
          cls="ThreadSampler", kind="lock", locks=("_lock",),
          allow=("_sample_once_locked",),
          why="self-time tallies written per tick, folded by result()"),
    Entry("bert_pytorch_tpu/telemetry/sampler.py", "_stacks",
          cls="ThreadSampler", kind="lock", locks=("_lock",),
          allow=("_sample_once_locked",),
          why="collapsed-stack exemplars written with the tallies"),
    Entry("bert_pytorch_tpu/telemetry/sampler.py", "_state",
          cls="CaptureController", kind="lock", locks=("_lock",),
          why="arm() (any HTTP worker thread) flips idle->armed while "
              "tick() (the owning boundary loop) advances armed->active"
              "->idle and status() snapshots it from /statsz threads"),
    Entry("bert_pytorch_tpu/telemetry/sampler.py", "_sampler",
          cls="CaptureController", kind="lock", locks=("_lock",),
          why="active-phase sampler handle published by tick() and "
              "cleared on collect; shared so status/teardown paths "
              "never see a half-built sampler"),

    # -- telemetry/profiler.py: the process-wide trace latch ---------------
    # jax.profiler traces are a process-wide singleton: one latch under
    # one lock is the whole discipline — begin() refuses (returns False)
    # instead of stacking a second start_trace, whichever plane asks.
    Entry("bert_pytorch_tpu/telemetry/profiler.py", "_TRACE_ACTIVE",
          kind="lock", locks=("_TRACE_LOCK",),
          why="startup windows and on-demand captures (train loop, serve "
              "dispatch loop) race for the one process-wide "
              "jax.profiler trace; the latch decides who wins"),

    # -- telemetry/flightrec.py: every emitting thread vs flush paths ------
    # The ring (and its accounting) is written by the train loop /
    # dispatch thread and every background emitter via note_record,
    # while incident/periodic/atexit/excepthook flushes read it — one
    # lock; *_locked helpers run with it held (the suffix contract).
    Entry("bert_pytorch_tpu/telemetry/flightrec.py", "_ring",
          cls="FlightRecorder", kind="lock", locks=("_lock",),
          allow=("_append_locked", "_payload_locked"),
          why="noted by every emitting thread, drained by flush paths "
              "(incident, periodic, atexit, excepthook)"),
    Entry("bert_pytorch_tpu/telemetry/flightrec.py", "_bytes",
          cls="FlightRecorder", kind="lock", locks=("_lock",),
          allow=("_append_locked", "_payload_locked"),
          why="byte-bound accounting updated with the ring"),
    Entry("bert_pytorch_tpu/telemetry/flightrec.py", "_dropped",
          cls="FlightRecorder", kind="lock", locks=("_lock",),
          allow=("_append_locked", "_payload_locked"),
          why="eviction counter updated with the ring"),
    Entry("bert_pytorch_tpu/telemetry/flightrec.py", "_noted",
          cls="FlightRecorder", kind="lock", locks=("_lock",),
          allow=("_append_locked", "_payload_locked"),
          why="note counter updated with the ring"),
    Entry("bert_pytorch_tpu/telemetry/flightrec.py", "_unflushed",
          cls="FlightRecorder", kind="lock", locks=("_lock",),
          allow=("_append_locked",),
          why="atexit-overwrite guard: reset by flushes, bumped by notes"),
    Entry("bert_pytorch_tpu/telemetry/flightrec.py", "_incident",
          cls="FlightRecorder", kind="lock", locks=("_lock",),
          why="clean-close file-removal decision shared by note/flush/"
              "close paths"),
    Entry("bert_pytorch_tpu/telemetry/flightrec.py", "_closed",
          cls="FlightRecorder", kind="lock", locks=("_lock",),
          why="close() (teardown thread) flips it while emitters note"),
    Entry("bert_pytorch_tpu/telemetry/flightrec.py", "_last_flush",
          cls="FlightRecorder", kind="lock", locks=("_lock",),
          why="periodic-flush cadence shared by every noting thread"),
    Entry("bert_pytorch_tpu/telemetry/flightrec.py", "_last_reason",
          cls="FlightRecorder", kind="lock", locks=("_lock",),
          why="flush bookkeeping read by the atexit guard"),
    Entry("bert_pytorch_tpu/telemetry/flightrec.py", "_flush_seq",
          cls="FlightRecorder", kind="lock", locks=("_lock",),
          why="payload build order, stamped with the ring state it "
              "captured"),
    Entry("bert_pytorch_tpu/telemetry/flightrec.py", "_written_seq",
          cls="FlightRecorder", kind="lock", locks=("_write_lock",),
          why="newest payload on disk: a descheduled periodic flush "
              "must never clobber a newer crash payload"),

    # -- telemetry/collector.py: background loop vs manual passes ----------
    # collect_once may be driven by a test/harness thread while the
    # background loop runs — the lock serializes whole passes, so the
    # target table, tailers, pass counter, and output handle are only
    # ever touched by the pass that holds it. The *_locked helpers run
    # with it held (the suffix contract).
    Entry("bert_pytorch_tpu/telemetry/collector.py", "_targets",
          cls="FleetCollector", kind="lock", locks=("_lock",),
          why="per-target sample state is rewritten every pass; a "
              "manual pass and the loop thread must never interleave"),
    Entry("bert_pytorch_tpu/telemetry/collector.py", "_tails",
          cls="FleetCollector", kind="lock", locks=("_lock",),
          why="tailer offsets advance per pass; interleaved passes "
              "would double-read or skip sink records"),
    Entry("bert_pytorch_tpu/telemetry/collector.py", "_passes",
          cls="FleetCollector", kind="lock", locks=("_lock",),
          allow=("_feed_stitch_locked", "_flush_stitch_locked"),
          why="pass counter bumped by whichever thread runs the pass; "
              "the stitch helpers read it for the orphan-grace clock "
              "with _lock held (the _locked suffix is their contract)"),
    Entry("bert_pytorch_tpu/telemetry/collector.py", "_stitch_pending",
          cls="FleetCollector", kind="lock", locks=("_lock",),
          allow=("_feed_stitch_locked", "_flush_stitch_locked",
                 "_stitch_record"),
          why="pending trace joins fed by whichever thread drains the "
              "tailers and drained by close() on the control thread; "
              "the _locked helpers (and _stitch_record, called only "
              "from _flush_stitch_locked) run with _lock held"),
    Entry("bert_pytorch_tpu/telemetry/collector.py", "_stitch_finalized",
          cls="FleetCollector", kind="lock", locks=("_lock",),
          why="close() may race a manual pass; the flag makes the "
              "force-drain exactly-once"),
    Entry("bert_pytorch_tpu/telemetry/collector.py", "_out_f",
          cls="FleetCollector", kind="lock", locks=("_lock",),
          allow=("_write_locked",),
          why="timeline writes are serialized per pass; stop() closes "
              "the handle while a pass could otherwise be writing"),

    # -- serve/supervisor.py: the supervisor's own heartbeat ---------------
    # Beaten only from poll_once (the monitor thread, or the fake-clock
    # test driving passes); safety rests on the binding being stable —
    # the same contract as the serve dispatch loop's heartbeat.
    Entry("bert_pytorch_tpu/serve/supervisor.py", "_heartbeat",
          cls="Supervisor", kind="frozen",
          why="beaten by the monitor thread's poll pass while start()/"
              "stop() run on control-plane threads; the binding must "
              "never change after __init__"),

    # -- telemetry/sentinels.py: the watchdog's own shared state -----------
    Entry("bert_pytorch_tpu/telemetry/sentinels.py", "_last",
          cls="HeartbeatWatchdog", kind="lock", locks=("_lock",),
          why="written by the train loop (note), read by the watchdog "
              "thread (check)"),
    Entry("bert_pytorch_tpu/telemetry/sentinels.py", "_flagged",
          cls="HeartbeatWatchdog", kind="lock", locks=("_lock",),
          why="re-arm latch shared by note (train loop) and check "
              "(watchdog thread)"),
    Entry("bert_pytorch_tpu/telemetry/sentinels.py", "stalls_flagged",
          cls="HeartbeatWatchdog", kind="lock", locks=("_lock",),
          why="stall counter incremented by the watchdog thread, read by "
              "runners/tests"),

    # -- serve/service.py: HTTP workers vs stage threads vs signal handler -
    Entry("bert_pytorch_tpu/serve/service.py", "_draining",
          cls="ServingService", kind="lock", locks=("_state_lock",),
          why="flipped by begin_drain (signal handler / run_server) while "
              "every HTTP worker reads it in submit/health"),
    Entry("bert_pytorch_tpu/serve/service.py", "_threads",
          cls="ServingService", kind="lock", locks=("_state_lock",),
          why="start/stop rebind the stage-thread list while HTTP workers "
              "read liveness through dispatch_alive for /healthz"),
    Entry("bert_pytorch_tpu/serve/service.py", "_forming",
          cls="ServingService", kind="lock", locks=("_state_lock",),
          why="forming-batch depth gauge written by the assembler stage, "
              "read by /healthz and /metricsz scrape threads"),
    Entry("bert_pytorch_tpu/serve/service.py", "_stage_inflight",
          cls="ServingService", kind="lock", locks=("_state_lock",),
          why="per-stage in-flight batch markers written by the executor "
              "and completion threads, swept by stop()'s fail-or-flush "
              "drain on the caller's thread"),
    Entry("bert_pytorch_tpu/serve/service.py", "_handoff",
          cls="ServingService", kind="frozen",
          why="depth-1 staged-batch queue shared by the assembler and "
              "executor stages (a Queue locks itself); the binding must "
              "never change after __init__"),
    Entry("bert_pytorch_tpu/serve/service.py", "_completed_q",
          cls="ServingService", kind="frozen",
          why="executed-batch queue shared by the executor and completion "
              "stages plus stop()'s flush; the binding must never change "
              "after __init__"),
    Entry("bert_pytorch_tpu/serve/service.py", "_hungry",
          cls="ServingService", kind="frozen",
          why="executor-is-waiting event read by the assembler's "
              "admission window (an Event locks itself); the binding "
              "must never change after __init__"),
    Entry("bert_pytorch_tpu/serve/service.py", "_batches_assembled",
          cls="ServingService", kind="confined",
          forbidden_in=("_execute_loop", "_complete_loop", "_loop",
                        "submit", "health", "metrics_text"),
          why="admit_hold chaos-hook counter owned by the assembler "
              "stage; no other stage or scrape path may touch it"),
    Entry("bert_pytorch_tpu/serve/service.py", "_last_exec_end",
          cls="ServingService", kind="confined",
          forbidden_in=("_assemble_loop", "_complete_loop",
                        "submit", "health", "metrics_text"),
          why="serial-mode executor-gap timestamp owned by the single "
              "device-calling thread (the pipelined executor keeps its "
              "own local)"),

    # -- serve/batcher.py: request FIFO + gauges ---------------------------
    Entry("bert_pytorch_tpu/serve/batcher.py", "_pending",
          cls="Batcher", kind="lock", locks=("_cond", "_lock"),
          allow=("_take_head_task_locked",),
          why="HTTP workers append, the dispatch thread drains; "
              "_take_head_task_locked is called with _cond held (the "
              "_locked suffix is the contract its name states)"),
    Entry("bert_pytorch_tpu/serve/batcher.py", "_inflight",
          cls="Batcher", kind="lock", locks=("_cond", "_lock"),
          allow=("_take_head_task_locked",),
          why="popped-but-unfinished accounting: the dispatch thread "
              "pops/requeues/finishes while stop()'s drain loop reads "
              "unfinished() from another thread (the requeue-during-"
              "drain fix, PR 11)"),
    Entry("bert_pytorch_tpu/serve/batcher.py", "depth_max",
          cls="Batcher", kind="lock", locks=("_cond", "_lock"),
          why="gauge updated under submit/requeue, read by telemetry"),
    Entry("bert_pytorch_tpu/serve/batcher.py", "submitted",
          cls="Batcher", kind="lock", locks=("_cond", "_lock"),
          why="gauge updated by every submitting thread"),
    Entry("bert_pytorch_tpu/serve/batcher.py", "_closed",
          cls="Batcher", kind="lock", locks=("_cond", "_lock"),
          why="close() (drain path) flips it while submit/next_batch "
              "check it"),

    # -- serve/tracing.py: dispatch thread vs /metricsz scrapes ------------
    # The per-task stats map is the request tracer's ONLY shared mutable
    # state: observe() (dispatch thread) and observe_error() (HTTP worker
    # threads) mutate it while metrics_text()/phase_snapshot() (/metricsz
    # and /statsz scrape threads) iterate it.
    Entry("bert_pytorch_tpu/serve/tracing.py", "_tasks",
          cls="TraceCollector", kind="lock", locks=("_lock",),
          why="dispatch-thread observe + HTTP-worker observe_error mutate "
              "the per-task aggregates while scrape threads render "
              "/metricsz and /statsz from them"),

    # -- serve/service.py: the dispatch loop's heartbeat -------------------
    # The Heartbeat object itself is only ever beaten by one thread at a
    # time (start() before the loop thread exists, then the loop, then
    # stop() after the join); safety rests on the binding being stable.
    Entry("bert_pytorch_tpu/serve/service.py", "_heartbeat",
          cls="ServingService", kind="frozen",
          why="beaten by the dispatch loop while stop()/start() run on "
              "other threads; the binding must never change after "
              "__init__ (beats are serialized by the thread lifecycle)"),
    Entry("bert_pytorch_tpu/serve/service.py", "capture",
          cls="ServingService", kind="frozen",
          why="armed by /profilez HTTP workers while the dispatch/"
              "completion loop ticks it at the same boundary the "
              "heartbeat rides; the controller locks its own state, the "
              "binding must not move"),

    # -- serve/stats.py: dispatch thread vs /statsz scrapes ----------------
    Entry("bert_pytorch_tpu/serve/stats.py", "total_requests",
          cls="ServeTelemetry", kind="lock", locks=("_lock",),
          why="observe_batch (dispatch thread) increments while HTTP "
              "workers snapshot for /statsz"),
    Entry("bert_pytorch_tpu/serve/stats.py", "total_batches",
          cls="ServeTelemetry", kind="lock", locks=("_lock",),
          why="same writer/reader split as total_requests"),
    Entry("bert_pytorch_tpu/serve/stats.py", "total_errors",
          cls="ServeTelemetry", kind="lock", locks=("_lock",),
          why="observe_error is called from HTTP worker threads too"),
    Entry("bert_pytorch_tpu/serve/stats.py", "_cold_start",
          cls="ServeTelemetry", kind="lock", locks=("_lock",),
          why="engine-startup stats written once by observe_cold_start "
              "(the thread that ran warmup) while HTTP workers read them "
              "via snapshot() for /statsz"),
    Entry("bert_pytorch_tpu/serve/stats.py", "_tracer",
          cls="ServeTelemetry", kind="lock", locks=("_lock",),
          why="attached once by the service before dispatch starts, read "
              "by snapshot()/finish() on scrape and shutdown threads"),

    # -- serve/router.py: scrape thread vs router worker threads -----------
    # One lock guards the whole router: the background scrape rewrites
    # replica health while every concurrent request thread balances on
    # it (_admit/_pick_hedge), feeds the latency history, and bumps the
    # window/run counters; dispatch/hedge worker threads release
    # in-flight slots through the same table.
    Entry("bert_pytorch_tpu/serve/router.py", "_replicas",
          cls="Router", kind="lock", locks=("_lock",),
          allow=("_window_record_locked",),
          why="scrape thread rewrites health/queue gauges while request "
              "threads pick replicas and mutate inflight counts; "
              "_window_record_locked runs with _lock held (the _locked "
              "suffix is its contract)"),
    Entry("bert_pytorch_tpu/serve/router.py", "_latencies",
          cls="Router", kind="lock", locks=("_lock",),
          why="dispatch worker threads append successful-request "
              "latencies while request threads read the hedge-threshold "
              "percentile from them"),
    Entry("bert_pytorch_tpu/serve/router.py", "_win",
          cls="Router", kind="lock", locks=("_lock",),
          why="window accumulator: every request thread folds its "
              "outcome in; flush_window (any thread) swaps it out"),
    Entry("bert_pytorch_tpu/serve/router.py", "_run",
          cls="Router", kind="lock", locks=("_lock",),
          why="run-level accumulator shared by request threads and "
              "/statsz snapshot readers"),
    Entry("bert_pytorch_tpu/serve/router.py", "_trace_seq",
          cls="Router", kind="lock", locks=("_lock",),
          why="trace-id sequence bumped by every concurrent request "
              "thread in _mint_trace; a duplicate id would stitch two "
              "requests into one tree"),
    Entry("bert_pytorch_tpu/serve/router.py", "_heartbeat",
          cls="Router", kind="frozen",
          why="beaten from the scrape thread (plus one final flush in "
              "stop() after that thread is joined); Heartbeat.beat is "
              "single-owner, so safety rests on the binding never "
              "moving"),
    Entry("bert_pytorch_tpu/serve/router.py", "_split",
          cls="Router", kind="lock", locks=("_lock",),
          why="rollout controller installs/widens/clears the canary "
              "split while every request thread reads it for cohort "
              "assignment and folds outcomes into its accumulators; "
              "split_window swaps the cohorts out from the observe "
              "loop's thread"),
    Entry("bert_pytorch_tpu/serve/router.py", "_version_requests",
          cls="Router", kind="lock", locks=("_lock",),
          why="per-version counters bumped by every admitting/hedging "
              "request thread while /metricsz and /statsz scrape "
              "threads snapshot them"),
    Entry("bert_pytorch_tpu/serve/router.py", "_next_target_index",
          cls="Router", kind="lock", locks=("_lock",),
          why="monotone target-index mint bumped by add_target "
              "(autoscaler thread) while the scrape thread and request "
              "threads walk the table it indexes; a reused index would "
              "alias two replicas' stats"),

    # -- serve/engine.py: the swappable params slot ------------------------
    # _swap_lock makes (spec.params, serving_version, _swap_epoch) one
    # atomic unit: swap_params flips all three in one acquisition while
    # the executor thread captures all three in one acquisition — a
    # mixed read (new params, old version) is the torn serve the
    # _torn_serves counter exists to falsify.
    Entry("bert_pytorch_tpu/serve/engine.py", "serving_version",
          cls="InferenceEngine", kind="lock", locks=("_swap_lock",),
          why="swap_params (control/HTTP thread) flips it with the "
              "params reference while the executor thread captures "
              "both for the forward pass and /statsz reports it"),
    Entry("bert_pytorch_tpu/serve/engine.py", "_swap_epoch",
          cls="InferenceEngine", kind="lock", locks=("_swap_lock",),
          why="bumped per flip; the executor re-reads it after the "
              "forward pass to detect a torn capture"),
    Entry("bert_pytorch_tpu/serve/engine.py", "_swaps",
          cls="InferenceEngine", kind="lock", locks=("_swap_lock",),
          why="swap counter written by swap_params, read by "
              "swap_stats() from scrape threads"),
    Entry("bert_pytorch_tpu/serve/engine.py", "_torn_serves",
          cls="InferenceEngine", kind="lock", locks=("_swap_lock",),
          why="executor increments on a detected torn capture while "
              "scrape threads read it for /statsz (the rollout's "
              "zero-tolerance gate)"),
    Entry("bert_pytorch_tpu/serve/engine.py", "_swap_inflight",
          cls="InferenceEngine", kind="lock", locks=("_swap_lock",),
          why="single-swap admission flag: concurrent /swapz callers "
              "race to set it; the loser gets SwapBusy (409)"),

    # -- serve/supervisor.py: monitor thread vs control-plane callers ------
    # The replica table (and every _Replica field reached through it) is
    # written by the monitor thread's poll pass while start/stop/status
    # run on the caller's thread.
    Entry("bert_pytorch_tpu/serve/supervisor.py", "_replicas",
          cls="Supervisor", kind="lock", locks=("_lock",),
          why="monitor thread reaps/restarts/kills replicas while "
              "start()/stop()/status() read and mutate the same table "
              "from control-plane threads"),
    Entry("bert_pytorch_tpu/serve/supervisor.py", "_next_index",
          cls="Supervisor", kind="lock", locks=("_lock",),
          why="monotone replica-index mint bumped by add_replica "
              "(autoscaler thread) while the monitor thread walks the "
              "table; an index reuse would alias a drained replica's "
              "heartbeat/postmortem baselines onto a fresh incarnation"),

    # -- serve/autoscaler.py: control loop vs status readers ---------------
    # The controller's decision state (evidence counters, cooldown
    # bookkeeping, the membership chain tail) is mutated by the loop
    # thread's tick() while status() snapshots it from the chaos
    # harness / HTTP threads; the fleet adapter's pending-drain list is
    # shared between tick() (reap) and whatever thread began the drain.
    Entry("bert_pytorch_tpu/serve/autoscaler.py", "_pending_drains",
          cls="ElasticFleet", kind="lock", locks=("_lock",),
          why="two-phase drains: begin_drain appends while tick()'s "
              "reap_drained sweeps and draining() is read from status "
              "threads"),
    Entry("bert_pytorch_tpu/serve/autoscaler.py", "_reds",
          cls="AutoscalerController", kind="lock", locks=("_lock",),
          why="consecutive-red evidence counter bumped/reset by tick() "
              "while status() reads it"),
    Entry("bert_pytorch_tpu/serve/autoscaler.py", "_greens",
          cls="AutoscalerController", kind="lock", locks=("_lock",),
          why="consecutive-green evidence counter bumped/reset by "
              "tick() while status() reads it"),
    Entry("bert_pytorch_tpu/serve/autoscaler.py", "_ticks",
          cls="AutoscalerController", kind="lock", locks=("_lock",),
          why="tick counter bumped by the loop thread, read by "
              "status()"),
    Entry("bert_pytorch_tpu/serve/autoscaler.py", "_scale_ups",
          cls="AutoscalerController", kind="lock", locks=("_lock",),
          why="action counter bumped by tick(), read by status()"),
    Entry("bert_pytorch_tpu/serve/autoscaler.py", "_scale_downs",
          cls="AutoscalerController", kind="lock", locks=("_lock",),
          why="action counter bumped by tick(), read by status()"),
    Entry("bert_pytorch_tpu/serve/autoscaler.py", "_last_scale_at",
          cls="AutoscalerController", kind="lock", locks=("_lock",),
          allow=("_cooldown_remaining",),
          why="cooldown anchor written on every scaling action, read by "
              "the next tick's cooldown check (_cooldown_remaining runs "
              "with _lock held — tick() only calls it inside the "
              "decision block)"),
    Entry("bert_pytorch_tpu/serve/autoscaler.py", "_last_direction",
          cls="AutoscalerController", kind="lock", locks=("_lock",),
          why="thrash detection reads the previous action's direction "
              "while tick() rewrites it"),
    Entry("bert_pytorch_tpu/serve/autoscaler.py", "_last_after",
          cls="AutoscalerController", kind="lock", locks=("_lock",),
          why="membership chain tail (exogenous-drift baseline) carried "
              "between ticks, read by status()"),
    Entry("bert_pytorch_tpu/serve/autoscaler.py", "_last_emitted",
          cls="AutoscalerController", kind="lock", locks=("_lock",),
          why="hold-dedup key carried between ticks on the loop "
              "thread; guarded because status readers share the lock"),
    Entry("bert_pytorch_tpu/serve/autoscaler.py", "_thrash",
          cls="AutoscalerController", kind="lock", locks=("_lock",),
          why="the structurally-impossible counter (zero-tolerance "
              "gate): bumped by tick(), asserted on by the chaos "
              "harness via status()"),
    Entry("bert_pytorch_tpu/serve/autoscaler.py", "_last_error",
          cls="AutoscalerController", kind="lock", locks=("_lock",),
          why="loop-thread actuation/scrape errors surfaced to "
              "status() readers"),

    # -- serve/rollout.py: observe loop vs status readers ------------------
    # One lock guards the whole stage state: observe() runs on a
    # scheduler thread while status() is read from HTTP handlers and
    # start() from the control plane.
    Entry("bert_pytorch_tpu/serve/rollout.py", "_stage",
          cls="RolloutController", kind="lock", locks=("_lock",),
          why="observe() advances it while status() renders it from "
              "HTTP handler threads"),
    Entry("bert_pytorch_tpu/serve/rollout.py", "_greens",
          cls="RolloutController", kind="lock", locks=("_lock",),
          why="consecutive-green counter bumped/reset by observe() "
              "while status() reads it"),
    Entry("bert_pytorch_tpu/serve/rollout.py", "_state",
          cls="RolloutController", kind="lock", locks=("_lock",),
          why="idle/canary/promoted/rolled_back transitions from "
              "start()/observe() while status() and the next observe() "
              "check it"),
    Entry("bert_pytorch_tpu/serve/rollout.py", "_windows",
          cls="RolloutController", kind="lock", locks=("_lock",),
          why="window counter bumped per observation, read by "
              "status()"),

    # -- serve/registry.py: manifest cache ---------------------------------
    Entry("bert_pytorch_tpu/serve/registry.py", "_cache",
          cls="ModelRegistry", kind="lock", locks=("_lock",),
          allow=("_read_locked", "_write_locked"),
          why="publish/set_state write-through while list_versions/get "
              "read from rollout, CLI, and HTTP threads; disk is the "
              "source of truth, the cache only skips re-reads"),

    # -- ops/pallas/autotune.py: the geometry-winners registry -------------
    # The process-global winners table is written by the serve engine's
    # startup (load/measure) and read at TRACE time by every kernel
    # call site; a pipelined dispatch plane traces from its executor
    # thread while a test/control thread may load or clear winners, so
    # every touch goes through the module lock.
    Entry("bert_pytorch_tpu/ops/pallas/autotune.py", "_winners",
          kind="lock", locks=("_lock",),
          why="engine startup loads/measures winners while kernel trace "
              "sites look geometry up from whichever thread traces"),

    # -- utils/logging.py: the JSONL sink background emitters write --------
    Entry("bert_pytorch_tpu/utils/logging.py", "_f",
          cls="JSONLHandler", kind="lock", locks=("_lock",),
          allow=("__init__",),
          why="watchdog/shard-retry/async-writer threads emit records "
              "concurrently with the train loop (PR 5 review fix)"),
)


def entries_for(rel_path: str) -> Tuple[Entry, ...]:
    """Registry entries whose module suffix matches ``rel_path``."""
    rel = rel_path.replace("\\", "/")
    return tuple(e for e in REGISTRY if rel.endswith(e.module))
