"""CT801/CT802 — cross-module contract drift.

Two contracts in this codebase span modules and were previously held by
review memory only:

* **CT801** telemetry kinds: every record the sinks write carries a
  ``kind`` that selects its required-key set in
  ``telemetry/schema.py KIND_REQUIRED_KEYS``. The schema lint catches
  an off-registry kind only AFTER a run produced the artifact; this
  check catches it at the emit site. Statically extracted emit sites:
  dict literals with a ``"kind"`` key and ``record["kind"] = "..."``
  assignments, anywhere in the program; the registry is read by PARSING
  the program's ``telemetry/schema.py`` (never importing it), so the
  check follows whatever the schema module actually declares. Skipped
  entirely when no schema module is in the program (single-file fixture
  runs).

* **CT802** argparse flags: a flag declared but never read is dead
  weight that misleads operators ("I set it and nothing changed"); a
  namespace attribute read but never declared is an AttributeError
  waiting for the first caller that exercises the path. Declarations =
  every ``add_argument``/``add_subparsers`` dest in the program (first
  long option, argparse's dash-to-underscore mapping, explicit
  ``dest=``); reads = ``args.<dest>`` loads, ``getattr(args, "<dest>"
  [, default])``, f-string getattrs matched as patterns
  (``getattr(args, f"{task}_checkpoint")`` reads every
  ``*_checkpoint`` dest), plus — deliberately lenient — any bare string
  literal equal to the dest elsewhere in the program (config-file key
  lists, ``require_args([...])``). ``args.<x> = ...`` stores count as
  programmatic declarations. A fully dynamic ``getattr(args, var)`` or
  ``vars(args)`` anywhere disables only the declared-but-never-read
  direction (it could read anything); read-but-never-declared keeps
  working.

Both checks are only meaningful whole-program; the CLI therefore parses
the canonical target set as context even for subset runs (findings are
still reported only for the requested files).
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, List, Optional, Set, Tuple

from bert_pytorch_tpu.analysis.core import Finding, Module
from bert_pytorch_tpu.analysis.graph import Program

CHECKS = {
    "CT801": "telemetry record kind not registered in telemetry/schema.py "
             "KIND_REQUIRED_KEYS",
    "CT802": "argparse flag declared but never read, or namespace "
             "attribute read but never declared",
}

_SCHEMA_SUFFIX = "telemetry/schema.py"
_NAMESPACE_NAMES = ("args",)
# Namespace attributes that are argparse/stdlib machinery, not flags.
_NAMESPACE_INTERNAL = {"__dict__", "__class__"}


# -- CT801 ----------------------------------------------------------------

def _registered_kinds(program: Program) -> Optional[Set[str]]:
    """Keys of KIND_REQUIRED_KEYS across every schema module in the
    program (fixtures bring their own mini schema next to the real one);
    None when the program holds no schema module at all."""
    kinds: Optional[Set[str]] = None
    for module in program.modules:
        if not module.rel.endswith(_SCHEMA_SUFFIX):
            continue
        for stmt in module.tree.body:
            if isinstance(stmt, ast.Assign) \
                    and any(isinstance(t, ast.Name)
                            and t.id == "KIND_REQUIRED_KEYS"
                            for t in stmt.targets) \
                    and isinstance(stmt.value, ast.Dict):
                kinds = set() if kinds is None else kinds
                kinds |= {k.value for k in stmt.value.keys
                          if isinstance(k, ast.Constant)
                          and isinstance(k.value, str)}
    return kinds


def _emit_sites(module: Module) -> List[Tuple[ast.AST, str]]:
    sites: List[Tuple[ast.AST, str]] = []
    for node in module.nodes:
        if isinstance(node, ast.Dict):
            for key, value in zip(node.keys, node.values):
                if isinstance(key, ast.Constant) and key.value == "kind" \
                        and isinstance(value, ast.Constant) \
                        and isinstance(value.value, str):
                    sites.append((value, value.value))
        elif isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.slice, ast.Constant) \
                        and t.slice.value == "kind":
                    sites.append((node.value, node.value.value))
    return sites


def _check_kinds(program: Program) -> List[Finding]:
    kinds = _registered_kinds(program)
    if kinds is None:
        return []
    findings: List[Finding] = []
    for module in program.modules:
        if module.rel not in program.target_rels \
                or module.rel.endswith(_SCHEMA_SUFFIX):
            continue
        for node, kind in _emit_sites(module):
            if kind not in kinds:
                findings.append(module.finding(
                    "CT801", node,
                    f"record kind '{kind}' is not registered in "
                    "telemetry/schema.py KIND_REQUIRED_KEYS — the "
                    "schema lint will reject the artifact this emits; "
                    "register the kind (with its required keys) first"))
    return findings


# -- CT802 ----------------------------------------------------------------

def _dest_of_add_argument(call: ast.Call) -> Optional[Tuple[str, bool]]:
    """(dest, is_flag) for an add_argument call with literal options;
    None when the options are dynamic. argparse semantics: explicit
    dest= wins, else the first long option, else the first option."""
    for kw in call.keywords:
        if kw.arg == "dest":
            if isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value, True
            return None
    options = [a.value for a in call.args
               if isinstance(a, ast.Constant) and isinstance(a.value, str)]
    if not options:
        return None
    flags = [o for o in options if o.startswith("-")]
    if not flags:
        return options[0], False  # positional: dest is the name itself
    long_flags = [o for o in flags if o.startswith("--")]
    chosen = (long_flags or flags)[0].lstrip("-")
    return chosen.replace("-", "_"), True


class _FlagScan:
    def __init__(self) -> None:
        self.declared: Dict[str, Tuple[Module, ast.AST, bool]] = {}
        self.read: Set[str] = set()
        self.read_sites: List[Tuple[Module, ast.AST, str]] = []
        self.stored: Set[str] = set()
        self.literals: Set[str] = set()
        self.patterns: Set[str] = set()
        self.wildcard_read = False


def _scan_module(module: Module, scan: _FlagScan) -> None:
    # A declaration's own strings (options, help text) are not read
    # evidence for itself; ast.walk visits parents first, so the skip
    # set is filled before its constants are reached.
    skip_literals: Set[int] = set()
    for node in module.nodes:
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) \
                    and func.attr in ("add_argument", "add_subparsers"):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Constant):
                        skip_literals.add(id(sub))
                hit = _dest_of_add_argument(node) \
                    if func.attr == "add_argument" else None
                if func.attr == "add_subparsers":
                    for kw in node.keywords:
                        if kw.arg == "dest" \
                                and isinstance(kw.value, ast.Constant):
                            hit = (kw.value.value, True)
                if hit is not None:
                    dest, is_flag = hit
                    scan.declared.setdefault(dest, (module, node, is_flag))
            elif isinstance(func, ast.Name) and func.id == "getattr" \
                    and node.args and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in _NAMESPACE_NAMES \
                    and len(node.args) >= 2:
                key = node.args[1]
                if isinstance(key, ast.Constant) \
                        and isinstance(key.value, str):
                    scan.read.add(key.value)
                elif isinstance(key, ast.JoinedStr):
                    pattern = "".join(
                        v.value if isinstance(v, ast.Constant) else "*"
                        for v in key.values)
                    scan.patterns.add(pattern)
                elif len(node.args) == 2:
                    # A dynamic 2-arg getattr could be the sole reader
                    # of anything: soundness requires the wildcard. The
                    # 3-arg form tolerates absence and its name always
                    # originates from a literal somewhere (require_args
                    # lists, config-file key tables) that the literal
                    # evidence below already credits.
                    scan.wildcard_read = True
            elif isinstance(func, ast.Name) and func.id == "vars" \
                    and node.args and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in _NAMESPACE_NAMES:
                scan.wildcard_read = True
        elif isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in _NAMESPACE_NAMES \
                and node.attr not in _NAMESPACE_INTERNAL:
            if isinstance(node.ctx, ast.Load):
                scan.read.add(node.attr)
                scan.read_sites.append((module, node, node.attr))
            else:
                scan.stored.add(node.attr)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and id(node) not in skip_literals:
            scan.literals.add(node.value)


def _check_flags(program: Program) -> List[Finding]:
    scan = _FlagScan()
    for module in program.modules:
        _scan_module(module, scan)

    findings: List[Finding] = []
    if not scan.wildcard_read:
        for dest, (module, node, is_flag) in sorted(scan.declared.items()):
            if module.rel not in program.target_rels:
                continue
            if dest in scan.read or dest in scan.stored:
                continue
            # Lenient literal evidence: the dest named anywhere else
            # (require_args lists, config-file key tables, subprocess
            # command lines passing the flag spelling) counts.
            if dest in scan.literals or f"--{dest}" in scan.literals:
                continue
            if any(fnmatch.fnmatchcase(dest, p) for p in scan.patterns):
                continue
            spelled = f"--{dest}" if is_flag else dest
            findings.append(module.finding(
                "CT802", node,
                f"flag '{spelled}' is declared but its value is never "
                "read anywhere in the program — wire it up or delete "
                "it (a knob that does nothing misleads operators)"))
    if not scan.declared:
        # No argparse anywhere in the program: 'args' is then just a
        # conventional parameter name of unknown type (fixtures,
        # out-of-repo runs) and the read direction has no registry to
        # judge against.
        return findings
    declared_or_stored = set(scan.declared) | scan.stored
    for module, node, attr in scan.read_sites:
        if module.rel not in program.target_rels:
            continue
        if attr in declared_or_stored:
            continue
        findings.append(module.finding(
            "CT802", node,
            f"'args.{attr}' is read but no parser in the program "
            "declares it (and nothing assigns it) — an AttributeError "
            "waiting for the first caller on this path"))
    return findings


def check_program(program: Program, registry=None) -> List[Finding]:
    return _check_kinds(program) + _check_flags(program)
