"""Shared analyzer machinery: parsed-module model, suppression comments,
check registry, and the file-walking entry points.

Everything here is stdlib-only (ast/re/os) — see the package docstring
for why that is a hard constraint.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Iterable, List, Optional

# Inline suppression: a trailing comment on the flagged line (or a comment
# on its own line DIRECTLY above, for statements too long to annotate
# inline). Multiple IDs comma-separate. An unknown ID is itself an error
# (JL000) — a typo'd suppression must not silently stop suppressing.
DISABLE_RE = re.compile(r"#\s*jaxlint:\s*disable=([A-Za-z0-9_,\s]+)")
# Hot-path marker: a comment on (or directly above) a ``def`` line opts
# that function into the host-sync scan even when it is not reachable
# from a ``tele.timed`` loop in the same module (host_sync.py).
HOT_RE = re.compile(r"#\s*jaxlint:\s*hot\b")

JL_BAD_ID = "JL000"
JL_PARSE = "JL001"


@dataclass(frozen=True)
class Finding:
    check: str
    path: str          # path as reported (relative when possible)
    line: int
    col: int
    message: str
    source: str        # stripped source of the flagged line (baseline key)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.check} {self.message}"


class Module:
    """One parsed source file plus the derived maps every check shares:
    parent links, import-alias resolution, suppression/hot comment lines."""

    def __init__(self, path: str, text: str, rel: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        # One BFS walk, shared by every check: ~25 separate ast.walk
        # passes per module dominated the gate's runtime otherwise.
        self.nodes = list(ast.walk(self.tree))
        self.parents: dict = {}
        for parent in self.nodes:
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.aliases = self._import_aliases()
        self.suppressions, self.bad_ids, self.hot_lines = \
            self._scan_comments()

    # -- comments --------------------------------------------------------

    def _comment_tokens(self):
        """(line, text) of every actual COMMENT token — a ``# jaxlint:``
        marker quoted inside a docstring must not count."""
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except tokenize.TokenError:
            return  # ast.parse already succeeded; be permissive here

    def _scan_comments(self):
        suppressions: dict = {}   # line -> set of IDs
        bad: list = []            # (line, bad_id)
        hot_lines: set = set()
        for lineno, comment in self._comment_tokens():
            if HOT_RE.search(comment):
                hot_lines.add(lineno)
            m = DISABLE_RE.search(comment)
            if not m:
                continue
            ids = {tok.strip() for tok in m.group(1).split(",") if tok.strip()}
            for check_id in ids:
                # ALL_CHECK_IDS is a module global populated at import
                # time; Modules are only built at analysis time, after.
                if check_id not in ALL_CHECK_IDS:
                    bad.append((lineno, check_id))
            suppressions[lineno] = ids
        return suppressions, bad, hot_lines

    def suppressed(self, line: int, check_id: str) -> bool:
        for at in (line, line - 1):
            ids = self.suppressions.get(at)
            if ids and check_id in ids:
                return True
        return False

    def source_line(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, check: str, node: ast.AST, message: str) -> Finding:
        return Finding(check=check, path=self.rel, line=node.lineno,
                       col=node.col_offset, message=message,
                       source=self.source_line(node.lineno))

    # -- name resolution -------------------------------------------------

    def _import_aliases(self) -> dict:
        """Map local names to canonical dotted roots: ``import numpy as
        np`` -> {"np": "numpy"}; ``from jax import random as jrandom`` ->
        {"jrandom": "jax.random"}."""
        aliases: dict = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def dotted(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of an attribute chain / name, resolved
        through the module's import aliases; None for anything else."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id, node.id)
        parts.append(root)
        return ".".join(reversed(parts))


# -- check registry ------------------------------------------------------

# The canonical lint target set — what the tier-1 gate, the acceptance
# command, and commit hooks all mean by "lint the repo". Also the
# context the CLI parses for the whole-program tier on subset runs.
JAXLINT_TARGETS = ("bert_pytorch_tpu", "run_glue.py", "run_ner.py",
                   "run_pretraining.py", "run_server.py", "run_squad.py",
                   "run_swag.py", "tools")


def _checks():
    # Local imports keep core importable before the check modules exist
    # in partial environments, and break the package import cycle.
    from bert_pytorch_tpu.analysis import (host_sync, lock_discipline,
                                           recompile, rng, tracer_leak)
    return (host_sync, recompile, rng, tracer_leak, lock_discipline)


def _program_checks():
    """The shardlint tier: whole-program checks over the cross-module
    symbol/call graph (graph.Program) instead of one file at a time."""
    from bert_pytorch_tpu.analysis import contracts, donation, sharding
    return (sharding, donation, contracts)


def all_check_ids() -> dict:
    """{check_id: one-line description} over every registered check,
    plus the analyzer's own JL error codes."""
    ids = {
        JL_BAD_ID: "unknown check ID in a jaxlint disable comment",
        JL_PARSE: "file failed to parse",
    }
    for mod in _checks() + _program_checks():
        ids.update(mod.CHECKS)
    return ids


# Computed once at import; the package __init__ re-exports it and the
# suppression parser checks typo'd IDs against it.
ALL_CHECK_IDS = all_check_ids()


def run_module(module: Module, registry=None, program=None) -> List[Finding]:
    """The per-file check tier. With ``program``, checks that can use
    the cross-module graph (HS101's hot-region propagation) may emit
    findings in OTHER modules; suppression is then looked up in the
    module that owns the flagged line, not the one being scanned."""
    findings: List[Finding] = []
    for line, bad_id in module.bad_ids:
        findings.append(Finding(
            check=JL_BAD_ID, path=module.rel, line=line, col=0,
            message=f"unknown check ID {bad_id!r} in disable comment "
                    f"(known: {', '.join(sorted(ALL_CHECK_IDS))})",
            source=module.source_line(line)))
    for mod in _checks():
        for f in mod.check(module, registry=registry, program=program):
            # JL000 is deliberately unsuppressable; everything else
            # honors the inline disable comment in its OWN module.
            owner = module
            if program is not None and f.path != module.rel:
                owner = program.by_rel.get(f.path, module)
            if f.check == JL_BAD_ID or not owner.suppressed(f.line, f.check):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.check))
    return findings


def _parse_module(path: str, repo_root: Optional[str]):
    """(Module, None) or (None, JL001 Finding)."""
    rel = os.path.relpath(path, repo_root) if repo_root else path
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
        return Module(path, text, rel), None
    except (SyntaxError, ValueError) as e:
        return None, Finding(
            check=JL_PARSE, path=rel.replace(os.sep, "/"),
            line=getattr(e, "lineno", 0) or 0, col=0,
            message=f"parse error: {e}", source="")


def run_files(paths: Iterable[str], repo_root: Optional[str] = None,
              registry=None,
              context_paths: Optional[Iterable[str]] = None
              ) -> List[Finding]:
    """Analyze the given FILES (no directory expansion — see run_paths).
    Unparseable files produce a JL001 finding instead of crashing the
    run: a syntax error in lint-scope code must fail the gate loudly.

    ``context_paths`` (the CLI passes the canonical target set) are
    parsed INTO the whole-program graph but produce no findings of
    their own — the shardlint tier (SD6xx/DN701/CT8xx) and HS101's
    cross-module propagation need the full program to judge a subset
    run correctly; a context file that fails to parse is silently
    skipped (it fails loudly when it is itself a target)."""
    findings: List[Finding] = []
    modules = []
    target_rels = set()
    seen = set()
    for path in paths:
        seen.add(os.path.abspath(path))
        module, err = _parse_module(path, repo_root)
        if err is not None:
            findings.append(err)
            continue
        modules.append(module)
        target_rels.add(module.rel)
    for path in context_paths or ():
        if os.path.abspath(path) in seen:
            continue
        seen.add(os.path.abspath(path))
        module, err = _parse_module(path, repo_root)
        if module is not None:
            modules.append(module)

    from bert_pytorch_tpu.analysis.graph import Program
    program = Program(modules, target_rels=target_rels)

    for module in modules:
        if module.rel not in target_rels:
            continue
        for f in run_module(module, registry=registry, program=program):
            # Cross-module propagation (HS101) can land a finding in a
            # context-only file; like the program tier below, subset runs
            # report only requested paths (the canonical gate targets
            # every file, so nothing is lost there).
            if f.path in target_rels:
                findings.append(f)
    for mod in _program_checks():
        for f in mod.check_program(program, registry=registry):
            if f.path not in target_rels:
                continue
            owner = program.by_rel.get(f.path)
            if owner is None or not owner.suppressed(f.line, f.check):
                findings.append(f)

    # Cross-module propagation can surface the same finding from two
    # scanning modules: dedupe, then order.
    unique, emitted = [], set()
    for f in findings:
        if f not in emitted:
            emitted.add(f)
            unique.append(f)
    unique.sort(key=lambda f: (f.path, f.line, f.col, f.check))
    return unique


def expand_paths(args: Iterable[str], repo_root: Optional[str] = None
                 ) -> List[str]:
    """Resolve CLI path arguments to a sorted .py file list. Directories
    recurse (skipping __pycache__ and hidden dirs); a bare name that does
    not exist is retried under ``<repo_root>/bert_pytorch_tpu/`` so
    ``jaxlint serve`` means the serving subsystem from anywhere."""
    files: List[str] = []
    for arg in args:
        path = arg
        if not os.path.exists(path) and repo_root:
            for base in (repo_root, os.path.join(repo_root,
                                                 "bert_pytorch_tpu")):
                candidate = os.path.join(base, arg)
                if os.path.exists(candidate):
                    path = candidate
                    break
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(filenames) if n.endswith(".py"))
        elif os.path.isfile(path):
            files.append(path)
        else:
            raise FileNotFoundError(f"jaxlint: no such path: {arg}")
    # De-duplicate while preserving order (bert_pytorch_tpu + serve both
    # naming serve/ files must not double-report).
    seen = set()
    unique = []
    for f in files:
        key = os.path.abspath(f)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return unique


def run_paths(args: Iterable[str], repo_root: Optional[str] = None,
              registry=None,
              context: Optional[Iterable[str]] = None) -> List[Finding]:
    context_files = None
    if context:
        try:
            context_files = expand_paths(context, repo_root)
        except FileNotFoundError:
            context_files = None  # partial checkouts: lint what exists
    return run_files(expand_paths(args, repo_root), repo_root=repo_root,
                     registry=registry, context_paths=context_files)
