"""DN701 — donated buffers read after the jitted call.

``jax.jit(..., donate_argnums=...)`` lets XLA reuse an input buffer for
an output — the reason every train step donates its state. The contract
is one-way: after the call dispatches, the donated argument's buffer is
GONE. Reading it afterwards raises on TPU ("Invalid buffer passed") but
— worse — can silently read stale bytes under some backends/transfer
paths, and the error only fires for the shapes/donation layout that
actually alias. The safe idiom rebinds the name from the call's own
result (``state, metrics = step(state, batch)``); anything else that
touches the name afterwards is flagged.

Lexical approximation, deliberately: for each call of a name bound to a
``jax.jit``/``pjit`` result with literal ``donate_argnums``/
``donate_argnames`` (resolved through wrapper calls the way RC201 does
— ``monitor.wrap(jax.jit(f, donate_argnums=(0, 1)), "train_step")``
records the OUTER assignment's name), every donated bare-Name argument
must either be rebound by the call's own assignment targets, or never
be loaded again later in the enclosing function (by line order; a
re-assignment to the name before the load clears the hazard). Loops are
line-ordered too, so the next-iteration re-donation of an un-rebound
name is out of reach — the rebind-or-never-touch idiom this check
enforces prevents it anyway.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from bert_pytorch_tpu.analysis.core import Finding, Module
from bert_pytorch_tpu.analysis.graph import Program

CHECKS = {
    "DN701": "argument donated to a jitted call (donate_argnums) and "
             "read after the call",
}

_JIT_CALLS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit", "pjit",
              "jit"}


@dataclass
class _DonateSig:
    nums: Tuple[int, ...] = ()
    names: Tuple[str, ...] = ()
    params: Tuple[str, ...] = field(default=())  # wrapped fn's params

    @property
    def donates(self) -> bool:
        return bool(self.nums or self.names)


def _literal_ints(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, int))
    return ()


def _literal_strs(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def _donate_sig(module: Module, call: ast.Call) -> Optional[_DonateSig]:
    dotted = module.dotted(call.func)
    if dotted not in _JIT_CALLS:
        return None
    sig = _DonateSig()
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            sig.nums = _literal_ints(kw.value)
        elif kw.arg == "donate_argnames":
            sig.names = _literal_strs(kw.value)
    if not sig.donates:
        return None
    # Map donate_argnames -> positions via the wrapped def's signature.
    if call.args and isinstance(call.args[0], ast.Name):
        for node in module.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == call.args[0].id:
                sig.params = tuple(
                    a.arg for a in node.args.posonlyargs + node.args.args)
    return sig


def _donated_bindings(module: Module) -> Dict[str, _DonateSig]:
    """name -> donation signature, for names whose assigned value
    contains a donating jit call anywhere (wrapper calls included)."""
    out: Dict[str, _DonateSig] = {}
    for node in module.nodes:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            continue
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Call):
                sig = _donate_sig(module, sub)
                if sig is not None:
                    out[node.targets[0].id] = sig
                    break
    return out


def _enclosing_scope(module: Module, node: ast.AST) -> ast.AST:
    cur = module.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = module.parents.get(cur)
    return module.tree


def _enclosing_statement(module: Module, node: ast.AST) -> ast.AST:
    cur = node
    parent = module.parents.get(cur)
    while parent is not None and not isinstance(parent, (
            ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
        cur = parent
        parent = module.parents.get(cur)
    return cur


def _assigned_names(stmt: ast.AST) -> Set[str]:
    names: Set[str] = set()
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name):
                names.add(sub.id)
    return names


def _check_call(module: Module, call: ast.Call, fn_name: str,
                sig: _DonateSig) -> List[Finding]:
    donated_positions = set(sig.nums)
    for name in sig.names:
        if name in sig.params:
            donated_positions.add(sig.params.index(name))
    donated_vars: List[Tuple[str, int]] = []
    for i in sorted(donated_positions):
        if i < len(call.args) and isinstance(call.args[i], ast.Name):
            donated_vars.append((call.args[i].id, i))
    if not donated_vars:
        return []

    stmt = _enclosing_statement(module, call)
    # The rebinding assignment may be the enclosing statement itself OR
    # an ancestor between the call and it (``state, m = step(state, b)``
    # inside a for loop: the statement is the For, the Assign sits on
    # the path up to it).
    rebound = _assigned_names(stmt)
    cur = call
    while cur is not stmt and cur is not None:
        if isinstance(cur, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            rebound |= _assigned_names(cur)
        cur = module.parents.get(cur)
    scope = _enclosing_scope(module, call)
    call_line = max(getattr(stmt, "end_lineno", stmt.lineno), stmt.lineno)

    findings: List[Finding] = []
    for var, pos in donated_vars:
        if var in rebound:
            continue  # state, m = step(state, batch): the safe idiom
        # First later access wins, by line: a Store clears the hazard, a
        # Load is the bug.
        accesses: List[Tuple[int, int, bool, ast.AST]] = []
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Name) and sub.id == var \
                    and sub.lineno > call_line:
                accesses.append((sub.lineno, sub.col_offset,
                                 isinstance(sub.ctx, (ast.Store, ast.Del)),
                                 sub))
        accesses.sort(key=lambda a: (a[0], a[1]))
        if accesses and not accesses[0][2]:
            _, _, _, load = accesses[0]
            findings.append(module.finding(
                "DN701", load,
                f"'{var}' was donated to jitted '{fn_name}' "
                f"(argument {pos}) on line {call.lineno}; its buffer is "
                "invalid after the call — rebind it from the call's "
                "result or stop reading it"))
    return findings


def _check_module(module: Module) -> List[Finding]:
    bindings = _donated_bindings(module)
    if not bindings:
        return []
    findings: List[Finding] = []
    for node in module.nodes:
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in bindings:
            findings.extend(_check_call(
                module, node, node.func.id, bindings[node.func.id]))
    return findings


def check_program(program: Program, registry=None) -> List[Finding]:
    findings: List[Finding] = []
    for module in program.modules:
        if module.rel in program.target_rels:
            findings.extend(_check_module(module))
    return findings
