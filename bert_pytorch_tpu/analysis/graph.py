"""Whole-program model: the cross-module symbol/call graph behind the
shardlint tier (SD6xx/DN701/CT8xx) and HS101's cross-module hot-region
propagation.

jaxlint's first tier judged one file at a time; that is the right
altitude for lexical hazards (a ``.item()`` in a loop body) but blind
to the contracts that span modules: an axis constant imported from
``parallel/mesh.py``, a telemetry ``kind`` emitted three packages away
from the schema that registers it, a CLI flag declared in
``telemetry/cli.py`` and read in a runner. :class:`Program` is the
second tier's shared substrate — every target (and context) file parsed
ONCE, keyed by both repo-relative path and dotted module name, with
conservative resolution helpers:

* :meth:`Program.resolve_function` — a called name to the
  ``(Module, FunctionDef)`` that defines it, through ``from X import f``
  aliases and ``pkg.mod.f`` attribute chains;
* :meth:`Program.resolve_constant` — a name to the module-level
  assignment that binds it, ditto;
* :func:`resolve_strings` — an expression to the set of string literals
  it statically denotes (literal, tuple/list/set/frozenset of literals,
  a local or module-level constant, or an imported constant), or None
  when the value is dynamic. Checks SKIP dynamic values: this tier
  proves what is statically knowable and stays silent about the rest.

Everything stdlib-only, like the whole analysis package: the graph is
built from ASTs, never from imports.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from bert_pytorch_tpu.analysis.core import Module


def module_name(rel: str) -> Optional[str]:
    """Dotted module name of a repo-relative path: parallel/mesh.py under
    bert_pytorch_tpu -> 'bert_pytorch_tpu.parallel.mesh'; run_glue.py ->
    'run_glue'; a package __init__.py names the package itself."""
    if not rel.endswith(".py"):
        return None
    parts = rel[:-3].replace("\\", "/").split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    if not parts or not all(p.isidentifier() for p in parts):
        return None
    return ".".join(parts)


class Program:
    """All parsed modules of one analysis run (targets + context)."""

    def __init__(self, modules: List[Module],
                 target_rels: Optional[Set[str]] = None):
        self.modules = list(modules)
        self.by_rel: Dict[str, Module] = {m.rel: m for m in self.modules}
        self.target_rels: Set[str] = (
            set(target_rels) if target_rels is not None
            else set(self.by_rel))
        self.by_name: Dict[str, Module] = {}
        for m in self.modules:
            name = module_name(m.rel)
            if name and name not in self.by_name:  # first wins on collisions
                self.by_name[name] = m
        # Per-module def/constant tables, built lazily.
        self._defs: Dict[str, Dict[str, ast.AST]] = {}
        self._consts: Dict[str, Dict[str, ast.AST]] = {}

    # -- per-module symbol tables ---------------------------------------

    def defs_of(self, module: Module) -> Dict[str, ast.AST]:
        """Function defs anywhere in the module, by name (last wins —
        matches runtime rebinding closely enough for a lint)."""
        table = self._defs.get(module.rel)
        if table is None:
            table = {}
            for node in module.nodes:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    table[node.name] = node
            self._defs[module.rel] = table
        return table

    def consts_of(self, module: Module) -> Dict[str, ast.AST]:
        """Module-level ``NAME = <value>`` bindings, by name."""
        table = self._consts.get(module.rel)
        if table is None:
            table = {}
            for stmt in module.tree.body:
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            table[t.id] = stmt.value
                elif isinstance(stmt, ast.AnnAssign) \
                        and isinstance(stmt.target, ast.Name) \
                        and stmt.value is not None:
                    table[stmt.target.id] = stmt.value
            self._consts[module.rel] = table
        return table

    # -- cross-module resolution ----------------------------------------

    def _split_dotted(self, dotted: str
                      ) -> Optional[Tuple[Module, str]]:
        """'pkg.mod.symbol' -> (Module for pkg.mod, 'symbol'), by longest
        known-module prefix; None when no prefix parses to a module we
        hold or the remainder is not a single attribute."""
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = self.by_name.get(".".join(parts[:cut]))
            if mod is not None:
                rest = parts[cut:]
                if len(rest) == 1:
                    return mod, rest[0]
                return None
        return None

    def resolve_function(self, module: Module, dotted: str
                         ) -> Optional[Tuple[Module, ast.AST]]:
        """The (defining module, FunctionDef) a dotted callable name
        denotes — same-module first, then through imports."""
        if "." not in dotted:
            fn = self.defs_of(module).get(dotted)
            if fn is not None:
                return module, fn
            dotted = module.aliases.get(dotted, dotted)
            if "." not in dotted:
                return None
        hit = self._split_dotted(dotted)
        if hit is None:
            return None
        target, symbol = hit
        fn = self.defs_of(target).get(symbol)
        # Only top-level defs are importable symbols.
        if fn is not None and isinstance(
                target.parents.get(fn), ast.Module):
            return target, fn
        return None

    def resolve_constant(self, module: Module, name: str
                         ) -> Optional[Tuple[Module, ast.AST]]:
        """The (defining module, value node) a name denotes as a
        module-level constant — locally, or through an import alias."""
        value = self.consts_of(module).get(name)
        if value is not None:
            return module, value
        dotted = module.aliases.get(name)
        if not dotted or "." not in dotted:
            return None
        hit = self._split_dotted(dotted)
        if hit is None:
            return None
        target, symbol = hit
        value = self.consts_of(target).get(symbol)
        if value is not None:
            return target, value
        return None


def _enclosing_functions(module: Module, node: ast.AST) -> List[ast.AST]:
    chain = []
    cur = module.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            chain.append(cur)
        cur = module.parents.get(cur)
    return chain


def _local_assignment(fn: ast.AST, name: str) -> Optional[ast.AST]:
    """The value of a simple ``name = <expr>`` assignment inside ``fn``
    (last one wins); None when the name is rebound in ways we cannot
    follow (aug-assign, tuple targets)."""
    value = None
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Assign):
            for t in sub.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    value = sub.value
    return value


def resolve_strings(program: Optional[Program], module: Module,
                    node: ast.AST, at: Optional[ast.AST] = None,
                    _depth: int = 0) -> Optional[Set[str]]:
    """The set of string literals ``node`` statically denotes, or None
    when any part is dynamic. ``at`` anchors Name lookups: enclosing
    function locals first, then module constants, then imports."""
    if _depth > 6:
        return None
    if isinstance(node, ast.Constant):
        return {node.value} if isinstance(node.value, str) else None
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out: Set[str] = set()
        for elt in node.elts:
            sub = resolve_strings(program, module, elt, at, _depth + 1)
            if sub is None:
                return None
            out |= sub
        return out
    if isinstance(node, ast.Call):
        # frozenset({...}) / set((...)) / tuple([...]) wrappers.
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in (
                "frozenset", "set", "tuple", "list") and len(node.args) == 1 \
                and not node.keywords:
            return resolve_strings(program, module, node.args[0], at,
                                   _depth + 1)
        return None
    if isinstance(node, ast.Name):
        anchor = at if at is not None else node
        for fn in _enclosing_functions(module, anchor):
            value = _local_assignment(fn, node.id)
            if value is not None:
                return resolve_strings(program, module, value, at,
                                       _depth + 1)
            if any(a.arg == node.id for a in
                   list(fn.args.args) + list(fn.args.kwonlyargs)
                   + list(fn.args.posonlyargs)):
                return None  # a parameter (lambdas included): dynamic
        if program is not None:
            hit = program.resolve_constant(module, node.id)
            if hit is not None:
                target, value = hit
                return resolve_strings(program, target, value, None,
                                       _depth + 1)
        else:
            value = None
            for stmt in module.tree.body:
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name) and t.id == node.id:
                            value = stmt.value
            if value is not None:
                return resolve_strings(program, module, value, None,
                                       _depth + 1)
        return None
    return None
