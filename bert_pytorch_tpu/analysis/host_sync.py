"""HS101 — blocking host transfers in step-loop hot paths.

The whole performance story of this codebase (FlashAttention-lineage
kernels, MFU accounting, async checkpointing, device prefetch) assumes
the train loop dispatches work and lets telemetry own the device sync at
a declared cadence. One stray ``.item()`` / ``float(loss)`` / ``np.
asarray(x)`` on a device value inside the step loop silently inserts a
host round-trip EVERY step — the exact failure mode the CompileMonitor
and StepTimer can only observe after the fact.

**What is hot.** Purely lexical, so it is cheap and predictable:

* the body of any ``for`` loop iterating ``...tele.timed(...)`` — the
  canonical step loop every runner and bench leg uses;
* any function whose ``def`` line (or the line above) carries a
  ``# jaxlint: hot`` marker;
* transitively: any function called from a non-exempt hot statement —
  same-module bare-name calls (``dispatch_step(...)`` in
  run_pretraining), and, when the whole-program graph is available
  (core.run_files builds one), functions IMPORTED from another module
  (``from helpers import fetch; ... fetch(m)`` inside a timed loop
  makes ``helpers.fetch`` a hot region too — the finding lands in the
  helper's file, honoring ITS suppression comments). Same-module
  behavior is the fallback whenever the graph cannot resolve a call.

**Declared sync-cadence sites** (exempt — the body only, the test still
runs per step and is scanned):

* ``if`` blocks whose test references ``last_step_synced`` /
  ``should_sync`` / ``force_sync`` — the telemetry facade's explicit
  "this step already paid the sync" signals;
* ``if`` blocks whose test contains a ``%`` cadence gate
  (``global_step % args.log_steps == 0`` — amortized by construction);
* ``if`` blocks testing equality against an integer literal
  (``if step_in_run == 1:`` — a once-per-run warmup gate).

**What is flagged** inside non-exempt hot code:

* ``x.item()``, ``x.tolist()``, ``x.block_until_ready()``;
* ``jax.device_get(...)``, ``jax.block_until_ready(...)``,
  ``np.asarray(...)``, ``np.array(...)``;
* ``float(x)`` / ``int(x)`` unless ``x`` is host-known: a constant,
  arithmetic over constants, ``len(...)``, ``x.shape[...]``/``.ndim``/
  ``.size`` (shape metadata is host-side even for device arrays),
  wall-clock calls, or an ``args.*`` attribute (argparse values).

False positives on genuinely-host values (a numpy ``valid`` mask) are
expected occasionally; that is what ``# jaxlint: disable=HS101`` with a
justifying comment is for — the suppression documents the host-ness.
"""

from __future__ import annotations

import ast
from typing import List, Set

from bert_pytorch_tpu.analysis.core import Finding, Module

CHECKS = {
    "HS101": "blocking host transfer (.item/float/np.asarray/device_get/"
             "block_until_ready) in a step-loop hot path",
}

# Dotted callables that force a device->host transfer or a sync.
_SYNC_CALLS = {
    "numpy.asarray", "numpy.array", "jax.device_get",
    "jax.block_until_ready",
}
# Method names that sync regardless of receiver spelling.
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# Names whose appearance in an if-test declares a sync-cadence site.
_SYNC_GATES = {"last_step_synced", "should_sync", "force_sync"}
# Host-only callables float()/int() may safely wrap.
_HOST_CALLS = {"len", "round", "min", "max", "abs", "time.time",
               "time.perf_counter", "time.monotonic", "time.time_ns"}
_HOST_ATTRS = {"shape", "ndim", "size"}


def _is_exempt_test(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mod):
            return True
        if isinstance(node, ast.Name) and node.id in _SYNC_GATES:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _SYNC_GATES:
            return True
        if (isinstance(node, ast.Compare)
                and len(node.ops) == 1 and isinstance(node.ops[0], ast.Eq)
                and any(isinstance(c, ast.Constant)
                        and isinstance(c.value, int)
                        for c in node.comparators)):
            return True
    return False


def _host_safe(module: Module, node: ast.AST) -> bool:
    """Conservatively: True only when the expression provably lives on
    the host (so float()/int() of it is free)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp):
        return _host_safe(module, node.operand)
    if isinstance(node, ast.BinOp):
        return _host_safe(module, node.left) and _host_safe(module, node.right)
    if isinstance(node, ast.BoolOp):
        return all(_host_safe(module, v) for v in node.values)
    if isinstance(node, ast.IfExp):
        return (_host_safe(module, node.body)
                and _host_safe(module, node.orelse))
    if isinstance(node, ast.Subscript):
        return _host_safe(module, node.value)
    if isinstance(node, ast.Attribute):
        if node.attr in _HOST_ATTRS:
            return True
        dotted = module.dotted(node)
        # argparse namespaces hold parsed host scalars.
        return bool(dotted) and dotted.split(".")[0] in ("args", "self_args")
    if isinstance(node, ast.Call):
        dotted = module.dotted(node.func)
        if dotted == "len":
            return True
        if dotted in _HOST_CALLS:
            return all(_host_safe(module, a) for a in node.args)
    return False


def _function_defs(module: Module) -> dict:
    defs: dict = {}
    for node in module.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Last definition wins on name collisions — matches runtime
            # rebinding closely enough for a lint.
            defs[node.name] = node
    return defs


def _is_timed_loop(node: ast.AST) -> bool:
    if not isinstance(node, ast.For):
        return False
    for sub in ast.walk(node.iter):
        if (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "timed"):
            return True
    return False


class _HotScanner:
    """Scans one module's hot regions; with ``program``, hotness
    propagates through imports — the scan queue carries (module, fn)
    pairs and findings land in the defining module."""

    def __init__(self, module: Module, program=None):
        self.root = module
        self.program = program
        self._defs_cache: dict = {}
        self.findings: List[Finding] = []
        self._scanned: Set[tuple] = set()
        self._pending: List[tuple] = []  # (Module, FunctionDef)

    def _defs(self, module: Module) -> dict:
        if self.program is not None:
            # Same last-def-wins table, cached program-wide instead of
            # per scanner (one _HotScanner is built per target module).
            return self.program.defs_of(module)
        defs = self._defs_cache.get(module.rel)
        if defs is None:
            defs = _function_defs(module)
            self._defs_cache[module.rel] = defs
        return defs

    def run(self) -> List[Finding]:
        module = self.root
        for node in module.nodes:
            if _is_timed_loop(node):
                self._scan_stmts(module, node.body)
        for name, fn in self._defs(module).items():
            marker_lines = {fn.lineno, fn.lineno - 1}
            if fn.decorator_list:
                marker_lines.add(fn.decorator_list[0].lineno - 1)
            if marker_lines & module.hot_lines:
                self._queue_local(module, name)
        while self._pending:
            mod, fn = self._pending.pop()
            self._scan_stmts(mod, fn.body)
        return self.findings

    def _queue_local(self, module: Module, name: str) -> None:
        fn = self._defs(module).get(name)
        if fn is not None:
            self._queue(module, name, fn)
            return
        # Not defined here: resolve through the program graph (imported
        # helpers called from a hot loop are hot regions too).
        if self.program is not None:
            hit = self.program.resolve_function(module, name)
            if hit is not None:
                target, target_fn = hit
                self._queue(target, getattr(target_fn, "name", name),
                            target_fn)

    def _queue(self, module: Module, name: str, fn) -> None:
        key = (module.rel, name)
        if key not in self._scanned:
            self._scanned.add(key)
            self._pending.append((module, fn))

    def _scan_stmts(self, module: Module, stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # a def is not execution; calls propagate hotness
            if isinstance(stmt, ast.If):
                self._scan_expr(module, stmt.test)
                if _is_exempt_test(stmt.test):
                    # The body is a declared sync-cadence site; the else
                    # branch is the common per-step path and stays hot.
                    self._scan_stmts(module, stmt.orelse)
                else:
                    self._scan_stmts(module, stmt.body)
                    self._scan_stmts(module, stmt.orelse)
                continue
            for expr in ast.iter_child_nodes(stmt):
                if isinstance(expr, ast.stmt):
                    continue
                self._scan_expr(module, expr)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if isinstance(sub, list) and sub \
                        and isinstance(sub[0], ast.stmt):
                    self._scan_stmts(module, sub)
            for handler in getattr(stmt, "handlers", []) or []:
                self._scan_stmts(module, handler.body)

    def _scan_expr(self, module: Module, expr: ast.AST) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            self._check_call(module, node)
            if isinstance(node.func, ast.Name):
                self._queue_local(module, node.func.id)
            elif self.program is not None \
                    and isinstance(node.func, ast.Attribute):
                # helpers.fetch(...) through an imported module object.
                dotted = module.dotted(node.func)
                if dotted and dotted not in _SYNC_CALLS:
                    hit = self.program.resolve_function(module, dotted)
                    if hit is not None:
                        target, fn = hit
                        self._queue(target, getattr(fn, "name", dotted), fn)

    def _check_call(self, module: Module, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _SYNC_METHODS:
            dotted = module.dotted(func)
            self.findings.append(module.finding(
                "HS101", node,
                f"'{dotted or '...' + func.attr}()' forces a device sync "
                "in a step-loop hot path; fetch on the telemetry sync "
                "cadence instead"))
            return
        dotted = module.dotted(func)
        if dotted in _SYNC_CALLS:
            self.findings.append(module.finding(
                "HS101", node,
                f"'{dotted}(...)' forces a device->host transfer in a "
                "step-loop hot path; stage/accumulate on device and fetch "
                "on the sync cadence"))
            return
        if dotted in ("float", "int") and len(node.args) == 1 \
                and not node.keywords \
                and not _host_safe(module, node.args[0]):
            self.findings.append(module.finding(
                "HS101", node,
                f"'{dotted}(...)' on a (possibly device) value in a "
                "step-loop hot path is a blocking host fetch; accumulate "
                "on device, or suppress with a comment proving the value "
                "is host-resident"))


def check(module: Module, registry=None, program=None) -> List[Finding]:
    # A timed loop inside a hot-marked function is scanned by both entry
    # points; report each flagged node once.
    seen: Set[Finding] = set()
    out: List[Finding] = []
    for f in _HotScanner(module, program=program).run():
        if f not in seen:
            seen.add(f)
            out.append(f)
    return out
