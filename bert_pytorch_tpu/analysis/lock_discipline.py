"""LK501/LK502/LK503 — enforcement of the concurrency registry
(``analysis/concurrency.py``).

For every registered shared attribute in its owning module:

* **LK501** (kind="lock") — a read or write lexically outside a
  ``with <lock>:`` block. Module-level initialization and ``__init__``
  bodies are implicitly allowed (no second thread can hold a reference
  yet); the entry's ``allow`` tuple names additional functions that are
  documented to run with the lock already held.

* **LK502** (kind="frozen") — any assignment outside ``__init__``.
  Frozen attributes are safe to share precisely because the binding
  never changes; reads are unrestricted.

* **LK503** (kind="confined") — any access inside one of the entry's
  ``forbidden_in`` functions (the targets that run on *other* threads).

The check is lexical, not a race detector: it proves the declared
discipline is followed at every access site, which is exactly the
property review memory kept failing to hold (PR 5's JSONL sink, PR 6's
pending-save slot).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from bert_pytorch_tpu.analysis.core import Finding, Module

CHECKS = {
    "LK501": "registered shared attribute accessed outside its declared "
             "lock",
    "LK502": "registered frozen attribute reassigned after __init__",
    "LK503": "thread-confined attribute accessed in a forbidden thread "
             "function",
}


def _enclosing(module: Module, node: ast.AST
               ) -> Tuple[Optional[str], Optional[str], List[ast.AST]]:
    """(innermost function name, innermost class name, ancestor chain)."""
    fn = cls = None
    chain = []
    cur = module.parents.get(node)
    while cur is not None:
        chain.append(cur)
        if fn is None and isinstance(cur, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
            fn = cur.name
        if cls is None and isinstance(cur, ast.ClassDef):
            cls = cur.name
        cur = module.parents.get(cur)
    return fn, cls, chain


def _lock_names_in_with(item_expr: ast.AST) -> List[str]:
    """Candidate guard names a with-item takes: ``with _lock:`` /
    ``with self._cond:`` / ``with obj.lock:``."""
    names = []
    if isinstance(item_expr, ast.Name):
        names.append(item_expr.id)
    elif isinstance(item_expr, ast.Attribute):
        names.append(item_expr.attr)
    elif isinstance(item_expr, ast.Call):
        # with lock.acquire_timeout(...) style wrappers: use the method's
        # receiver attribute name.
        names.extend(_lock_names_in_with(item_expr.func))
    return names


def _held_locks(chain: List[ast.AST]) -> List[str]:
    held: List[str] = []
    for ancestor in chain:
        if isinstance(ancestor, (ast.With, ast.AsyncWith)):
            for item in ancestor.items:
                held.extend(_lock_names_in_with(item.context_expr))
    return held


def _accesses(module: Module, entry) -> List[Tuple[ast.AST, bool]]:
    """(node, is_store) for every access of the registered attribute."""
    out: List[Tuple[ast.AST, bool]] = []
    for node in module.nodes:
        if entry.cls:
            if isinstance(node, ast.Attribute) and node.attr == entry.attr \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == "self":
                out.append((node, isinstance(node.ctx,
                                             (ast.Store, ast.Del))))
        else:
            if isinstance(node, ast.Name) and node.id == entry.attr:
                out.append((node, isinstance(node.ctx,
                                             (ast.Store, ast.Del))))
    return out


def check(module: Module, registry=None, program=None) -> List[Finding]:
    if registry is None:
        from bert_pytorch_tpu.analysis import concurrency
        registry = concurrency.REGISTRY
    entries = [e for e in registry
               if module.rel.endswith(e.module.replace("\\", "/"))]
    findings: List[Finding] = []
    for entry in entries:
        for node, is_store in _accesses(module, entry):
            fn, cls, chain = _enclosing(module, node)
            if entry.cls and cls != entry.cls:
                continue
            if entry.kind == "confined":
                if fn in entry.forbidden_in:
                    findings.append(module.finding(
                        "LK503", node,
                        f"'{entry.attr}' is confined to its owner thread "
                        f"({entry.why}) but is accessed in '{fn}', a "
                        "declared other-thread function"))
                continue
            if entry.kind == "frozen":
                if is_store and fn != "__init__":
                    findings.append(module.finding(
                        "LK502", node,
                        f"'{entry.attr}' is registered frozen "
                        f"({entry.why}); reassigning it outside __init__ "
                        "races every thread reading the binding"))
                continue
            # kind == "lock"
            if fn is None or fn == "__init__" or fn in entry.allow:
                continue
            held = _held_locks(chain)
            if not any(lock in held for lock in entry.locks):
                want = " or ".join(f"'with {name}:'"
                                   for name in entry.locks)
                findings.append(module.finding(
                    "LK501", node,
                    f"'{entry.attr}' ({entry.why}) accessed in '{fn}' "
                    f"outside its guard — wrap the access in {want}"))
    return findings
