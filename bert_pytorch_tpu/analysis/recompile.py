"""RC201/RC202/RC203 — recompile and retrace hazards at jit boundaries.

Every recompile of a BERT-large step costs minutes; the CompileMonitor
attributes them after they happen, these checks prevent the three
classic causes from landing:

* **RC201** — a *collection-typed* argument (list/dict/set literal or
  comprehension) passed at a position a ``jax.jit``/``pjit`` declared
  static (``static_argnums``/``static_argnames``). Unhashable statics
  raise at best; hashable-but-freshly-built collections (tuples of
  tuples) silently miss the jit cache every call. Pass a hashable
  singleton (module constant, frozen dataclass) instead.

* **RC202** — a jitted function closing over *module-level mutable
  state* (a lowercase module global bound to a list/dict/set). The
  closure value is baked in at trace time: mutations after the first
  call are silently ignored, and rebinding the global forces a retrace.
  ALL_CAPS module constants are exempt by convention — the name says
  "never mutated".

* **RC203** — a *numeric Python literal* passed at a static position.
  Each distinct value compiles a new executable; a value that belongs
  in the computation should be a weak-typed array argument (dynamic),
  and a true constant belongs in the function, not the call site.
  String/bool/None statics are mode flags with tiny cardinality and are
  not flagged.

All three are resolved lexically per module: ``g = jax.jit(f,
static_argnames=("mode",))`` records g's static signature; later
``g(x, mode=[...])`` call sites are checked against it. Decorated defs
(``@jax.jit``, ``@partial(jax.jit, ...)``) are handled the same way.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from bert_pytorch_tpu.analysis.core import Finding, Module

CHECKS = {
    "RC201": "collection-typed argument at a jit static position "
             "(unhashable or cache-missing every call)",
    "RC202": "jitted function closes over module-level mutable state",
    "RC203": "numeric Python literal at a jit static position "
             "(per-value recompile; pass a weak-typed array instead)",
}

_JIT_CALLS = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit", "pjit",
              "jit"}
_PARTIAL_CALLS = {"functools.partial", "partial"}
_COLLECTION_NODES = (ast.List, ast.Dict, ast.Set, ast.ListComp,
                     ast.DictComp, ast.SetComp, ast.GeneratorExp)
_MUTABLE_CTORS = {"list", "dict", "set", "collections.defaultdict",
                  "collections.deque", "collections.OrderedDict",
                  "collections.Counter"}


@dataclass
class _JitSig:
    static_nums: Tuple[int, ...] = ()
    static_names: Tuple[str, ...] = ()
    wrapped: Optional[str] = None  # name of the wrapped FunctionDef
    node: Optional[ast.AST] = None

    @property
    def has_statics(self) -> bool:
        return bool(self.static_nums or self.static_names)


def _literal_ints(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


def _literal_strs(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(elt.value for elt in node.elts
                     if isinstance(elt, ast.Constant)
                     and isinstance(elt.value, str))
    return ()


def _jit_sig_from_call(module: Module, call: ast.Call) -> Optional[_JitSig]:
    """The static signature when ``call`` is jax.jit/pjit(...), else None.
    ``partial(jax.jit, ...)`` unwraps one level (the decorator idiom)."""
    dotted = module.dotted(call.func)
    if dotted in _PARTIAL_CALLS and call.args:
        inner_dotted = module.dotted(call.args[0])
        if inner_dotted in _JIT_CALLS:
            sig = _JitSig(node=call)
            for kw in call.keywords:
                _fill_sig(sig, kw)
            return sig
        return None
    if dotted not in _JIT_CALLS:
        return None
    sig = _JitSig(node=call)
    if call.args and isinstance(call.args[0], ast.Name):
        sig.wrapped = call.args[0].id
    if call.args and isinstance(call.args[0], ast.Lambda):
        sig.wrapped = None
    for kw in call.keywords:
        _fill_sig(sig, kw)
    return sig


def _fill_sig(sig: _JitSig, kw: ast.keyword) -> None:
    if kw.arg == "static_argnums":
        sig.static_nums = _literal_ints(kw.value)
    elif kw.arg == "static_argnames":
        sig.static_names = _literal_strs(kw.value)


class _State:
    def __init__(self, module: Module):
        self.module = module
        # name -> static signature, for names bound to a jit result.
        self.jitted_names: Dict[str, _JitSig] = {}
        # FunctionDefs that are traced under jit (decorated, or passed
        # to a jit call by name, incl. lambdas handled inline).
        self.jitted_fns: List[ast.FunctionDef] = []
        self.jitted_lambdas: List[ast.Lambda] = []
        # lowercase module-level names bound to mutable collections.
        self.mutable_globals: Set[str] = set()


def _collect(module: Module) -> _State:
    state = _State(module)
    fn_defs = {n.name: n for n in module.nodes
               if isinstance(n, ast.FunctionDef)}

    for stmt in module.tree.body:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            value = stmt.value
            if value is None:
                continue
            is_mutable = isinstance(value, _COLLECTION_NODES) or (
                isinstance(value, ast.Call)
                and module.dotted(value.func) in _MUTABLE_CTORS)
            if is_mutable:
                for t in targets:
                    if isinstance(t, ast.Name) and not t.id.isupper():
                        state.mutable_globals.add(t.id)

    for node in module.nodes:
        if isinstance(node, ast.Call):
            sig = _jit_sig_from_call(module, node)
            if sig is None:
                continue
            if sig.wrapped and sig.wrapped in fn_defs:
                state.jitted_fns.append(fn_defs[sig.wrapped])
            if node.args and isinstance(node.args[0], ast.Lambda):
                state.jitted_lambdas.append(node.args[0])
            parent = module.parents.get(node)
            # name = jax.jit(f, ...) records the callable's static sig.
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                    and isinstance(parent.targets[0], ast.Name):
                state.jitted_names[parent.targets[0].id] = sig
            # tele.instrument(jax.jit(f, ...), "name") and similar
            # wrappers: the sig follows the enclosing assignment.
            if isinstance(parent, ast.Call):
                outer = module.parents.get(parent)
                if isinstance(outer, ast.Assign) \
                        and len(outer.targets) == 1 \
                        and isinstance(outer.targets[0], ast.Name):
                    state.jitted_names[outer.targets[0].id] = sig
        elif isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                dotted = module.dotted(dec)
                if dotted in _JIT_CALLS:
                    state.jitted_fns.append(node)
                elif isinstance(dec, ast.Call):
                    sig = _jit_sig_from_call(module, dec)
                    if sig is not None:
                        state.jitted_fns.append(node)
                        state.jitted_names[node.name] = sig
    return state


def _check_call_sites(state: _State) -> List[Finding]:
    module = state.module
    findings: List[Finding] = []
    for node in module.nodes:
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in state.jitted_names):
            continue
        sig = state.jitted_names[node.func.id]
        if not sig.has_statics:
            continue
        static_args = [(f"position {i}", node.args[i])
                       for i in sig.static_nums if i < len(node.args)]
        static_args += [(f"'{kw.arg}'", kw.value) for kw in node.keywords
                        if kw.arg in sig.static_names]
        for where, arg in static_args:
            if isinstance(arg, _COLLECTION_NODES):
                findings.append(module.finding(
                    "RC201", arg,
                    f"collection literal passed at static {where} of "
                    f"jitted '{node.func.id}': unhashable statics raise, "
                    "freshly-built ones miss the jit cache every call"))
            elif isinstance(arg, ast.Constant) \
                    and isinstance(arg.value, (int, float)) \
                    and not isinstance(arg.value, bool):
                findings.append(module.finding(
                    "RC203", arg,
                    f"numeric literal {arg.value!r} at static {where} of "
                    f"jitted '{node.func.id}' recompiles per value; pass "
                    "it as a weak-typed array argument or hoist it into "
                    "the function"))
    return findings


def _check_closures(state: _State) -> List[Finding]:
    module = state.module
    findings: List[Finding] = []
    if not state.mutable_globals:
        return findings

    def scan(fn_node: ast.AST, body, name: str) -> None:
        # Names rebound locally shadow the module global.
        local: Set[str] = set()
        for sub in ast.walk(fn_node):
            if isinstance(sub, ast.arg):
                local.add(sub.arg)
            elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                local.add(sub.id)
        for sub in ast.walk(fn_node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load) \
                    and sub.id in state.mutable_globals \
                    and sub.id not in local:
                findings.append(module.finding(
                    "RC202", sub,
                    f"jitted {name} reads module-level mutable "
                    f"'{sub.id}': its value is baked in at trace time "
                    "(mutations ignored, rebinds retrace); pass it as an "
                    "argument or make it an ALL_CAPS constant"))

    for fn in state.jitted_fns:
        scan(fn, fn.body, f"function '{fn.name}'")
    for lam in state.jitted_lambdas:
        scan(lam, [lam.body], "lambda")
    return findings


def check(module: Module, registry=None, program=None) -> List[Finding]:
    state = _collect(module)
    return _check_call_sites(state) + _check_closures(state)
