"""RN301/RN302 — PRNG hygiene.

* **RN301** — a PRNG key consumed twice without an intervening
  ``split``. JAX keys are values, not stateful generators: drawing from
  the same key twice yields IDENTICAL randomness (correlated dropout
  masks, repeated initializations) with no runtime error. Tracked per
  function scope: names bound from ``jax.random.PRNGKey/split/fold_in``
  are keys; passing one to a consuming ``jax.random.*`` call (or into an
  ``rngs={...}`` dict / ``.apply``/``.init`` call) consumes it; a second
  consumption without a re-bind flags. Loop bodies are scanned twice, so
  a consumption inside a loop of a key created outside it flags on the
  simulated second iteration — the classic "same dropout mask every
  step" bug. ``fold_in`` does not consume (folding distinct data into
  one base key is its purpose); ``split`` consumes its argument and its
  targets become fresh keys.

* **RN302** — a seed derived from wall-clock time
  (``PRNGKey(int(time.time()))``, ``default_rng(time.time_ns())``).
  Wall-clock seeds destroy the bit-exact resume/replay story the fault
  tolerance layer depends on (PR 5: (seed, epoch, index)-derived
  masking), and two processes started in the same second silently share
  a stream. Seeds come from config, never from the clock.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from bert_pytorch_tpu.analysis.core import Finding, Module

CHECKS = {
    "RN301": "PRNG key consumed twice without an intervening split",
    "RN302": "PRNG seed derived from wall-clock time",
}

_KEY_MAKERS = {"jax.random.PRNGKey", "jax.random.key", "jax.random.split",
               "jax.random.fold_in", "jax.random.clone"}
# jax.random.* callables that do NOT consume their key argument.
_NON_CONSUMING = {"PRNGKey", "key", "fold_in", "key_data", "wrap_key_data",
                  "clone", "key_impl"}
_SEED_SINKS = {"jax.random.PRNGKey", "jax.random.key", "numpy.random.seed",
               "numpy.random.default_rng", "random.seed", "random.Random"}
_CLOCK_CALLS = {"time.time", "time.time_ns", "time.monotonic",
                "time.monotonic_ns", "time.perf_counter",
                "datetime.datetime.now", "datetime.datetime.utcnow",
                "datetime.datetime.today"}


def _wallclock_seed_findings(module: Module) -> List[Finding]:
    findings = []
    for node in module.nodes:
        if not isinstance(node, ast.Call):
            continue
        if module.dotted(node.func) not in _SEED_SINKS:
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Call) \
                        and module.dotted(sub.func) in _CLOCK_CALLS:
                    findings.append(module.finding(
                        "RN302", node,
                        "seed derived from wall-clock time breaks "
                        "bit-exact resume/replay; take the seed from "
                        "config (and fold_in run identifiers if needed)"))
                    break
    return findings


class _KeyTracker:
    """Linear abstract interpretation of one function body: which names
    hold PRNG keys, and has each been consumed since its last bind."""

    def __init__(self, module: Module):
        self.module = module
        self.findings: List[Finding] = []

    # state: name -> consumed? (True after one consumption)

    def scan_function(self, body: List[ast.stmt]) -> None:
        self._scan(body, {})

    def _scan(self, stmts: List[ast.stmt], state: Dict[str, bool]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope; scanned by its own tracker
            if isinstance(stmt, ast.If):
                self._consume_in_expr(stmt.test, state)
                body_state = dict(state)
                else_state = dict(state)
                self._scan(stmt.body, body_state)
                self._scan(stmt.orelse, else_state)
                # Merge: consumed in either branch counts as consumed
                # after the join (one dynamic path uses it; a later use
                # would be that path's second). Keys created in only one
                # branch are dropped — conservatively untracked.
                for name in list(state):
                    state[name] = body_state.get(name, True) \
                        or else_state.get(name, True)
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                if isinstance(stmt, ast.For):
                    self._consume_in_expr(stmt.iter, state)
                else:
                    self._consume_in_expr(stmt.test, state)
                # Two passes simulate the second iteration: a key made
                # outside the loop and consumed inside it flags here.
                self._scan(stmt.body, state)
                self._scan(stmt.body, state)
                self._scan(stmt.orelse, state)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._consume_in_expr(item.context_expr, state)
                self._scan(stmt.body, state)
                continue
            if isinstance(stmt, ast.Try):
                self._scan(stmt.body, state)
                for handler in stmt.handlers:
                    self._scan(handler.body, state)
                self._scan(stmt.orelse, state)
                self._scan(stmt.finalbody, state)
                continue
            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                value = stmt.value
                if value is not None:
                    self._consume_in_expr(value, state)
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                fresh = value is not None and isinstance(value, ast.Call) \
                    and self.module.dotted(value.func) in _KEY_MAKERS
                for t in targets:
                    names = [t] if isinstance(t, ast.Name) else (
                        [e for e in t.elts if isinstance(e, ast.Name)]
                        if isinstance(t, (ast.Tuple, ast.List)) else [])
                    for n in names:
                        if fresh:
                            state[n.id] = False
                        else:
                            state.pop(n.id, None)
                continue
            # Any other statement: scan its expressions for consumption.
            for node in ast.iter_child_nodes(stmt):
                if not isinstance(node, ast.stmt):
                    self._consume_in_expr(node, state)

    def _consume_in_expr(self, expr: ast.AST, state: Dict[str, bool]) -> None:
        if expr is None:
            return
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            dotted = self.module.dotted(node.func) or ""
            consumed_names: List[ast.Name] = []
            if dotted.startswith("jax.random.") \
                    and dotted.rsplit(".", 1)[1] not in _NON_CONSUMING:
                for arg in node.args[:1]:  # the key is the first argument
                    if isinstance(arg, ast.Name):
                        consumed_names.append(arg)
            # rngs={"dropout": key} / .apply(..., rngs=...) / .init(key, ...)
            if dotted.endswith((".apply", ".init")):
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    if isinstance(arg, ast.Name):
                        consumed_names.append(arg)
                    elif isinstance(arg, ast.Dict):
                        consumed_names.extend(
                            v for v in arg.values if isinstance(v, ast.Name))
            else:
                for kw in node.keywords:
                    if kw.arg == "rngs":
                        if isinstance(kw.value, ast.Name):
                            consumed_names.append(kw.value)
                        elif isinstance(kw.value, ast.Dict):
                            consumed_names.extend(
                                v for v in kw.value.values
                                if isinstance(v, ast.Name))
            for name_node in consumed_names:
                name = name_node.id
                if name not in state:
                    continue
                if state[name]:
                    self.findings.append(self.module.finding(
                        "RN301", node,
                        f"PRNG key '{name}' consumed again without an "
                        "intervening split: identical randomness both "
                        "times (split the key, or fold_in distinguishing "
                        "data)"))
                else:
                    state[name] = True


def check(module: Module, registry=None, program=None) -> List[Finding]:
    findings = _wallclock_seed_findings(module)
    for node in module.nodes:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            tracker = _KeyTracker(module)
            tracker.scan_function(node.body)
            findings.extend(tracker.findings)
    tracker = _KeyTracker(module)
    tracker.scan_function(
        [s for s in module.tree.body
         if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef))])
    findings.extend(tracker.findings)
    return findings
