"""SD601/SD602/SD603 — sharding discipline against the axes registry
(``analysis/axes.py``), the safety net the "one mesh" refactor lands on.

* **SD601** collective-axis discipline: every statically-resolvable axis
  name handed to a ``lax`` collective (``psum``/``pmean``/``pmax``/
  ``pmin``/``all_gather``/``ppermute``/``all_to_all``/``psum_scatter``/
  ``axis_index``) must be a registered mesh axis, or be declared by an
  enclosing ``shard_map``/``pmap``/``xmap`` scope (``axis_names=`` /
  ``axis_name=`` — including a wrapper function the collective's
  enclosing function is passed to, and ``@partial(shard_map, ...)``
  decorators). A typo'd axis traces fine and fails only under the mesh
  shape that exercises it; the registry catches it at commit time.
  Dynamic axis arguments (a parameter, a computed name) are skipped —
  this tier proves what is statically knowable.

* **SD602** logical-rule coverage: every logical name in an
  ``nn.with_logical_partitioning`` annotation (or a ``kernel_axes=`` /
  ``bias_axes=`` / ``embedding_axes=`` keyword, the factory spelling
  ``ops/quant.py make_dense`` uses) must have a rule under EVERY
  declared strategy (``axes.logical_coverage``); an unmatched name
  silently REPLICATES the parameter — the ZeRO-lineage fsdp bug class.
  ``PartitionSpec`` literals are checked against the mesh axes the same
  way (an unknown axis raises only when a mesh is finally attached).

* **SD603** raw mesh-axis literals outside ``parallel/``: axis-position
  string literals equal to a mesh axis (collective args, PartitionSpec
  entries, ``axis``/``axis_name``/``axis_names``/``seq_axis`` keywords
  and parameter defaults, ``mesh.shape[...]`` lookups, axis-named
  assignment targets and comparisons) must import the ``AXIS_*``
  constants from ``parallel/mesh.py`` instead. The constants are the
  first concrete step of the one-mesh consolidation: when the refactor
  renames or splits an axis, it edits ONE module and the type of every
  consumer, instead of a repo-wide string hunt. ``parallel/`` itself
  (the defining layer) and this analysis package (the mirror) are
  exempt.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from bert_pytorch_tpu.analysis import axes as axes_registry
from bert_pytorch_tpu.analysis.core import Finding, Module
from bert_pytorch_tpu.analysis.graph import Program, resolve_strings

CHECKS = {
    "SD601": "collective axis name neither registered in the axes "
             "registry nor declared by an enclosing shard_map/pmap scope",
    "SD602": "logical/PartitionSpec axis name without a rule under every "
             "declared strategy (silently replicates)",
    "SD603": "raw mesh-axis string literal outside parallel/ (import the "
             "AXIS_* constants from parallel/mesh.py)",
}

# lax collectives -> index of the axis-name positional argument.
_COLLECTIVES: Dict[str, int] = {
    "jax.lax.psum": 1,
    "jax.lax.pmean": 1,
    "jax.lax.pmax": 1,
    "jax.lax.pmin": 1,
    "jax.lax.all_gather": 1,
    "jax.lax.ppermute": 1,
    "jax.lax.pshuffle": 1,
    "jax.lax.all_to_all": 1,
    "jax.lax.psum_scatter": 1,
    "jax.lax.axis_index": 0,
}
# Keywords that carry axis names on collective calls.
_AXIS_KEYWORDS = ("axis_name", "axis_names")
# Callables that DECLARE manual axes for a wrapped function. Matched on
# the last dotted component: shard_map arrives as jax.shard_map,
# jax.experimental.shard_map.shard_map, or the pipeline shim
# bert_pytorch_tpu.parallel.pipeline.shard_map.
_DECLARING_TAILS = ("shard_map", "pmap", "xmap")
# Keywords/defaults/targets whose string payload is axis-positioned for
# SD603 (the spellings this codebase actually uses).
_AXIS_NAMED = ("axis", "axis_name", "axis_names", "seq_axis")
# Modules allowed to spell mesh axes as raw literals: the defining
# parallel layer and the analysis mirror itself.
_SD603_EXEMPT_PREFIXES = ("bert_pytorch_tpu/parallel/",
                          "bert_pytorch_tpu/analysis/")

_PARTITION_SPEC_TAILS = ("PartitionSpec",)
_LOGICAL_CALL_TAILS = ("with_logical_partitioning",)
_LOGICAL_KEYWORDS = ("kernel_axes", "bias_axes", "embedding_axes")


def _tail(dotted: Optional[str]) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


def _is_declaring_call(module: Module, call: ast.Call) -> bool:
    dotted = module.dotted(call.func)
    if _tail(dotted) in _DECLARING_TAILS:
        return True
    # partial(shard_map, ...) — the decorator idiom.
    if _tail(dotted) == "partial" and call.args:
        return _tail(module.dotted(call.args[0])) in _DECLARING_TAILS
    return False


def _declared_axes_of_call(program: Optional[Program], module: Module,
                           call: ast.Call) -> Set[str]:
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg in _AXIS_KEYWORDS:
            resolved = resolve_strings(program, module, kw.value, at=call)
            if resolved:
                names |= resolved
    return names


def _wrapped_function_names(module: Module, call: ast.Call) -> List[str]:
    """Bare names of functions a declaring call wraps: shard_map(f, ...),
    shard_map(partial(f, ...), ...)."""
    if not call.args:
        return []
    arg = call.args[0]
    # partial(shard_map, ...) declares for its later callee, which we
    # cannot see here; the decorator path handles the common case.
    if _tail(module.dotted(call.func)) == "partial":
        return []
    out: List[str] = []
    if isinstance(arg, ast.Name):
        out.append(arg.id)
    elif isinstance(arg, ast.Call) and _tail(
            module.dotted(arg.func)) == "partial" and arg.args \
            and isinstance(arg.args[0], ast.Name):
        out.append(arg.args[0].id)
    return out


class _ScopeAxes:
    """Axis names each function/lambda in a module may legally use in
    collectives, gathered from every declaring call that (a) lexically
    encloses it, (b) wraps it by name, or (c) decorates it."""

    def __init__(self, program: Optional[Program], module: Module):
        self.module = module
        self.program = program
        self.by_fn: Dict[ast.AST, Set[str]] = {}
        self._collect()

    def _collect(self) -> None:
        module = self.module
        defs: Dict[str, List[ast.AST]] = {}
        for node in module.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call) \
                            and _is_declaring_call(module, dec):
                        self._add(node, _declared_axes_of_call(
                            self.program, module, dec))
        for node in module.nodes:
            if not (isinstance(node, ast.Call)
                    and _is_declaring_call(module, node)):
                continue
            declared = _declared_axes_of_call(self.program, module, node)
            if not declared:
                continue
            # The wrapped function, by name...
            for name in _wrapped_function_names(module, node):
                for fn in defs.get(name, []):
                    self._add(fn, declared)
            # ...or inline (a lambda / def lexically inside the call).
            for sub in ast.walk(node):
                if isinstance(sub, ast.Lambda):
                    self._add(sub, declared)

    def _add(self, fn: ast.AST, names: Set[str]) -> None:
        self.by_fn.setdefault(fn, set()).update(names)

    def allowed_at(self, node: ast.AST) -> Set[str]:
        """Declared axes visible at ``node``: every enclosing function's
        declarations plus any enclosing declaring call's own."""
        out: Set[str] = set()
        cur = self.module.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                out |= self.by_fn.get(cur, set())
            if isinstance(cur, ast.Call) \
                    and _is_declaring_call(self.module, cur):
                out |= _declared_axes_of_call(self.program, self.module, cur)
            cur = self.module.parents.get(cur)
        return out


def _check_collectives(program: Optional[Program], module: Module,
                       scopes: _ScopeAxes) -> List[Finding]:
    findings: List[Finding] = []
    for node in module.nodes:
        if not isinstance(node, ast.Call):
            continue
        dotted = module.dotted(node.func)
        if dotted not in _COLLECTIVES:
            continue
        pos = _COLLECTIVES[dotted]
        axis_nodes: List[ast.AST] = []
        if len(node.args) > pos:
            axis_nodes.append(node.args[pos])
        axis_nodes.extend(kw.value for kw in node.keywords
                          if kw.arg in _AXIS_KEYWORDS)
        for axis_node in axis_nodes:
            resolved = resolve_strings(program, module, axis_node, at=node)
            if resolved is None:
                continue  # dynamic: out of this tier's reach
            allowed = None
            for name in sorted(resolved):
                if axes_registry.is_mesh_axis(name):
                    continue
                if allowed is None:
                    allowed = scopes.allowed_at(node)
                if name in allowed:
                    continue
                findings.append(module.finding(
                    "SD601", axis_node,
                    f"'{_tail(dotted)}' over axis '{name}': not a "
                    "registered mesh axis (analysis/axes.py) and no "
                    "enclosing shard_map/pmap scope declares it — a "
                    "typo'd axis only fails under the mesh shape that "
                    "exercises it"))
    return findings


def _check_logical(program: Optional[Program], module: Module
                   ) -> List[Finding]:
    findings: List[Finding] = []

    def check_logical_names(node: ast.AST, where: str) -> None:
        resolved = resolve_strings(program, module, node, at=node)
        if resolved is None:
            return
        for name in sorted(resolved):
            missing = axes_registry.uncovered_strategies(name)
            if missing:
                findings.append(module.finding(
                    "SD602", node,
                    f"logical axis '{name}' ({where}) has no rule under "
                    f"strateg{'y' if len(missing) == 1 else 'ies'} "
                    f"{', '.join(missing)} — an unmatched logical name "
                    "silently replicates; add the rule to "
                    "parallel/mesh.py AND its mirror analysis/axes.py"))

    for node in module.nodes:
        if not isinstance(node, ast.Call):
            continue
        tail = _tail(module.dotted(node.func))
        if tail in _LOGICAL_CALL_TAILS and len(node.args) >= 2:
            check_logical_names(node.args[1], "with_logical_partitioning")
        for kw in node.keywords:
            if kw.arg in _LOGICAL_KEYWORDS:
                check_logical_names(kw.value, f"{kw.arg}=")
        if tail in _PARTITION_SPEC_TAILS:
            for arg in node.args:
                if isinstance(arg, ast.Starred):
                    continue
                resolved = resolve_strings(program, module, arg, at=node)
                if resolved is None:
                    continue
                for name in sorted(resolved):
                    if not axes_registry.is_mesh_axis(name):
                        findings.append(module.finding(
                            "SD602", arg,
                            f"PartitionSpec axis '{name}' is not a "
                            "registered mesh axis (analysis/axes.py); "
                            "it only fails once a mesh is attached"))
    return findings


def _axis_literals(node: ast.AST) -> List[ast.Constant]:
    """Constant mesh-axis strings anywhere in an axis-positioned
    expression subtree."""
    return [sub for sub in ast.walk(node)
            if isinstance(sub, ast.Constant)
            and isinstance(sub.value, str)
            and axes_registry.is_mesh_axis(sub.value)]


def _is_axis_named(name: Optional[str]) -> bool:
    return bool(name) and (name in _AXIS_NAMED or name.endswith("_axis")
                           or name.endswith("_axes"))


def _sd603_sites(module: Module) -> List[ast.Constant]:
    sites: List[ast.Constant] = []
    for node in module.nodes:
        if isinstance(node, ast.Call):
            dotted = module.dotted(node.func)
            pos = _COLLECTIVES.get(dotted)
            if pos is not None and len(node.args) > pos:
                sites.extend(_axis_literals(node.args[pos]))
            if _tail(dotted) in _PARTITION_SPEC_TAILS:
                for arg in node.args:
                    sites.extend(_axis_literals(arg))
            # mesh.shape.get("seq", ...) lookups.
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" \
                    and isinstance(node.func.value, ast.Attribute) \
                    and node.func.value.attr == "shape" and node.args:
                sites.extend(_axis_literals(node.args[0]))
            for kw in node.keywords:
                if _is_axis_named(kw.arg):
                    sites.extend(_axis_literals(kw.value))
        elif isinstance(node, ast.Subscript):
            # mesh.shape["pipe"] lookups.
            if isinstance(node.value, ast.Attribute) \
                    and node.value.attr == "shape":
                sites.extend(_axis_literals(node.slice))
        elif isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and _is_axis_named(t.id)
                   for t in node.targets):
                sites.extend(_axis_literals(node.value))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sig = node.args
            # Trailing positional defaults pair with the last params.
            pos_args = list(sig.posonlyargs) + list(sig.args)
            paired = zip(pos_args[-len(sig.defaults):], sig.defaults) \
                if sig.defaults else []
            for arg, default in list(paired) + list(
                    zip(sig.kwonlyargs, sig.kw_defaults)):
                if default is not None and _is_axis_named(arg.arg):
                    sites.extend(_axis_literals(default))
        elif isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
            if any(isinstance(op, ast.Name) and _is_axis_named(op.id)
                   or isinstance(op, ast.Attribute)
                   and _is_axis_named(op.attr) for op in operands):
                for op in operands:
                    if isinstance(op, ast.Constant):
                        sites.extend(_axis_literals(op))
    return sites


def _check_literals(module: Module) -> List[Finding]:
    if any(module.rel.startswith(p) or ("/" + p) in module.rel
           for p in _SD603_EXEMPT_PREFIXES):
        return []
    findings: List[Finding] = []
    seen: Set[int] = set()
    for lit in _sd603_sites(module):
        key = id(lit)
        if key in seen:
            continue
        seen.add(key)
        const = axes_registry.constant_for(lit.value)
        findings.append(module.finding(
            "SD603", lit,
            f"raw mesh-axis literal '{lit.value}' outside parallel/ — "
            f"import {const} from bert_pytorch_tpu.parallel.mesh so the "
            "one-mesh refactor renames axes in one place"))
    return findings


def check_program(program: Program, registry=None) -> List[Finding]:
    findings: List[Finding] = []
    for module in program.modules:
        if module.rel not in program.target_rels:
            continue
        scopes = _ScopeAxes(program, module)
        findings.extend(_check_collectives(program, module, scopes))
        findings.extend(_check_logical(program, module))
        findings.extend(_check_literals(module))
    return findings
