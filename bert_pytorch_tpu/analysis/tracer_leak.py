"""TL401 — traced values assigned to ``self.*`` or globals inside jitted
functions.

Inside a jit trace every intermediate is a tracer. Storing one on
``self`` or a module global "works" at trace time, then either leaks a
``UnexpectedTracerError`` much later (jax >= 0.4 with leak checking) or
— worse — silently pins the FIRST trace's value forever: the attribute
holds a stale tracer/constant while every subsequent call recomputes
fresh values that go nowhere. State leaves a jitted function through its
return value, never through side effects.

Detection is lexical: for every function traced under jit in the module
(``@jax.jit`` decorated, ``jax.jit(f)``-wrapped by name, or a lambda
passed to jit — the same resolution recompile.py uses), flag

* ``self.<attr> = value`` / ``self.<attr> += value``,
* assignment to a name declared ``global`` in that function,

unless the assigned value is a plain constant (setting a flag to a
literal is config, not a leak).
"""

from __future__ import annotations

import ast
from typing import List

from bert_pytorch_tpu.analysis.core import Finding, Module
from bert_pytorch_tpu.analysis.recompile import _collect

CHECKS = {
    "TL401": "traced value assigned to self.*/global inside a jitted "
             "function (state must leave jit via the return value)",
}


def _scan_jitted(module: Module, fn: ast.AST, label: str) -> List[Finding]:
    findings: List[Finding] = []
    global_names = {
        name
        for node in ast.walk(fn) if isinstance(node, ast.Global)
        for name in node.names
    }
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets, value = [node.target], node.value
        else:
            continue
        if isinstance(value, ast.Constant):
            continue
        for t in targets:
            leaks = None
            if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                leaks = f"self.{t.attr}"
            elif isinstance(t, ast.Name) and t.id in global_names:
                leaks = f"global '{t.id}'"
            elif isinstance(t, ast.Subscript):
                base = t.value
                if isinstance(base, ast.Attribute) \
                        and isinstance(base.value, ast.Name) \
                        and base.value.id == "self":
                    leaks = f"self.{base.attr}[...]"
                elif isinstance(base, ast.Name) and base.id in global_names:
                    leaks = f"global '{base.id}[...]'"
            if leaks:
                findings.append(module.finding(
                    "TL401", node,
                    f"{label} assigns a traced value to {leaks}: the "
                    "stored tracer is stale after the first trace (or "
                    "raises UnexpectedTracerError); return the value "
                    "instead"))
    return findings


def check(module: Module, registry=None, program=None) -> List[Finding]:
    state = _collect(module)
    findings: List[Finding] = []
    for fn in state.jitted_fns:
        findings.extend(_scan_jitted(module, fn, f"jitted '{fn.name}'"))
    for lam in state.jitted_lambdas:
        findings.extend(_scan_jitted(module, lam, "jitted lambda"))
    return findings
