"""Model configuration and the three-level CLI > JSON > defaults config system.

Behavioral parity targets (see SURVEY.md §5.6):
  - ``BertConfig`` semantics of reference src/modeling.py:188-295 —
    ``from_dict`` merges arbitrary keys onto defaults, ``from_json_file`` reads
    a JSON file; data-pipeline keys (vocab_file / tokenizer / lowercase) ride
    along inside the model config.
  - The runner config system of reference run_pretraining.py:75-177: argparse
    defaults are overridden by ``--config_file`` JSON values, which are in turn
    overridden by flags explicitly present on the command line.
"""

from __future__ import annotations

import argparse
import copy
import json
import sys
from typing import Any


class BertConfig:
    """Architecture configuration for the BERT model family.

    Mirrors reference src/modeling.py:188-295 (``BertConfig``): the same
    default values, dict/JSON constructors with merge semantics, and tolerance
    for extra keys (the reference stores tokenizer/data keys in the same file,
    run_pretraining.py:369-374).
    """

    def __init__(
        self,
        vocab_size: int = 30522,
        hidden_size: int = 768,
        num_hidden_layers: int = 12,
        num_attention_heads: int = 12,
        intermediate_size: int = 3072,
        hidden_act: str = "gelu",
        hidden_dropout_prob: float = 0.1,
        attention_probs_dropout_prob: float = 0.1,
        max_position_embeddings: int = 512,
        type_vocab_size: int = 2,
        initializer_range: float = 0.02,
        layer_norm_eps: float = 1e-12,
        next_sentence: bool = True,
        output_all_encoded_layers: bool = False,
        pad_token_id: int = 0,
        **extra: Any,
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        self.next_sentence = next_sentence
        self.output_all_encoded_layers = output_all_encoded_layers
        self.pad_token_id = pad_token_id
        # Extra keys (vocab_file, tokenizer, lowercase, ...) ride along so the
        # data path can read them from the same file.
        for key, value in extra.items():
            setattr(self, key, value)

    # -- constructors --------------------------------------------------------

    @classmethod
    def from_dict(cls, json_object: dict) -> "BertConfig":
        """Construct from a dict, merging onto defaults (modeling.py:255-261)."""
        config = cls()
        for key, value in json_object.items():
            setattr(config, key, value)
        return config

    @classmethod
    def from_json_file(cls, json_file: str) -> "BertConfig":
        with open(json_file, "r", encoding="utf-8") as reader:
            return cls.from_dict(json.loads(reader.read()))

    # -- serialization -------------------------------------------------------

    def to_dict(self) -> dict:
        return copy.deepcopy(self.__dict__)

    def to_json_string(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True) + "\n"

    def to_json_file(self, json_file: str) -> None:
        with open(json_file, "w", encoding="utf-8") as writer:
            writer.write(self.to_json_string())

    def __repr__(self) -> str:
        return f"BertConfig {self.to_json_string()}"

    # -- derived properties --------------------------------------------------

    @property
    def head_dim(self) -> int:
        if self.hidden_size % self.num_attention_heads != 0:
            raise ValueError(
                f"hidden_size {self.hidden_size} is not a multiple of "
                f"num_attention_heads {self.num_attention_heads}"
            )
        return self.hidden_size // self.num_attention_heads

    def padded_vocab_size(self, multiple: int = 8) -> int:
        """Vocab padded up for MXU-friendly tiling (run_pretraining.py:237-238
        pads to a multiple of 8; on TPU 128-lane alignment is natural but 8
        keeps checkpoint-shape parity)."""
        return ((self.vocab_size + multiple - 1) // multiple) * multiple


def parse_args_with_config_file(
    parser: argparse.ArgumentParser,
    argv: list[str] | None = None,
    config_file_flag: str = "--config_file",
) -> argparse.Namespace:
    """Three-level precedence: CLI flag > JSON config file > argparse default.

    Reimplements the mechanism of reference run_pretraining.py:159-177: a
    default-suppressing clone of the parser detects which flags were explicitly
    passed on the command line; JSON config values override defaults; explicit
    CLI flags override the JSON.
    """
    argv = list(sys.argv[1:] if argv is None else argv)
    args = parser.parse_args(argv)

    config_dest = config_file_flag.lstrip("-")
    config_path = getattr(args, config_dest, None)
    if not config_path:
        return args

    with open(config_path, "r", encoding="utf-8") as f:
        config_values = json.load(f)

    # Detect explicitly-passed flags with a default-suppressing aux parser.
    aux = argparse.ArgumentParser(argument_default=argparse.SUPPRESS, add_help=False)
    for action in parser._actions:
        if action.option_strings and not isinstance(action, argparse._HelpAction):
            kwargs: dict[str, Any] = {"dest": action.dest}
            if isinstance(
                action, (argparse._StoreTrueAction, argparse._StoreFalseAction)
            ):
                kwargs["action"] = "store_true"
            else:
                kwargs["type"] = action.type
                kwargs["nargs"] = action.nargs
            aux.add_argument(*action.option_strings, **kwargs)
    explicit, _ = aux.parse_known_args(argv)
    explicitly_set = set(vars(explicit).keys())

    known = {action.dest for action in parser._actions}
    for key, value in config_values.items():
        if key not in known:
            raise ValueError(f"Unknown key '{key}' in config file {config_path}")
        if key not in explicitly_set:
            setattr(args, key, value)
    return args


def require_args(args: argparse.Namespace, names: list[str]) -> None:
    """Required args may come from CLI or config file (run_pretraining.py:573-581)."""
    missing = [name for name in names if getattr(args, name, None) is None]
    if missing:
        raise ValueError(
            f"Missing required arguments (set via CLI or config file): {missing}"
        )
