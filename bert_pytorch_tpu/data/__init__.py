"""Data runtime: sharded HDF5 streaming, dynamic masking, samplers, loaders.

The TPU-host analog of the reference's data stack (SURVEY.md §2.1):
ShardedPretrainingDataset + contiguous DistributedSampler + a torch-free
prefetching DataLoader, plus sequence packing (data/packing.py,
docs/packing.md) for padding-free pretraining batches.
"""

from bert_pytorch_tpu.data.dataset import (
    LEGACY_FORMAT_KEYS,
    NEW_FORMAT_KEYS,
    ShardedPretrainingDataset,
)
from bert_pytorch_tpu.data.device_prefetch import DevicePrefetcher
from bert_pytorch_tpu.data.loader import (
    BATCH_KEYS,
    PACKED_EXTRA_KEYS,
    DataLoader,
)
from bert_pytorch_tpu.data.packing import (
    PACKED_FORMAT_KEYS,
    PackedPretrainingDataset,
    first_fit_decreasing,
    pack_features,
    write_packed_shard,
)
from bert_pytorch_tpu.data.sampler import DistributedSampler

__all__ = [
    "BATCH_KEYS",
    "DataLoader",
    "DevicePrefetcher",
    "DistributedSampler",
    "LEGACY_FORMAT_KEYS",
    "NEW_FORMAT_KEYS",
    "PACKED_EXTRA_KEYS",
    "PACKED_FORMAT_KEYS",
    "PackedPretrainingDataset",
    "ShardedPretrainingDataset",
    "first_fit_decreasing",
    "pack_features",
    "write_packed_shard",
]
