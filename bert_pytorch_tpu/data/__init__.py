"""Data runtime: sharded HDF5 streaming, dynamic masking, samplers, loaders.

The TPU-host analog of the reference's data stack (SURVEY.md §2.1):
ShardedPretrainingDataset + contiguous DistributedSampler + a torch-free
prefetching DataLoader.
"""

from bert_pytorch_tpu.data.dataset import (
    LEGACY_FORMAT_KEYS,
    NEW_FORMAT_KEYS,
    ShardedPretrainingDataset,
)
from bert_pytorch_tpu.data.loader import BATCH_KEYS, DataLoader
from bert_pytorch_tpu.data.sampler import DistributedSampler

__all__ = [
    "BATCH_KEYS",
    "DataLoader",
    "DistributedSampler",
    "LEGACY_FORMAT_KEYS",
    "NEW_FORMAT_KEYS",
    "ShardedPretrainingDataset",
]
