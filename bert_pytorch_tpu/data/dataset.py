"""Sharded HDF5 pretraining dataset with dynamic masking.

Behavioral parity with reference src/dataset.py:9-338
(``ShardedPretrainingDataset``): at most two shard files in RAM (current +
background-thread prefetch of the next), segment/input-mask derivation from
``special_token_positions``, dynamic masking with the 80/10/10 split, legacy
NVIDIA pre-masked format support, and warn-and-skip shard verification.
Offline-PACKED shards (sequence packing, data/packing.py / docs/packing.md)
are auto-detected: samples then carry sequence_ids/cls_positions and
per-sequence NSP labels, with dynamic masking run per packed member.

Deliberate deviations from the reference (SURVEY.md §7 "known quirks"):
  - mask positions are sampled WITHOUT replacement (the reference's
    ``np.random.choice`` default could duplicate positions, dataset.py:286);
  - masking draws come from a PER-SAMPLE generator seeded on
    ``(seed, epoch, index)`` instead of one sequential stream
    (dataset.py:122-123): draws for sample i no longer depend on how many
    samples were read before it, so a checkpoint-resumed run reproduces
    the exact masking an uninterrupted run would have applied (the
    property the chaos harness asserts, docs/fault_tolerance.md), worker
    processes decorrelate without per-worker reseeding, and epochs still
    re-draw (dynamic masking stays dynamic);
  - the in-file index is computed from the file start (the reference's
    ``idx -= file_sample_end_idx`` negative indexing, dataset.py:171, is
    equivalent but obscure);
  - HDF5 shard opens/reads retry with backoff (``utils/retry.py``) and a
    configurable skip-shard-vs-abort startup policy — transient storage
    errors cost a delay, not the run (docs/fault_tolerance.md).

No torch dependency: samples are numpy int32 arrays ready for
``jax.device_put`` batching.
"""

from __future__ import annotations

import os
import threading
import warnings
from typing import Callable, Optional, Sequence

import h5py
import numpy as np

from bert_pytorch_tpu.utils.retry import RetryPolicy, retry_call


class DataReadError(RuntimeError):
    """A shard read failed past the retry budget (or the startup
    verification failed under ``shard_error_policy='abort'``)."""


NEW_FORMAT_KEYS = ("input_ids", "special_token_positions", "next_sentence_labels")
LEGACY_FORMAT_KEYS = (
    "input_ids",
    "segment_ids",
    "input_mask",
    "masked_lm_positions",
    "masked_lm_ids",
    "next_sentence_labels",
)
# Offline-packed shards (data/packing.py write_packed_shard; docs/packing.md):
# several sequences share one row; samples gain sequence_ids/cls_positions
# and per-sequence NSP labels. Detected per dataset (mixing packed and
# unpacked shards is an error — the sample shapes differ).
PACKED_KEY = "packed_sequence_lengths"


class ShardedPretrainingDataset:
    """Streams sorted HDF5 shards keeping <= 2 files in memory.

    ``__getitem__`` expects forward-moving indices (per reader); use
    :class:`bert_pytorch_tpu.data.sampler.DistributedSampler`, which chunks
    contiguously. Forward skips (strided DataLoader workers) and cyclic
    wrap-around (epoch restarts, including mid-dataset chunk starts for
    ranks > 0) are supported; a genuinely random access pattern (shuffling
    sampler) is not an error but reloads shard files pathologically — the
    contiguity contract lives in the sampler (cf. the invariant check at
    reference dataset.py:161-169, which also rejected the legal multi-rank
    epoch restart).
    """

    def __init__(
        self,
        files: Sequence[str] | str,
        mask_token_index: Optional[int],
        max_pred_per_seq: int,
        masked_lm_prob: float,
        vocab_size: int,
        original_token_prob: float = 0.1,
        random_token_prob: float = 0.1,
        seed: Optional[int] = None,
        read_retries: int = 2,
        retry_base_delay_s: float = 0.2,
        shard_error_policy: str = "skip",
        on_fault: Optional[Callable[[dict], None]] = None,
    ):
        if mask_token_index is not None and not isinstance(mask_token_index, (int, np.integer)):
            raise ValueError("mask_token_index must be an integer")
        if not isinstance(max_pred_per_seq, (int, np.integer)) or max_pred_per_seq < 0:
            raise ValueError("max_pred_per_seq must be an integer >= 0")
        if not 0 <= masked_lm_prob <= 1:
            raise ValueError("masked_lm_prob must be in [0,1]")
        if not isinstance(vocab_size, (int, np.integer)) or vocab_size < 0:
            raise ValueError("vocab_size must be an integer >= 0")
        if not 0 <= original_token_prob <= 1:
            raise ValueError("original_token_prob must be in [0,1]")
        if not 0 <= random_token_prob <= 1:
            raise ValueError("random_token_prob must be in [0,1]")
        if random_token_prob + original_token_prob > 1:
            raise ValueError("random_token_prob + original_token_prob > 1")

        if shard_error_policy not in ("skip", "abort"):
            raise ValueError(
                f"shard_error_policy must be 'skip' or 'abort', got "
                f"{shard_error_policy!r}")
        # Data-path resilience knobs (docs/fault_tolerance.md): every HDF5
        # open/read goes through utils/retry.py with these bounds, and the
        # STARTUP verification applies the skip-vs-abort policy. A
        # mid-stream read that stays broken past the retries always raises
        # DataReadError — the index space is fixed at startup, so silently
        # dropping a shard then would feed wrong samples for its range.
        self.read_retries = max(0, int(read_retries))
        self.retry_base_delay_s = float(retry_base_delay_s)
        self.shard_error_policy = shard_error_policy
        self.on_fault = on_fault

        if isinstance(files, str):
            files = [files]
        files = sorted(files)  # all processes must agree on the order
        (self.files, self.file_idxs, self.packed,
         self.max_sequences_per_pack) = self._verify_and_count_samples(files)

        self.mask_token_index = mask_token_index
        self.max_pred_per_seq = int(max_pred_per_seq)
        self.masked_lm_prob = float(masked_lm_prob)
        self.vocab_size = int(vocab_size)
        self.original_token_prob = float(original_token_prob)
        self.random_token_prob = float(random_token_prob)
        self.seed = seed
        self.epoch = 0
        self._mask_seed_base = self._seed_base(seed)
        self._rng = np.random.default_rng(seed)

        self.file_idx: Optional[int] = None
        self.next_file_idx: Optional[int] = None
        self.file_sample_start_idx = -1
        self.file_sample_end_idx = -1
        self.data = None
        self._next_file_data = None
        self._next_file_error: Optional[BaseException] = None
        self._next_file_thread: Optional[threading.Thread] = None

    # -- pickling (DataLoader worker processes) ------------------------------

    def __getstate__(self):
        """Drop the streaming runtime (loaded shard data, prefetch thread):
        a worker process re-streams from its own file handles. The fault
        hook is dropped too (a telemetry emit closure doesn't pickle;
        workers fall back to warnings). Masking draws need no per-worker
        reseeding — they derive from (seed, epoch, index)."""
        state = self.__dict__.copy()
        for k in ("data", "_next_file_data", "_next_file_thread", "_rng",
                  "_next_file_error", "on_fault"):
            state[k] = None
        state["file_idx"] = None
        state["next_file_idx"] = None
        state["file_sample_start_idx"] = -1
        state["file_sample_end_idx"] = -1
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._rng = np.random.default_rng(self.seed)

    @staticmethod
    def _seed_base(seed: Optional[int]) -> int:
        """Base entropy for the per-sample masking derivation. ``None``
        keeps its pre-PR-5 meaning — fresh OS entropy per dataset, so
        unseeded runs draw run-unique masks instead of silently colliding
        with seed=0. The base is pickled to worker processes, so every
        reader of one dataset instance still agrees per index."""
        if seed is not None:
            return int(seed) % (2 ** 63)
        return int(np.random.SeedSequence().entropy) % (2 ** 63)

    def reseed(self, seed: Optional[int]) -> None:
        self.seed = seed
        self._mask_seed_base = self._seed_base(seed)
        self._rng = np.random.default_rng(seed)

    # -- epoch / size --------------------------------------------------------

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.file_idxs[-1][1]

    # -- streaming -----------------------------------------------------------

    def __getitem__(self, idx: int):
        if self.data is None:
            # First access: infer the starting file from idx and prefetch it.
            self.next_file_idx = self._file_idx_for(idx)
            self._next_file_thread = self._async_load_file(self.next_file_idx)

        if not (self.file_sample_start_idx <= idx < self.file_sample_end_idx):
            # Walk the cyclic file sequence forward to the file holding idx.
            # Multiple swaps: a strided reader (a DataLoader worker taking
            # every Nth batch) may skip past an entire small shard in one
            # step. Cyclic: an epoch restart (rank-chunk end -> chunk start,
            # possibly mid-dataset for ranks > 0) walks through the wrap —
            # the previous one-swap-only logic raised on exactly that legal
            # multi-rank restart. The access contract (contiguous forward
            # chunks) is owned by DistributedSampler; a shuffling sampler
            # here degrades to pathological full-file reloads per access
            # rather than an error.
            target = self._file_idx_for(idx)  # raises if idx >= len(self)
            while self.file_idx != target:
                # Swap in the prefetched file; start loading its successor.
                del self.data  # drop the old shard before holding two new
                self._next_file_thread.join()
                if self._next_file_error is not None:
                    # The prefetch thread exhausted the retry budget; a
                    # swallowed error here would surface later as a
                    # baffling KeyError on stale/None data.
                    error, self._next_file_error = self._next_file_error, None
                    self.data = None
                    raise DataReadError(
                        f"shard load failed past "
                        f"{self.read_retries + 1} attempt(s): "
                        f"{type(error).__name__}: {error}") from error
                self.data = self._next_file_data
                self.file_idx = self.next_file_idx
                self.next_file_idx = (self.next_file_idx + 1) % len(self.files)
                self._next_file_thread = self._async_load_file(self.next_file_idx)
                (self.file_sample_start_idx,
                 self.file_sample_end_idx) = self.file_idxs[self.file_idx]

        # Per-sample masking generator, derived from (seed, epoch, index):
        # sample i's draws are independent of read order and worker
        # topology, so a resumed run masks exactly like an uninterrupted
        # one (module docstring; docs/fault_tolerance.md). seed=None uses
        # a per-instance random base (see _seed_base).
        self._rng = np.random.default_rng(
            (self._mask_seed_base, int(self.epoch), int(idx)))

        local = idx - self.file_sample_start_idx
        input_ids = np.array(self.data["input_ids"][local])
        next_sentence_label = np.asarray(self.data["next_sentence_labels"][local])

        if self.packed:
            return self._packed_item(local, input_ids, next_sentence_label)
        if "special_token_positions" in self.data:
            special = np.asarray(self.data["special_token_positions"][local])
            segment_ids = self._get_segment_ids(input_ids, special)
            input_mask = self._get_input_mask(input_ids, special)
            masked_input_ids, masked_lm_labels = self._mask_input(input_ids, special)
        else:
            # Legacy NVIDIA pre-masked format (reference dataset.py:184-192).
            segment_ids = np.asarray(self.data["segment_ids"][local])
            input_mask = np.asarray(self.data["input_mask"][local])
            positions = np.asarray(self.data["masked_lm_positions"][local])
            ids = np.asarray(self.data["masked_lm_ids"][local])
            masked_input_ids = input_ids
            masked_lm_labels = self._get_masked_labels(input_ids, positions, ids)

        return [
            masked_input_ids.astype(np.int32),
            segment_ids.astype(np.int32),
            input_mask.astype(np.int32),
            masked_lm_labels.astype(np.int32),
            next_sentence_label.astype(np.int32),
        ]

    def _packed_item(self, local: int, input_ids, nsp_labels):
        """One offline-packed row (data/packing.py layout): re-derive
        sequence_ids/segments/cls positions from the per-member lengths and
        run dynamic masking per member — the same draws a member would get
        unpacked, just rebased onto its offset in the row."""
        lengths = np.asarray(self.data[PACKED_KEY][local], np.int64)
        specials_all = np.asarray(
            self.data["packed_special_token_positions"][local], np.int64)
        nsp_labels = np.asarray(nsp_labels, np.int64).reshape(-1)
        k_max = self.max_sequences_per_pack
        seq_len = input_ids.shape[0]

        segment_ids = np.zeros_like(input_ids)
        input_mask = np.zeros_like(input_ids)
        sequence_ids = np.zeros_like(input_ids)
        labels = np.full_like(input_ids, -1)
        nsp = np.full(k_max, -1, np.int32)
        cls_positions = np.zeros(k_max, np.int32)

        offset = 0
        for k, n in enumerate(lengths):
            n = int(n)
            span = slice(offset, offset + n)
            sequence_ids[span] = k + 1
            input_mask[span] = 1
            cls_positions[k] = offset
            nsp[k] = int(nsp_labels[k])
            member_specials = (
                specials_all[(specials_all >= offset)
                             & (specials_all < offset + n)] - offset)
            if len(member_specials) == 3:
                # [CLS] a [SEP] b [SEP]: second segment gets type 1
                # (the unpacked _get_segment_ids rule, rebased).
                segment_ids[offset + member_specials[1] + 1:
                            offset + member_specials[2] + 1] = 1
            ids_view = input_ids[span]
            _, member_labels = self._mask_input(ids_view, member_specials)
            labels[span] = member_labels
            offset += n
        assert offset <= seq_len, (offset, seq_len)

        return [
            input_ids.astype(np.int32),
            segment_ids.astype(np.int32),
            input_mask.astype(np.int32),
            labels.astype(np.int32),
            nsp.astype(np.int32),
            sequence_ids.astype(np.int32),
            cls_positions.astype(np.int32),
        ]

    def _file_idx_for(self, idx: int) -> int:
        for i, (start, end) in enumerate(self.file_idxs):
            if start <= idx < end:
                return i
        raise ValueError(f"idx ({idx}) exceeds dataset size ({len(self)})")

    def _async_load_file(self, file_idx: int) -> threading.Thread:
        self._next_file_error = None
        th = threading.Thread(
            target=self._load_hdf5, args=(self.files[file_idx],), daemon=True
        )
        th.start()
        return th

    # -- resilient shard IO (docs/fault_tolerance.md) ------------------------

    def _emit_fault(self, record: dict) -> None:
        """Best-effort fault telemetry (run_pretraining wires the JSONL
        sink in via ``on_fault``); never let an emit failure mask the IO
        error being reported."""
        if self.on_fault is None:
            return
        try:
            self.on_fault(record)
        except Exception:
            pass

    def _retry_policy(self) -> RetryPolicy:
        return RetryPolicy(attempts=self.read_retries + 1,
                           base_delay_s=self.retry_base_delay_s)

    def _read_shard(self, filepath: str, reader: Callable) -> dict:
        """Run ``reader(h5py.File)`` with retry/backoff; transient storage
        errors (and armed fault injections, testing/faults.py) cost a
        delay, a warning, and a ``fault`` telemetry record — not the run.
        """
        def attempt():
            from bert_pytorch_tpu.testing import faults
            faults.get_plan().shard_read_check(
                filepath, emit=self._emit_fault)
            with h5py.File(filepath, "r") as f:
                return reader(f)

        def on_retry(n, exc, delay):
            warnings.warn(
                f"shard read of {filepath} failed (attempt {n}: "
                f"{type(exc).__name__}: {exc}); retrying in {delay:.2f}s")
            self._emit_fault({
                "kind": "fault", "tag": "telemetry",
                "fault": "shard_read_retry", "injected": False,
                "path": filepath, "attempt": n,
                "error": f"{type(exc).__name__}: {exc}"})

        return retry_call(attempt, policy=self._retry_policy(),
                          on_retry=on_retry,
                          description=f"shard read {filepath}")

    def _load_hdf5(self, filepath: str) -> None:
        try:
            self._next_file_data = self._read_shard(
                filepath,
                lambda f: {key: np.asarray(f[key][:]) for key in f.keys()})
        except BaseException as e:
            # Runs on the prefetch thread: park the error for the swap in
            # __getitem__ to re-raise (a daemon thread's traceback would
            # otherwise vanish and the consumer would read stale data).
            self._next_file_error = e

    # -- feature derivation (reference dataset.py:224-296) -------------------

    @staticmethod
    def _get_segment_ids(input_ids, special_token_positions):
        """[CLS] a... [SEP] b... [SEP] pad -> 0 0...0 0 1...1 1 0...0
        (reference dataset.py:224-238)."""
        segment_ids = np.zeros_like(input_ids)
        if len(special_token_positions) == 3:
            segment_ids[
                special_token_positions[1] + 1 : special_token_positions[2] + 1
            ] = 1
        return segment_ids

    @staticmethod
    def _get_input_mask(input_ids, special_token_positions):
        """1 through the final [SEP], 0 on padding (dataset.py:240-252)."""
        input_mask = np.zeros_like(input_ids)
        input_mask[: special_token_positions[-1] + 1] = 1
        return input_mask

    @staticmethod
    def _get_masked_labels(input_ids, masked_lm_positions, masked_lm_ids):
        """Scatter true ids at masked positions, -1 elsewhere
        (legacy format; dataset.py:254-275)."""
        labels = np.full_like(input_ids, -1)
        index = len(input_ids)
        padded = np.nonzero(masked_lm_positions == 0)[0]
        if len(padded) != 0:
            index = padded[0]
        labels[masked_lm_positions[:index]] = masked_lm_ids[:index]
        return labels

    def _mask_input(self, input_ids, special_token_positions):
        """Dynamic masking (dataset.py:277-296): choose up to
        min(max_pred, max(1, round-down of len*prob)) non-special positions;
        each keeps its token w.p. original_token_prob, becomes random w.p.
        random_token_prob, else [MASK].

        Fully vectorized: this runs per sample on the host data path and was
        the pipeline's hot spot as a Python loop (~80% of __getitem__; the
        numpy form is ~10x faster, which is what lets one producer feed
        multiple chips — see tools/bench_loader.py for measured rates).
        """
        masked_lm_labels = np.full_like(input_ids, -1)
        candidates = np.arange(int(special_token_positions[-1]))
        candidates = candidates[
            ~np.isin(candidates, np.asarray(special_token_positions))
        ]
        if candidates.size == 0:
            return input_ids, masked_lm_labels
        mask_count = min(
            self.max_pred_per_seq,
            max(1, int(candidates.size * self.masked_lm_prob)),
        )
        mask_indices = self._rng.choice(
            candidates, size=min(mask_count, candidates.size), replace=False
        )
        masked_lm_labels[mask_indices] = input_ids[mask_indices]
        draws = self._rng.random(mask_indices.size)
        rand_sel = mask_indices[
            (draws >= self.original_token_prob)
            & (draws < self.original_token_prob + self.random_token_prob)
        ]
        mask_sel = mask_indices[
            draws >= self.original_token_prob + self.random_token_prob
        ]
        if rand_sel.size:
            input_ids[rand_sel] = self._rng.integers(
                0, self.vocab_size - 1, size=rand_sel.size
            )
        input_ids[mask_sel] = self.mask_token_index
        return input_ids, masked_lm_labels

    # -- shard verification (dataset.py:298-338) -----------------------------

    def _verify_and_count_samples(self, files):
        """Open every shard (with retry) and count samples. Unreadable
        shards follow ``shard_error_policy``: 'skip' (default) keeps the
        reference's warn-and-skip stance; 'abort' raises — a run that
        would rather fail fast than silently train on a subset."""
        current_idx = 0
        verified_files, verified_idxs = [], []
        packed_flags, pack_limits = [], []
        keys = ["input_ids", "next_sentence_labels"]

        def skip_or_abort(fpath, why):
            if self.shard_error_policy == "abort":
                raise DataReadError(
                    f"{why} (shard_error_policy='abort'): {fpath}")
            warnings.warn(f"{why}: {fpath}. Skipping File")
            self._emit_fault({
                "kind": "fault", "tag": "telemetry", "fault": "shard_skipped",
                "injected": False, "path": fpath, "error": why})

        def read_counts(f):
            counts = [len(f[key]) for key in keys]
            is_packed = PACKED_KEY in f
            pack_limit = 0
            if is_packed:
                from bert_pytorch_tpu.data.packing import (
                    PACKED_MAX_SEQUENCES_ATTR)
                pack_limit = int(f.attrs[PACKED_MAX_SEQUENCES_ATTR])
            return counts, is_packed, pack_limit

        for fpath in files:
            if not os.path.isfile(fpath):
                skip_or_abort(fpath, "File not found")
                continue
            try:
                counts, is_packed, pack_limit = self._read_shard(
                    fpath, read_counts)
            except Exception:
                skip_or_abort(fpath, f"Unable to read keys ({keys})")
                continue
            if len(set(counts)) != 1:
                skip_or_abort(
                    fpath, "Number of samples per key do not match")
                continue
            verified_files.append(fpath)
            verified_idxs.append((current_idx, current_idx + counts[0]))
            packed_flags.append(is_packed)
            if is_packed:
                # Only VERIFIED shards may shape the dataset-wide pack
                # limit (a rejected shard contributes zero samples and
                # must not widen every [B, K] batch array).
                pack_limits.append(pack_limit)
            current_idx += counts[0]
        if not verified_files:
            raise RuntimeError("Unable to open any valid data files")
        if len(set(packed_flags)) > 1:
            # Packed and unpacked samples have different shapes; one batch
            # cannot hold both, and silently dropping either set would skew
            # the data distribution.
            raise ValueError(
                "cannot mix packed and unpacked shards in one dataset: "
                f"packed={[f for f, p in zip(verified_files, packed_flags) if p]}")
        packed = packed_flags[0]
        return (verified_files, verified_idxs, packed,
                max(pack_limits) if packed else 0)
