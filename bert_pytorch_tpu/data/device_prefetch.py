"""Double-buffered device prefetch: the stage between the host loader
queue and the training loop.

``data/loader.py`` keeps host batches ahead of the loop; this module keeps
them ahead ON DEVICE. A background thread pulls host items, runs the
caller's ``stage`` function (typically ``put_batch`` with the step's input
shardings — an async dispatch, so on real accelerators the H2D transfer
overlaps device compute), and keeps up to ``depth`` staged batches in a
queue. The training loop's ``data_wait`` then measures only true producer
stalls: with a fast producer the queue is never empty and data_wait p50
drops to ~0; with a slow producer the stall still lands in data_wait,
correctly attributed.

Telemetry attribution (docs/telemetry.md): the thread records, per batch,
the time it blocked on the HOST producer and the time it spent in the
staging call. When the consumer blocks on an empty queue, the delivered
batch's staging time bounds how much of that wait was H2D work:
``pop_h2d_wait_s`` returns ``min(consumer_wait, stage_time)`` — by
construction never more than the step's data_wait, which is what lets the
schema lint pin ``h2d_wait <= data_wait``. (On a synchronous backend like
CPU the staging call IS the copy; on TPU it is the dispatch, and a staged
batch that has not finished transferring simply parks the wait inside the
next step's device phase, where overlap hides it.)

``depth <= 0`` degrades to inline staging on the consumer thread — same
iterator contract and gauges, no background thread — so one code path
serves ``--device_prefetch 0`` everywhere.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator

from bert_pytorch_tpu.data.loader import _bounded_put


def add_cli_args(parser, default: int = 2) -> None:
    """Register the one shared device-prefetch flag (every runner)."""
    parser.add_argument(
        "--device_prefetch", type=int, default=default,
        help="batches staged ahead ON DEVICE (double-buffered host->device "
             "transfer, data/device_prefetch.py): the H2D copy overlaps "
             "device compute and telemetry's data_wait measures only true "
             "producer stalls (an h2d_wait sub-phase reports the staging "
             "share). 0 stages inline on the loop thread (no overlap)")


class DevicePrefetcher:
    """One-shot iterator of device-resident items staged ``depth`` ahead.

    ``source`` yields host items; ``stage(item)`` moves one to device
    (e.g. ``pretrain.put_batch`` with the step's input shardings). Errors
    from either surface at the consumer's ``next()``. Call ``close()``
    when abandoning the iterator mid-epoch (the runners do, in their
    ``finally``): it sets the stop event — which aborts a thread parked
    in its blocked put — and briefly joins; a thread stuck inside an
    uninterruptible ``next(source)`` is left to daemon teardown but will
    not touch the staging fn again (see :meth:`close`).
    """

    def __init__(self, source: Iterable, stage: Callable, depth: int = 2,
                 clock: Callable[[], float] = time.perf_counter):
        self._source = iter(source)
        self._stage = stage
        self.depth = int(depth)
        self._clock = clock
        self._queue: queue.Queue = queue.Queue(maxsize=max(1, self.depth))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._done = False
        self._last_h2d_wait_s = 0.0
        self._reset_stats()

    # -- gauges (window "prefetch" sub-object, telemetry/runner.py) -----

    def _reset_stats(self) -> None:
        self._stats = {"batches": 0, "wait_s_total": 0.0,
                       "h2d_wait_s_total": 0.0, "h2d_s_total": 0.0,
                       "producer_wait_s_total": 0.0, "depth_max": 0}

    def snapshot(self):
        """Gauges accumulated since the previous snapshot (None when no
        batches were delivered in the interval)."""
        s = self._stats
        if s["batches"] == 0:
            return None
        out = {"batches": s["batches"],
               "wait_s_total": round(s["wait_s_total"], 6),
               "h2d_wait_s_total": round(s["h2d_wait_s_total"], 6),
               "h2d_s_total": round(s["h2d_s_total"], 6),
               "producer_wait_s_total": round(s["producer_wait_s_total"], 6),
               "depth_max": s["depth_max"]}
        self._reset_stats()
        return out

    def pop_h2d_wait_s(self) -> float:
        """H2D-attributable share of the wait for the batch just
        delivered (consumed by TrainTelemetry.timed -> note_h2d)."""
        value, self._last_h2d_wait_s = self._last_h2d_wait_s, 0.0
        return value

    # -- producer thread ------------------------------------------------

    def _produce(self) -> None:
        while not self._stop.is_set():
            t0 = self._clock()
            try:
                item = next(self._source)
            except StopIteration:
                break
            except BaseException as e:  # surfaced at the consumer's next()
                _bounded_put(self._queue, (e, 0.0, 0.0), self._stop)
                return
            if self._stop.is_set():
                # close() raced the blocking pull above: never call the
                # staging fn (a device dispatch) on an abandoned
                # prefetcher — the consumer may be tearing the runtime
                # down.
                return
            t1 = self._clock()
            try:
                staged = self._stage(item)
            except BaseException as e:
                _bounded_put(self._queue, (e, 0.0, 0.0), self._stop)
                return
            t2 = self._clock()
            if not _bounded_put(self._queue, (staged, t1 - t0, t2 - t1),
                                self._stop):
                return
        _bounded_put(self._queue, (None, 0.0, 0.0), self._stop)

    # -- consumer protocol ----------------------------------------------

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        if self.depth <= 0:
            return self._next_inline()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._produce, name="device-prefetch", daemon=True)
            self._thread.start()
        t0 = self._clock()
        depth = self._queue.qsize()
        item, producer_wait_s, h2d_s = self._queue.get()
        wait_s = self._clock() - t0
        if item is None:
            self.close()
            raise StopIteration
        if isinstance(item, BaseException):
            self.close()
            raise item
        self._observe(wait_s, min(wait_s, h2d_s), h2d_s,
                      producer_wait_s, depth)
        return item

    def _next_inline(self):
        """depth<=0: pull + stage on the consumer thread. The whole
        staging call is consumer wait, so the attribution is exact; a
        producer or staging error closes the iterator exactly like the
        threaded path (no silent skip-and-resume past a failed item)."""
        t0 = self._clock()
        try:
            item = next(self._source)
        except StopIteration:
            self._done = True
            raise
        except BaseException:
            self._done = True
            raise
        t1 = self._clock()
        try:
            staged = self._stage(item)
        except BaseException:
            self._done = True
            raise
        t2 = self._clock()
        self._observe(t2 - t0, t2 - t1, t2 - t1, t1 - t0, 0)
        return staged

    def _observe(self, wait_s, h2d_wait_s, h2d_s, producer_wait_s,
                 depth) -> None:
        self._last_h2d_wait_s = h2d_wait_s
        s = self._stats
        s["batches"] += 1
        s["wait_s_total"] += wait_s
        s["h2d_wait_s_total"] += h2d_wait_s
        s["h2d_s_total"] += h2d_s
        s["producer_wait_s_total"] += producer_wait_s
        s["depth_max"] = max(s["depth_max"], depth)

    def close(self) -> None:
        """Stop the producer. The short join covers the common case (the
        thread is parked in the queue put, which aborts on the stop
        event); a thread blocked inside ``next(source)`` — an
        uninterruptible pull from the host loader — is abandoned to
        daemon-thread teardown instead of burning a preemption grace
        budget on a long join (it exits at the stop check before ever
        touching the staging fn again)."""
        self._done = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None
