"""GLUE task processors, featurization, and metrics.

Beyond-reference capability: the reference ships a GLUE *downloader*
(utils/download.py:81-101) but no GLUE runner — the TSVs it fetches can't be
consumed anywhere in that repo. This module closes the loop: per-task readers
for the standard GLUE TSV layouts (the format produced by the community
``download_glue_data.py`` script the downloader drives), sentence-pair
featurization in the [CLS] A [SEP] B [SEP] convention of the model library
(models/bert.py ``BertForSequenceClassification``), and the official GLUE
per-task metrics (accuracy, F1, Matthews correlation, Pearson/Spearman) in
plain numpy.

Task name → processor registry in :data:`PROCESSORS`; ``sts-b`` is the one
regression task (``num_labels == 1``, MSE loss in the runner).
"""

from __future__ import annotations

import csv
import dataclasses
import os
from typing import List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class InputExample:
    guid: str
    text_a: str
    text_b: Optional[str] = None
    label: Optional[str] = None


@dataclasses.dataclass
class InputFeatures:
    input_ids: np.ndarray  # [S] int32
    input_mask: np.ndarray  # [S] int32
    segment_ids: np.ndarray  # [S] int32
    label: float  # class index, or the raw score for regression


def _read_tsv(path: str, quotechar: Optional[str] = None) -> List[List[str]]:
    with open(path, encoding="utf-8-sig") as f:
        return [
            line
            for line in csv.reader(f, delimiter="\t", quotechar=quotechar)
            if line
        ]


class DataProcessor:
    """One GLUE task: how to parse its TSVs and what its labels/metric are."""

    #: column spec, overridden per task
    labels: Sequence[str] = ("0", "1")
    metric: str = "accuracy"
    regression: bool = False
    train_file = "train.tsv"
    dev_file = "dev.tsv"

    def get_train_examples(self, data_dir: str) -> List[InputExample]:
        return self._create_examples(
            _read_tsv(os.path.join(data_dir, self.train_file)), "train"
        )

    def get_dev_examples(self, data_dir: str) -> List[InputExample]:
        return self._create_examples(
            _read_tsv(os.path.join(data_dir, self.dev_file)), "dev"
        )

    def _create_examples(self, rows, set_type) -> List[InputExample]:
        raise NotImplementedError


class ColaProcessor(DataProcessor):
    """CoLA: no header; [source, label, author-mark, sentence]."""

    metric = "matthews"

    def _create_examples(self, rows, set_type):
        return [
            InputExample(f"{set_type}-{i}", row[3], None, row[1])
            for i, row in enumerate(rows)
        ]


class Sst2Processor(DataProcessor):
    """SST-2: header; [sentence, label]."""

    def _create_examples(self, rows, set_type):
        return [
            InputExample(f"{set_type}-{i}", row[0], None, row[1])
            for i, row in enumerate(rows[1:])
        ]


class MrpcProcessor(DataProcessor):
    """MRPC: header; [Quality, #1 ID, #2 ID, #1 String, #2 String]."""

    metric = "acc_and_f1"

    def _create_examples(self, rows, set_type):
        return [
            InputExample(f"{set_type}-{i}", row[3], row[4], row[0])
            for i, row in enumerate(rows[1:])
        ]


class StsbProcessor(DataProcessor):
    """STS-B: header; sentence1/sentence2 at 7/8, score at 9. Regression."""

    labels = ()
    metric = "pearson_and_spearman"
    regression = True

    def _create_examples(self, rows, set_type):
        return [
            InputExample(f"{set_type}-{i}", row[7], row[8], row[9])
            for i, row in enumerate(rows[1:])
        ]


class QqpProcessor(DataProcessor):
    """QQP: header; question1/question2 at 3/4, is_duplicate at 5."""

    metric = "acc_and_f1"

    def _create_examples(self, rows, set_type):
        examples = []
        for i, row in enumerate(rows[1:]):
            if len(row) < 6:  # a handful of malformed rows exist in the dump
                continue
            examples.append(
                InputExample(f"{set_type}-{i}", row[3], row[4], row[5])
            )
        return examples


class MnliProcessor(DataProcessor):
    """MNLI matched: header; sentence1/sentence2 at 8/9, gold label last."""

    labels = ("contradiction", "entailment", "neutral")
    dev_file = "dev_matched.tsv"

    def _create_examples(self, rows, set_type):
        return [
            InputExample(f"{set_type}-{i}", row[8], row[9], row[-1])
            for i, row in enumerate(rows[1:])
        ]


class MnliMismatchedProcessor(MnliProcessor):
    dev_file = "dev_mismatched.tsv"


class QnliProcessor(DataProcessor):
    """QNLI: header; [index, question, sentence, label]."""

    labels = ("entailment", "not_entailment")

    def _create_examples(self, rows, set_type):
        return [
            InputExample(f"{set_type}-{i}", row[1], row[2], row[3])
            for i, row in enumerate(rows[1:])
        ]


class RteProcessor(QnliProcessor):
    """RTE: same layout as QNLI ([index, sentence1, sentence2, label])."""


class WnliProcessor(DataProcessor):
    """WNLI: header; [index, sentence1, sentence2, label]."""

    def _create_examples(self, rows, set_type):
        return [
            InputExample(f"{set_type}-{i}", row[1], row[2], row[3])
            for i, row in enumerate(rows[1:])
        ]


PROCESSORS = {
    "cola": ColaProcessor,
    "sst-2": Sst2Processor,
    "mrpc": MrpcProcessor,
    "sts-b": StsbProcessor,
    "qqp": QqpProcessor,
    "mnli": MnliProcessor,
    "mnli-mm": MnliMismatchedProcessor,
    "qnli": QnliProcessor,
    "rte": RteProcessor,
    "wnli": WnliProcessor,
}


def _encode_ids(tokenizer, text: str) -> List[int]:
    return tokenizer.encode(text, add_special_tokens=False).ids


def _truncate_pair(tokens_a: List[int], tokens_b: List[int], max_len: int):
    """Truncate the longer sequence first, one token at a time — the
    length-budgeting convention BERT sentence-pair tasks use (keeps the more
    informative short side intact)."""
    while len(tokens_a) + len(tokens_b) > max_len:
        if len(tokens_a) > len(tokens_b):
            tokens_a.pop()
        else:
            tokens_b.pop()


def convert_examples_to_features(
    examples: Sequence[InputExample],
    tokenizer,
    max_seq_length: int,
    label_list: Sequence[str],
    regression: bool = False,
) -> List[InputFeatures]:
    label_map = {label: i for i, label in enumerate(label_list)}
    cls_id = tokenizer.token_to_id("[CLS]")
    sep_id = tokenizer.token_to_id("[SEP]")
    features = []
    for example in examples:
        ids_a = _encode_ids(tokenizer, example.text_a)
        ids_b = _encode_ids(tokenizer, example.text_b) if example.text_b else []
        if ids_b:
            _truncate_pair(ids_a, ids_b, max_seq_length - 3)
        else:
            ids_a = ids_a[: max_seq_length - 2]

        input_ids = [cls_id] + ids_a + [sep_id]
        segment_ids = [0] * len(input_ids)
        if ids_b:
            input_ids += ids_b + [sep_id]
            segment_ids += [1] * (len(ids_b) + 1)
        input_mask = [1] * len(input_ids)

        pad = max_seq_length - len(input_ids)
        input_ids += [0] * pad
        input_mask += [0] * pad
        segment_ids += [0] * pad

        if example.label is None:
            label = 0.0
        elif regression:
            label = float(example.label)
        else:
            label = float(label_map[example.label])
        features.append(
            InputFeatures(
                input_ids=np.asarray(input_ids, np.int32),
                input_mask=np.asarray(input_mask, np.int32),
                segment_ids=np.asarray(segment_ids, np.int32),
                label=label,
            )
        )
    return features


def features_to_arrays(features: Sequence[InputFeatures], regression: bool):
    return {
        "input_ids": np.stack([f.input_ids for f in features]),
        "input_mask": np.stack([f.input_mask for f in features]),
        "segment_ids": np.stack([f.segment_ids for f in features]),
        "labels": np.asarray(
            [f.label for f in features],
            np.float32 if regression else np.int32,
        ),
    }


# ---------------------------------------------------------------------------
# Metrics (official GLUE per-task definitions, numpy)


def accuracy(preds: np.ndarray, labels: np.ndarray) -> dict:
    return {"accuracy": float((preds == labels).mean())}


def acc_and_f1(preds: np.ndarray, labels: np.ndarray) -> dict:
    acc = float((preds == labels).mean())
    tp = float(np.sum((preds == 1) & (labels == 1)))
    fp = float(np.sum((preds == 1) & (labels == 0)))
    fn = float(np.sum((preds == 0) & (labels == 1)))
    precision = tp / (tp + fp) if tp + fp else 0.0
    recall = tp / (tp + fn) if tp + fn else 0.0
    f1 = (
        2 * precision * recall / (precision + recall)
        if precision + recall
        else 0.0
    )
    return {"accuracy": acc, "f1": f1, "acc_and_f1": (acc + f1) / 2}


def matthews(preds: np.ndarray, labels: np.ndarray) -> dict:
    tp = float(np.sum((preds == 1) & (labels == 1)))
    tn = float(np.sum((preds == 0) & (labels == 0)))
    fp = float(np.sum((preds == 1) & (labels == 0)))
    fn = float(np.sum((preds == 0) & (labels == 1)))
    denom = np.sqrt((tp + fp) * (tp + fn) * (tn + fp) * (tn + fn))
    return {"matthews": float((tp * tn - fp * fn) / denom) if denom else 0.0}


def _pearson(x: np.ndarray, y: np.ndarray) -> float:
    x = x - x.mean()
    y = y - y.mean()
    denom = np.sqrt((x * x).sum() * (y * y).sum())
    return float((x * y).sum() / denom) if denom else 0.0


def _rank(x: np.ndarray) -> np.ndarray:
    """Average ranks (ties share the mean of their rank range)."""
    order = np.argsort(x, kind="stable")
    ranks = np.empty(len(x), np.float64)
    ranks[order] = np.arange(1, len(x) + 1, dtype=np.float64)
    for value in np.unique(x):
        mask = x == value
        if mask.sum() > 1:
            ranks[mask] = ranks[mask].mean()
    return ranks


def pearson_and_spearman(preds: np.ndarray, labels: np.ndarray) -> dict:
    pearson = _pearson(preds.astype(np.float64), labels.astype(np.float64))
    spearman = _pearson(_rank(preds), _rank(labels))
    return {
        "pearson": pearson,
        "spearman": spearman,
        "corr": (pearson + spearman) / 2,
    }


METRICS = {
    "accuracy": accuracy,
    "acc_and_f1": acc_and_f1,
    "matthews": matthews,
    "pearson_and_spearman": pearson_and_spearman,
}


def compute_metrics(task: str, preds: np.ndarray, labels: np.ndarray) -> dict:
    return METRICS[PROCESSORS[task].metric](preds, labels)
