"""Torch-free batching loader with background prefetch.

Replaces the reference's ``torch.utils.data.DataLoader(num_workers=4,
pin_memory=True)`` (run_pretraining.py:394-395): a producer thread walks the
sampler, pulls samples from the dataset (whose own background thread streams
shard files), collates numpy batches, and keeps a small queue ahead of the
training loop so host-side dynamic masking overlaps device compute — the
TPU-feeding strategy called out in SURVEY.md §7 "hard parts".

``drop_last`` defaults to True: XLA-jitted steps want static batch shapes, so
ragged tail batches (which the reference tolerates, SURVEY §2.1) would force
a recompile for one step.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional

import numpy as np

BATCH_KEYS = (
    "input_ids",
    "segment_ids",
    "input_mask",
    "masked_lm_labels",
    "next_sentence_labels",
)


class DataLoader:
    def __init__(
        self,
        dataset,
        sampler,
        batch_size: int,
        drop_last: bool = True,
        prefetch_batches: int = 2,
    ):
        self.dataset = dataset
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        self.prefetch_batches = prefetch_batches

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[dict]:
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_batches)
        stop = threading.Event()

        def put(item) -> bool:
            """Bounded put that aborts when the consumer is gone — a plain
            q.put() blocks forever once the consumer breaks out of the
            iterator with the queue full (the finally-block's stop.set()
            can't unblock a thread already inside q.put), leaking one
            producer thread and its buffered batches per abandoned
            iteration (e.g. every early-stopped validation pass)."""
            while True:
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    if stop.is_set():
                        return False

        def produce():
            samples = []
            try:
                for idx in self.sampler:
                    if stop.is_set():
                        return
                    samples.append(self.dataset[idx])
                    if len(samples) == self.batch_size:
                        if not put(self._collate(samples)):
                            return
                        samples = []
                if samples and not self.drop_last:
                    if not put(self._collate(samples)):
                        return
            except BaseException as e:  # surface worker errors to the consumer
                put(e)
                return
            put(None)

        worker = threading.Thread(target=produce, daemon=True)
        worker.start()
        try:
            while True:
                item = q.get()
                if item is None:
                    return
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()

    @staticmethod
    def _collate(samples) -> dict:
        arrays = [np.stack([s[i] for s in samples]) for i in range(len(BATCH_KEYS))]
        return dict(zip(BATCH_KEYS, arrays))
