"""Torch-free batching loader with background prefetch.

Replaces the reference's ``torch.utils.data.DataLoader(num_workers=4,
pin_memory=True)`` (run_pretraining.py:394-395): a producer walks the
sampler, pulls samples from the dataset (whose own background thread streams
shard files), collates numpy batches, and keeps a small queue ahead of the
training loop so host-side dynamic masking overlaps device compute — the
TPU-feeding strategy called out in SURVEY.md §7 "hard parts".

``num_workers=0`` (default) produces on one background THREAD. With the
vectorized masking path this measures 13.1k seq/s at the phase-1 shape
(seq 128, batch 64) and 11.1k seq/s at phase-2 (seq 512) on this image —
32x / 132x one v5e chip's consumption, i.e. enough for a full 8-chip
host (tools/bench_loader.py reproduces the numbers).
``num_workers=N`` matches the reference's multi-worker process scaling:
N spawned PROCESSES each produce every Nth batch (torch's round-robin
batch assignment), and the parent interleaves their queues back into
exact sampler order — sample-to-step assignment and the dataset's
forward-moving access pattern (strictly increasing indices per worker;
forward skips allowed) match the thread path, and the live sampler.index
tracks DELIVERED batches exactly (the thread path's runs ahead by the
prefetch queue; resume goes through the runner's trained_index either
way). Masking draws derive from (seed base, epoch, sample index) inside
the dataset (data/dataset.py, PR 5) — workers need no per-worker reseed
to decorrelate, epochs still re-draw, and thread and process paths
produce byte-identical features (the resume-exactness invariant,
docs/fault_tolerance.md). NB: each strided
worker re-reads every shard file, so with the cheap vectorized masking
the thread path is FASTER at BERT shapes; processes pay off only if
per-sample featurization grows to dominate file IO.

``drop_last`` defaults to True: XLA-jitted steps want static batch shapes, so
ragged tail batches (which the reference tolerates, SURVEY §2.1) would force
a recompile for one step.
"""

from __future__ import annotations

import multiprocessing as mp
import queue
import threading
import time
from typing import Iterator, Optional

import numpy as np

BATCH_KEYS = (
    "input_ids",
    "segment_ids",
    "input_mask",
    "masked_lm_labels",
    "next_sentence_labels",
)
# Packed samples (data/packing.py) append the per-token sequence-id vector
# and per-pack [CLS] offsets; next_sentence_labels is then [K] per row.
PACKED_EXTRA_KEYS = ("sequence_ids", "cls_positions")


def _bounded_put(q, item, stop_event) -> bool:
    """Put that aborts when the consumer is gone — a plain q.put() blocks
    forever once the consumer stops draining with the queue full (the
    abandoning side's stop_event.set() can't unblock a producer already
    inside q.put). Shared by the thread producer and the worker processes;
    both queue flavors raise queue.Full on timeout."""
    while True:
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            if stop_event.is_set():
                return False


def _worker_main(dataset, index_batches, out_queue, stop_event, worker_id):
    """Producer process: featurize+collate its assigned batches in order.

    ``index_batches`` is the ordered list of (batch_number, [dataset indices])
    this worker owns. Results go out as (batch_number, batch_dict); errors as
    (batch_number, RuntimeError) so the parent re-raises at the right step.
    """
    # No reseed: masking draws derive from (seed base, epoch, sample
    # index) inside the dataset (data/dataset.py), and the seed BASE rides
    # in the pickled dataset state — so workers decorrelate per index with
    # no per-worker fold, epochs re-draw via the pickled set_epoch state,
    # and the process path produces BYTE-IDENTICAL features to the thread
    # path (also for seed=None, whose random base is drawn once in the
    # parent). That worker-topology independence is what keeps checkpoint
    # resume exact under any worker count (docs/fault_tolerance.md).
    for bno, idxs in index_batches:
        if stop_event.is_set():
            return
        try:
            batch = DataLoader._collate([dataset[i] for i in idxs])
        except BaseException as e:
            _bounded_put(out_queue, (bno, RuntimeError(
                f"DataLoader worker {worker_id} failed on batch {bno}: "
                f"{type(e).__name__}: {e}")), stop_event)
            return
        if not _bounded_put(out_queue, (bno, batch), stop_event):
            return
    _bounded_put(out_queue, (None, None), stop_event)


class DataLoader:
    def __init__(
        self,
        dataset,
        sampler,
        batch_size: int,
        drop_last: bool = True,
        prefetch_batches: int = 2,
        num_workers: int = 0,
    ):
        self.dataset = dataset
        self.sampler = sampler
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        self.prefetch_batches = prefetch_batches
        self.num_workers = int(num_workers)
        self._reset_stats()

    # -- telemetry gauges (docs/telemetry.md) ---------------------------
    #
    # Consumer-side instrumentation of the prefetch queue: how long the
    # training loop blocked waiting for a batch (wait), how often it found
    # the queue EMPTY (a stall — the producer is the bottleneck), and the
    # queue depth observed at each get (depth ~= prefetch_batches means the
    # producer keeps up; ~0 means it doesn't). snapshot() returns the deltas
    # since the last snapshot, so the runner can fold them into each
    # telemetry step-window record.

    def _reset_stats(self) -> None:
        self._stats = {"batches": 0, "wait_s_total": 0.0, "wait_s_max": 0.0,
                       "stalls": 0, "depth_sum": 0, "depth_max": 0}

    def _observe_get(self, wait_s: float, depth: int) -> None:
        s = self._stats
        s["batches"] += 1
        s["wait_s_total"] += wait_s
        s["wait_s_max"] = max(s["wait_s_max"], wait_s)
        if depth == 0:
            s["stalls"] += 1
        s["depth_sum"] += depth
        s["depth_max"] = max(s["depth_max"], depth)

    def snapshot(self) -> Optional[dict]:
        """Gauges accumulated since the previous snapshot (None if no
        batches were delivered in the interval)."""
        s = self._stats
        if s["batches"] == 0:
            return None
        out = {
            "batches": s["batches"],
            "wait_s_total": round(s["wait_s_total"], 6),
            "wait_s_max": round(s["wait_s_max"], 6),
            "stalls": s["stalls"],
            "depth_mean": round(s["depth_sum"] / s["batches"], 2),
            "depth_max": s["depth_max"],
        }
        self._reset_stats()
        return out

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def __iter__(self) -> Iterator[dict]:
        if self.num_workers > 0:
            return self._iter_multiprocess()
        return self._iter_thread()

    def _iter_multiprocess(self) -> Iterator[dict]:
        """Spawned workers, round-robin over batches, in-order delivery.

        The sampler is consumed up front (it is a cheap index mapping), and
        its live ``index`` is advanced per DELIVERED batch below — exact,
        unlike the thread path whose live index runs AHEAD of training by
        the prefetch queue (the skew run_pretraining.py works around with
        its trained_index counter; both paths resume correctly through
        that counter). Spawn — not fork — because the parent has a live
        JAX runtime.
        """
        start = self.sampler.index  # nonzero on mid-epoch resume
        positions = list(self.sampler)  # drains; resets sampler.index to 0
        n_batches = len(positions) // self.batch_size
        tail = positions[n_batches * self.batch_size:]
        batches = [
            (b, positions[b * self.batch_size:(b + 1) * self.batch_size])
            for b in range(n_batches)
        ]
        if tail and not self.drop_last:
            batches.append((n_batches, tail))
        ctx = mp.get_context("spawn")
        stop = ctx.Event()
        n_workers = max(1, min(self.num_workers, max(1, len(batches))))
        out_queues = [
            ctx.Queue(maxsize=max(2, self.prefetch_batches))
            for _ in range(n_workers)
        ]
        procs = [
            ctx.Process(
                target=_worker_main,
                args=(self.dataset, batches[w::n_workers], out_queues[w],
                      stop, w),
                daemon=True)
            for w in range(n_workers)
        ]
        for p in procs:
            p.start()
        try:
            for b in range(len(batches)):
                q = out_queues[b % n_workers]
                try:
                    depth = q.qsize()
                except NotImplementedError:  # macOS mp.Queue
                    depth = 0
                t_wait0 = time.perf_counter()
                while True:
                    try:
                        bno, item = q.get(timeout=5.0)
                        break
                    except queue.Empty:
                        dead = procs[b % n_workers]
                        if not dead.is_alive():
                            raise RuntimeError(
                                f"DataLoader worker {b % n_workers} died "
                                f"(exit code {dead.exitcode}) before "
                                f"producing batch {b}")
                if isinstance(item, BaseException):
                    raise item
                assert bno == b, (bno, b)
                self._observe_get(time.perf_counter() - t_wait0, depth)
                self.sampler.index = min(
                    len(self.sampler), start + (b + 1) * self.batch_size)
                yield item
            self.sampler.index = 0  # epoch complete, like __next__'s reset
        finally:
            stop.set()
            for p in procs:
                p.join(timeout=5.0)
                if p.is_alive():
                    p.terminate()
            for q in out_queues:
                q.close()
                q.cancel_join_thread()

    def _iter_thread(self) -> Iterator[dict]:
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_batches)
        stop = threading.Event()

        def put(item) -> bool:
            # Without the abort, an abandoned iteration (e.g. every
            # early-stopped validation pass) leaks one producer thread and
            # its buffered batches.
            return _bounded_put(q, item, stop)

        def produce():
            samples = []
            try:
                for idx in self.sampler:
                    if stop.is_set():
                        return
                    samples.append(self.dataset[idx])
                    if len(samples) == self.batch_size:
                        if not put(self._collate(samples)):
                            return
                        samples = []
                if samples and not self.drop_last:
                    if not put(self._collate(samples)):
                        return
            except BaseException as e:  # surface worker errors to the consumer
                put(e)
                return
            put(None)

        worker = threading.Thread(target=produce, daemon=True)
        worker.start()
        try:
            while True:
                depth = q.qsize()
                t_wait0 = time.perf_counter()
                item = q.get()
                if item is None:
                    return
                if isinstance(item, BaseException):
                    raise item
                self._observe_get(time.perf_counter() - t_wait0, depth)
                yield item
        finally:
            stop.set()

    @staticmethod
    def _collate(samples) -> dict:
        keys = BATCH_KEYS + PACKED_EXTRA_KEYS[:len(samples[0]) - len(BATCH_KEYS)]
        arrays = [np.stack([s[i] for s in samples]) for i in range(len(keys))]
        return dict(zip(keys, arrays))
