"""CoNLL-style NER dataset.

Parity with reference src/ner_dataset.py: per-word tokenization with the
word's label propagated to every subtoken (:13-26), [CLS]/[SEP] wrapping
with the special label encoded as -100 (:28-35), zero-padding to
max_seq_len (:37-44), and the CoNLL file parser that splits sentences on
blank/-DOCSTART lines reading column 0 (token) and column 3 (tag) (:66-85).

Label ids start at 1 (0 is reserved, matching the reference's
``enumerate(labels, start=1)`` at :54 and the +1 head size in run_ner.py:224).
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Sequence

import numpy as np

SPECIAL_LABEL = -100


def _encode_word(tokenizer, word: str) -> List[str]:
    if hasattr(tokenizer, "encode"):
        return tokenizer.encode(word, add_special_tokens=False).tokens
    return tokenizer.tokenize(word)


def _token_id(tokenizer, token: str) -> int:
    if hasattr(tokenizer, "token_to_id"):
        tid = tokenizer.token_to_id(token)
        return tid if tid is not None else tokenizer.token_to_id("[UNK]")
    return tokenizer.vocab.get(token, tokenizer.vocab["[UNK]"])


@dataclasses.dataclass
class Sample:
    sentence: List[str]
    labels: List[str]

    def __post_init__(self):
        assert len(self.sentence) == len(self.labels)

    def encoded(self, tokenizer, label_to_id, max_seq_len: int):
        tokens: List[str] = []
        labels: List[str] = []
        for word, label in zip(self.sentence, self.labels):
            subtokens = _encode_word(tokenizer, word)
            tokens.extend(subtokens)
            labels.extend([label] * len(subtokens))

        tokens = tokens[: max_seq_len - 2]
        labels = labels[: max_seq_len - 2]
        tokens = ["[CLS]"] + tokens + ["[SEP]"]

        encoded_seq = [_token_id(tokenizer, t) for t in tokens]
        encoded_labels = (
            [SPECIAL_LABEL]
            + [label_to_id[l] for l in labels]
            + [SPECIAL_LABEL]
        )
        mask = [1] * len(encoded_seq)
        pad = max_seq_len - len(encoded_seq)
        encoded_seq += [0] * pad
        encoded_labels += [0] * pad
        mask += [0] * pad
        return tokens, encoded_seq, encoded_labels, mask


class NERDataset:
    def __init__(self, filename: str, tokenizer, labels: Sequence[str],
                 max_seq_len: int):
        self.samples = self._parse_file(filename)
        self.tokenizer = tokenizer
        self.label_to_id = {label: i for i, label in enumerate(labels, start=1)}
        self.max_seq_len = max_seq_len

    def __len__(self) -> int:
        return len(self.samples)

    def __getitem__(self, idx: int):
        _, seq, labels, mask = self.samples[idx].encoded(
            self.tokenizer, self.label_to_id, self.max_seq_len)
        return (np.asarray(seq, np.int32), np.asarray(labels, np.int32),
                np.asarray(mask, np.int32))

    @staticmethod
    def _parse_file(filename: str) -> List[Sample]:
        samples = []
        sentence: List[str] = []
        labels: List[str] = []
        with open(filename, "r", encoding="utf-8") as f:
            for line in f:
                if line == "" or line.startswith("-DOCSTART") or line[0] == "\n":
                    if sentence:
                        samples.append(Sample(sentence, labels))
                        sentence, labels = [], []
                    continue
                cols = [c.strip() for c in re.split(" |\t", line)]
                sentence.append(cols[0])
                labels.append(cols[3])
        if sentence:
            samples.append(Sample(sentence, labels))
        return samples
