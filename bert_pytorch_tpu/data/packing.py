"""Sequence packing for pretraining (Krell et al. 2021, arXiv:2107.02027).

With real Wikipedia-style length distributions most pretraining rows are far
shorter than ``max_seq_len``, so a padded batch spends a large fraction of
every step's FLOPs attending to and predicting on pad tokens. Packing
concatenates several short sequences into one row and carries a per-token
``sequence_ids`` array (``[S]``, 0 = pad, k = k-th packed sequence) that the
attention layer turns into a block-diagonal mask — no cross-contamination
between packed sequences (ops/attention.py, ops/pallas/attention.py), and
position embeddings restart per packed sequence (models/bert.py).

Three pieces live here, single-sourcing the packed layout:

* :func:`first_fit_decreasing` — the greedy packer both the offline encoder
  (tools/encode_data.py) and the on-the-fly wrapper use;
* :func:`write_packed_shard` / the ``PACKED_FORMAT_KEYS`` layout — the
  offline HDF5 shard format ``data/dataset.py`` detects and decodes;
* :class:`PackedPretrainingDataset` — the on-the-fly mode: wraps a
  :class:`~bert_pytorch_tpu.data.dataset.ShardedPretrainingDataset`, packs
  WITHIN each shard (preserving the streaming dataset's forward-moving file
  access), and assembles packed rows from the base dataset's already-masked
  per-sample features.

Per packed row the training batch carries two extra arrays
(data/loader.py ``PACKED_EXTRA_KEYS``):

* ``sequence_ids``  [S]  int32, 0 on padding;
* ``cls_positions`` [K]  int32, the row offset of each packed sequence's
  [CLS] token (0-filled for empty slots — their NSP label is -1, so the
  loss ignores them; K = ``max_sequences_per_pack``).

``next_sentence_labels`` becomes [K] per row (-1 = empty slot), which the
existing NSP cross-entropy already ignores and count-normalizes
(models/losses.py ``_xent_ignore``).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import h5py
import numpy as np

# Offline packed shard layout (docs/packing.md). ``packed_sequence_lengths``
# and ``packed_special_token_positions`` are ragged (vlen) per-row arrays;
# ``next_sentence_labels`` is ragged too (one label per packed sequence).
PACKED_FORMAT_KEYS = (
    "input_ids",
    "packed_sequence_lengths",
    "packed_special_token_positions",
    "next_sentence_labels",
)
PACKED_MAX_SEQUENCES_ATTR = "packed_max_sequences"


def first_fit_decreasing(
    lengths: Sequence[int],
    max_seq_len: int,
    max_sequences_per_pack: int,
) -> List[List[int]]:
    """Greedy first-fit-decreasing bin packing.

    Returns packs as lists of indices into ``lengths``; every pack's total
    length fits ``max_seq_len`` and holds at most ``max_sequences_per_pack``
    members. Over-long inputs (length > max_seq_len) get a singleton pack —
    the assembler truncates, matching the unpacked pipeline's behavior.

    FFD is the strategy both packing papers converge on (Krell 2021 §3's
    NNLSHP refines it, Kosec 2021 uses it directly): sorting by decreasing
    length first places the hard-to-fit long sequences, then back-fills the
    gaps with short ones — within ~1-2% of optimal occupancy on BERT-phase
    length histograms at a fraction of the cost.
    """
    if max_seq_len <= 0:
        raise ValueError(f"max_seq_len must be positive, got {max_seq_len}")
    if max_sequences_per_pack < 1:
        raise ValueError(
            "max_sequences_per_pack must be >= 1, got "
            f"{max_sequences_per_pack}")
    order = sorted(range(len(lengths)), key=lambda i: -int(lengths[i]))
    packs: List[List[int]] = []
    residual: List[int] = []  # remaining room per pack
    for idx in order:
        n = min(int(lengths[idx]), max_seq_len)
        placed = False
        for p, room in enumerate(residual):
            if room >= n and len(packs[p]) < max_sequences_per_pack:
                packs[p].append(idx)
                residual[p] = room - n
                placed = True
                break
        if not placed:
            packs.append([idx])
            residual.append(max_seq_len - n)
    # Emit packs ordered by their smallest member index so a streaming
    # consumer (PackedPretrainingDataset over sorted shards) walks the
    # underlying samples roughly forward.
    packs.sort(key=min)
    return packs


def pack_features(
    samples: Sequence[Sequence[np.ndarray]],
    max_seq_len: int,
    max_sequences_per_pack: int,
) -> list:
    """Assemble one packed row from per-sample FEATURE tuples.

    ``samples`` holds the unpacked pipeline's per-sample outputs
    (data/dataset.py ``__getitem__``): ``[input_ids, segment_ids,
    input_mask, masked_lm_labels, next_sentence_label]`` — already masked,
    padded rows. The non-pad prefix of each (its ``input_mask`` popcount)
    is concatenated; everything downstream of the dataset sees ONE row.

    Returns ``[input_ids, segment_ids, input_mask, masked_lm_labels,
    next_sentence_labels[K], sequence_ids, cls_positions[K]]``.
    """
    if not 1 <= len(samples) <= max_sequences_per_pack:
        raise ValueError(
            f"pack holds {len(samples)} sequences, limit is "
            f"{max_sequences_per_pack}")
    input_ids = np.zeros(max_seq_len, np.int32)
    segment_ids = np.zeros(max_seq_len, np.int32)
    input_mask = np.zeros(max_seq_len, np.int32)
    labels = np.full(max_seq_len, -1, np.int32)
    sequence_ids = np.zeros(max_seq_len, np.int32)
    nsp = np.full(max_sequences_per_pack, -1, np.int32)
    cls_positions = np.zeros(max_sequences_per_pack, np.int32)

    offset = 0
    for k, sample in enumerate(samples):
        ids, segs, mask, labs, nsp_k = sample[:5]
        n = int(np.sum(np.asarray(mask) != 0))
        n = min(n, max_seq_len - offset)
        if n <= 0:
            raise ValueError(
                "pack overflows max_seq_len "
                f"({max_seq_len}); the packer must pre-fit lengths")
        input_ids[offset:offset + n] = np.asarray(ids)[:n]
        segment_ids[offset:offset + n] = np.asarray(segs)[:n]
        input_mask[offset:offset + n] = 1
        labels[offset:offset + n] = np.asarray(labs)[:n]
        sequence_ids[offset:offset + n] = k + 1
        nsp[k] = int(np.asarray(nsp_k).reshape(()))
        cls_positions[k] = offset
        offset += n
    return [input_ids, segment_ids, input_mask, labels, nsp,
            sequence_ids, cls_positions]


def write_packed_shard(
    path: str,
    rows: Sequence[Sequence],
    max_seq_len: int,
    max_sequences_per_pack: int,
) -> int:
    """Write an offline packed HDF5 shard (``PACKED_FORMAT_KEYS`` layout).

    ``rows`` is a list of packed rows; each row is a list of member
    sequences, each member a ``(token_ids, special_token_positions,
    next_sentence_label)`` tuple with positions RELATIVE to the member
    (the writer rebases them onto the packed row). Token ids must be the
    unpadded sequence including its [CLS]/[SEP] specials.

    Dynamic masking stays in the runtime dataset exactly as for unpacked
    shards: the shard stores raw token ids; data/dataset.py re-derives
    masks/labels per epoch from the per-member special positions.
    """
    n = len(rows)
    input_ids = np.zeros((n, max_seq_len), np.int32)
    seq_lengths, specials, nsp_labels = [], [], []
    for r, members in enumerate(rows):
        if not 1 <= len(members) <= max_sequences_per_pack:
            raise ValueError(
                f"row {r} holds {len(members)} sequences, limit is "
                f"{max_sequences_per_pack}")
        offset = 0
        lens, specs, nsps = [], [], []
        for ids, special, nsp in members:
            ids = np.asarray(ids, np.int32)
            if offset + len(ids) > max_seq_len:
                raise ValueError(
                    f"row {r} overflows max_seq_len ({max_seq_len})")
            input_ids[r, offset:offset + len(ids)] = ids
            lens.append(len(ids))
            specs.extend(int(p) + offset for p in special)
            nsps.append(int(nsp))
            offset += len(ids)
        seq_lengths.append(np.asarray(lens, np.int32))
        specials.append(np.asarray(specs, np.int32))
        nsp_labels.append(np.asarray(nsps, np.int8))

    vlen_i4 = h5py.vlen_dtype(np.dtype("i4"))
    vlen_i1 = h5py.vlen_dtype(np.dtype("i1"))
    with h5py.File(path, "w") as f:
        f.create_dataset("input_ids", data=input_ids, dtype="i4",
                         compression="gzip")
        ds_len = f.create_dataset(
            "packed_sequence_lengths", (n,), dtype=vlen_i4)
        ds_spec = f.create_dataset(
            "packed_special_token_positions", (n,), dtype=vlen_i4)
        ds_nsp = f.create_dataset("next_sentence_labels", (n,), dtype=vlen_i1)
        for r in range(n):
            ds_len[r] = seq_lengths[r]
            ds_spec[r] = specials[r]
            ds_nsp[r] = nsp_labels[r]
        f.attrs[PACKED_MAX_SEQUENCES_ATTR] = int(max_sequences_per_pack)
    return n


def _sample_lengths_for_file(path: str) -> np.ndarray:
    """Per-sample token lengths of one UNPACKED shard, reading only the
    cheap metadata arrays (never the [N, S] input_ids)."""
    with h5py.File(path, "r") as f:
        if "special_token_positions" in f:
            specials = f["special_token_positions"][:]
            return np.asarray([int(sp[-1]) + 1 for sp in specials], np.int64)
        # Legacy pre-masked format: length = popcount of the input mask.
        return np.asarray(f["input_mask"][:], np.int64).sum(axis=1)


class PackedPretrainingDataset:
    """On-the-fly packing over a :class:`ShardedPretrainingDataset`.

    At construction, per-sample lengths are read from the shard metadata
    and packed first-fit-decreasing WITHIN each shard — members of a pack
    always live in one file, and packs are ordered by shard, so the base
    dataset's streaming contract (forward-moving file access; free random
    access inside the loaded shard) holds. ``__getitem__(i)`` fetches the
    pack's members through the base dataset (dynamic masking runs per
    member exactly as unpacked) and assembles one packed row via
    :func:`pack_features`.

    The wrapper mirrors the base dataset's DataLoader-facing surface
    (``seed``/``epoch``/``reseed``/``set_epoch``) so worker re-seeding and
    epoch folding keep working unchanged.
    """

    def __init__(self, base, max_sequences_per_pack: int = 8,
                 max_seq_len: Optional[int] = None):
        if getattr(base, "packed", False):
            raise ValueError(
                "base dataset already reads offline-packed shards; "
                "on-the-fly packing would pack packs")
        self.base = base
        self.max_sequences_per_pack = int(max_sequences_per_pack)
        if max_seq_len is None:
            with h5py.File(base.files[0], "r") as f:
                max_seq_len = int(f["input_ids"].shape[1])
        self.max_seq_len = int(max_seq_len)

        self.packs: List[List[int]] = []
        total_tokens = 0
        for fpath, (start, _end) in zip(base.files, base.file_idxs):
            lengths = _sample_lengths_for_file(fpath)
            total_tokens += int(lengths.sum())
            for pack in first_fit_decreasing(
                    lengths, self.max_seq_len, self.max_sequences_per_pack):
                self.packs.append([start + i for i in pack])
        self.occupancy = float(total_tokens) / max(
            1, len(self.packs) * self.max_seq_len)
        self.n_samples = len(base)

    # -- DataLoader-facing surface mirrored from the base ----------------

    @property
    def seed(self):
        return self.base.seed

    @seed.setter
    def seed(self, value) -> None:
        # DistributedSampler assigns dataset.seed directly; mirror the
        # plain-attribute behavior onto the base (reseed() rebuilds the rng).
        self.base.seed = value

    @property
    def epoch(self):
        return self.base.epoch

    @epoch.setter
    def epoch(self, value) -> None:
        self.base.epoch = value

    def reseed(self, seed) -> None:
        self.base.reseed(seed)

    def set_epoch(self, epoch: int) -> None:
        self.base.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.packs)

    def __getitem__(self, idx: int):
        members = [self.base[i] for i in self.packs[idx]]
        return pack_features(
            members, self.max_seq_len, self.max_sequences_per_pack)
