"""Contiguous-chunk distributed sampler with checkpointable position.

Parity with reference src/dataset.py:341-428 (``DistributedSampler``): each
rank takes a contiguous chunk of the index space (so ranks stream different
shard files sequentially, not round-robin), the sampler is itself the
iterator so its ``index`` can be saved/restored, and restore is skipped with
a warning when the dataset size or replica count changed.

Fixes the reference's latent ``math.ceil``-without-import bug in the pad
branch (dataset.py:376) by actually importing math.
"""

from __future__ import annotations

import math
import warnings
from typing import Optional


class DistributedSampler:
    def __init__(
        self,
        dataset,
        num_replicas: int,
        rank: int,
        drop_last: bool = False,
        seed: int = 0,
    ):
        if rank >= num_replicas or rank < 0:
            raise ValueError(f"rank {rank} out of range for {num_replicas} replicas")
        self.dataset = dataset
        self.num_replicas = num_replicas
        self.rank = rank
        self.drop_last = drop_last
        self.seed = seed
        self.epoch = 0
        # NB: the sampler no longer overwrites dataset.seed (pre-PR-5 it
        # assigned the attribute WITHOUT rebuilding the masking RNG, so
        # the value silently lied). Masking entropy is owned by the
        # dataset's own seed via the per-(seed, epoch, index) derivation
        # (data/dataset.py); the sampler's seed is its own.

        n = len(dataset)
        if self.drop_last and n % num_replicas != 0:
            self.num_samples = n // num_replicas
        else:
            self.num_samples = math.ceil(n / num_replicas)
        self.total_size = self.num_samples * num_replicas

        indices = list(range(n))
        if not self.drop_last:
            padding_size = self.total_size - len(indices)
            if padding_size <= len(indices):
                indices += indices[:padding_size]
            else:
                indices += (indices * math.ceil(padding_size / len(indices)))[
                    :padding_size
                ]
        else:
            indices = indices[: self.total_size]
        assert len(indices) == self.total_size

        self.global_indices = indices
        self.index = 0

    def __len__(self) -> int:
        return self.num_samples

    def __iter__(self):
        return self

    def __next__(self) -> int:
        if self.index == self.num_samples:
            self.index = 0
            raise StopIteration()
        x = self.global_indices[self.index + self.rank * self.num_samples]
        self.index += 1
        return x

    def state_dict(self) -> dict:
        return {
            "epoch": self.epoch,
            "seed": self.seed,
            "num_replicas": self.num_replicas,
            "total_size": self.total_size,
            "index": self.index,
        }

    def load_state_dict(self, state_dict: dict) -> None:
        if state_dict["total_size"] != self.total_size:
            warnings.warn(
                "The number of samples in the Sampler has changed. Skipping "
                f"restoring sampler state. Expected size {self.total_size} but "
                f"got size {state_dict['total_size']}. If the dataset was "
                "changed and the sampler should be reset, ignore this message"
            )
            return
        if state_dict["num_replicas"] != self.num_replicas:
            warnings.warn(
                "The number of replicas has changed so the resume index from "
                "the sampler is no longer valid. Skipping restoring sampler "
                "state."
            )
            return
        self.epoch = state_dict["epoch"]
        self.seed = state_dict["seed"]
        self.index = state_dict["index"]

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)
