"""SWAG multiple-choice dataset: reading and featurization.

Beyond-reference capability: the reference ships ``BertForMultipleChoice``
with a SWAG usage example in its docstring (modeling.py:1131-1197) but no
runner or data path that can feed it. This module reads the standard SWAG
CSV layout (``train.csv``/``val.csv``: video-id, fold-ind, startphrase,
sent1, sent2, gold-source, ending0..3, label) and featurizes each example
into the [choices, seq] layout ``BertForMultipleChoice`` expects: per
choice, ``[CLS] sent1 [SEP] sent2 ending_i [SEP]`` with segment 1 on the
continuation — the pairing convention of the original SWAG BERT recipe.
"""

from __future__ import annotations

import csv
import dataclasses
from typing import List, Optional

import numpy as np

from bert_pytorch_tpu.data.glue import _truncate_pair

NUM_CHOICES = 4


@dataclasses.dataclass
class SwagExample:
    guid: str
    context: str  # sent1
    start: str  # sent2 (the shared beginning of every ending)
    endings: List[str]
    label: Optional[int] = None


def read_swag_examples(path: str, has_label: bool = True) -> List[SwagExample]:
    with open(path, encoding="utf-8") as f:
        rows = list(csv.reader(f))
    header = rows[0]
    col = {name: i for i, name in enumerate(header)}
    required = ["sent1", "sent2", "ending0", "ending1", "ending2", "ending3"]
    missing = [c for c in required if c not in col]
    if missing:
        raise ValueError(f"{path} is missing SWAG columns {missing}")
    examples = []
    for i, row in enumerate(rows[1:]):
        examples.append(
            SwagExample(
                guid=f"swag-{i}",
                context=row[col["sent1"]],
                start=row[col["sent2"]],
                endings=[row[col[f"ending{j}"]] for j in range(NUM_CHOICES)],
                label=int(row[col["label"]])
                if has_label and "label" in col
                else None,
            )
        )
    return examples


def convert_examples_to_arrays(
    examples, tokenizer, max_seq_length: int
) -> dict:
    """-> dict of [N, choices, S] int32 arrays + [N] labels."""
    cls_id = tokenizer.token_to_id("[CLS]")
    sep_id = tokenizer.token_to_id("[SEP]")
    unlabeled = [e.guid for e in examples if e.label is None]
    if unlabeled:
        raise ValueError(
            f"{len(unlabeled)} example(s) have no label (e.g. {unlabeled[0]}) "
            "— SWAG test.csv ships without labels and cannot be used for "
            "training or accuracy evaluation")
    n = len(examples)
    shape = (n, NUM_CHOICES, max_seq_length)
    input_ids = np.zeros(shape, np.int32)
    input_mask = np.zeros(shape, np.int32)
    segment_ids = np.zeros(shape, np.int32)
    labels = np.zeros((n,), np.int32)
    for idx, example in enumerate(examples):
        ids_context = tokenizer.encode(
            example.context, add_special_tokens=False).ids
        for c, ending in enumerate(example.endings):
            ids_a = list(ids_context)
            ids_b = tokenizer.encode(
                (example.start + " " + ending).strip(),
                add_special_tokens=False).ids
            _truncate_pair(ids_a, ids_b, max_seq_length - 3)
            ids = [cls_id] + ids_a + [sep_id] + ids_b + [sep_id]
            seg = [0] * (len(ids_a) + 2) + [1] * (len(ids_b) + 1)
            input_ids[idx, c, : len(ids)] = ids
            input_mask[idx, c, : len(ids)] = 1
            segment_ids[idx, c, : len(ids)] = seg
        labels[idx] = example.label
    return {
        "input_ids": input_ids,
        "input_mask": input_mask,
        "segment_ids": segment_ids,
        "labels": labels,
    }
