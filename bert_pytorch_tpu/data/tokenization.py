"""Tokenization facade.

Parity with reference src/tokenization.py: thin constructors for fast
WordPiece/BPE tokenizers (:42-57) plus a pure-Python BasicTokenizer/
WordpieceTokenizer/BertTokenizer (:60-229) whose exact semantics the SQuAD
answer-alignment path depends on (run_squad.py's ``get_final_text``).

Fast-path backends, in preference order:
  1. the in-repo C++ tokenizer core (bert_pytorch_tpu/tools/tokenizer_cpp,
     replacing the reference's Rust `tokenizers` dependency — SURVEY §2.3),
  2. the HuggingFace `tokenizers` package when installed.
The pure-Python implementation below is the behavioral specification both
are tested against.

Thread-safety (audited for the serving engine's worker threads,
docs/serving.md; asserted by tests/test_tokenizer.py's concurrent-encode
test):

* the pure-Python ``BertTokenizer``/``BasicTokenizer``/
  ``WordpieceTokenizer`` hold only read-only state after construction
  (vocab dicts, flags) — concurrent ``tokenize``/``convert_*`` calls are
  safe and run in parallel;
* the C++ tokenizers keep per-HANDLE result buffers (``wp_encode`` writes,
  ``wp_get_ids`` reads), so ``encode`` is stateful; each instance
  serializes encode calls behind its own ``_encode_lock``
  (tools/tokenizer_cpp.py) — safe under concurrency, one encode at a time
  per instance. Construct one tokenizer per thread for parallel encoding;
* HF ``tokenizers`` encode is thread-safe per upstream (Rust, no shared
  mutable state on the encode path).

One SHARED instance per server is therefore correct for all backends —
the engine's preprocessing threads contend only on the C++ lock, and
tokenization is microseconds against a model forward.
"""

from __future__ import annotations

import collections
import unicodedata
from typing import Iterable, Optional


def load_vocab(vocab_file: str) -> "collections.OrderedDict[str, int]":
    """token -> id, file order (reference tokenization.py:18-27)."""
    vocab = collections.OrderedDict()
    with open(vocab_file, "r", encoding="utf-8") as reader:
        for index, line in enumerate(reader):
            token = line.rstrip("\n")
            if not token:
                continue
            vocab[token] = index
    return vocab


def get_wordpiece_tokenizer(
    vocab_file: str, uppercase: bool = False, backend: str = "auto"
):
    """BERT WordPiece fast tokenizer (reference tokenization.py:42-49):
    BERT normalizer (clean text, CJK handling, accent-strip + lowercase
    unless ``uppercase``), whitespace+punct pre-tokenization, greedy
    longest-match WordPiece."""
    if backend in ("auto", "cpp"):
        try:
            from bert_pytorch_tpu.tools.tokenizer_cpp import CppWordPieceTokenizer

            return CppWordPieceTokenizer(vocab_file, lowercase=not uppercase)
        except Exception:
            if backend == "cpp":
                raise
    from tokenizers import BertWordPieceTokenizer

    return BertWordPieceTokenizer(
        vocab_file,
        lowercase=not uppercase,
        strip_accents=not uppercase,
        handle_chinese_chars=True,
        clean_text=True,
    )


def get_bpe_tokenizer(vocab_file: str, uppercase: bool = False, backend: str = "auto"):
    """Byte-level BPE tokenizer (reference tokenization.py:51-57).
    ``vocab_file`` may be a merges-adjacent vocab.json path prefix per the
    reference's convention. ``backend='cpp'`` forces the in-repo C++ core
    (native/tokenizer.cpp); 'auto' tries it and falls back to HF."""
    merges = vocab_file.replace("vocab.json", "merges.txt")
    if backend in ("auto", "cpp"):
        try:
            from bert_pytorch_tpu.tools.tokenizer_cpp import (
                CppByteLevelBPETokenizer,
            )

            return CppByteLevelBPETokenizer(
                vocab_file, merges, lowercase=not uppercase)
        except Exception:
            if backend == "cpp":
                raise
    from tokenizers import ByteLevelBPETokenizer

    tok = ByteLevelBPETokenizer(vocab_file, merges, lowercase=not uppercase)
    return tok


# ---------------------------------------------------------------------------
# Pure-Python reference implementation (the behavioral spec).
# ---------------------------------------------------------------------------


def _is_whitespace(char: str) -> bool:
    if char in (" ", "\t", "\n", "\r"):
        return True
    return unicodedata.category(char) == "Zs"


def _is_control(char: str) -> bool:
    if char in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(char).startswith("C")


def _is_punctuation(char: str) -> bool:
    cp = ord(char)
    # ASCII non-alphanumeric ranges count as punctuation even when unicode
    # disagrees (e.g. '$', '`'), matching Google BERT behavior.
    if (33 <= cp <= 47) or (58 <= cp <= 64) or (91 <= cp <= 96) or (123 <= cp <= 126):
        return True
    return unicodedata.category(char).startswith("P")


def _is_cjk(cp: int) -> bool:
    return (
        0x4E00 <= cp <= 0x9FFF
        or 0x3400 <= cp <= 0x4DBF
        or 0x20000 <= cp <= 0x2A6DF
        or 0x2A700 <= cp <= 0x2B73F
        or 0x2B740 <= cp <= 0x2B81F
        or 0x2B820 <= cp <= 0x2CEAF
        or 0xF900 <= cp <= 0xFAFF
        or 0x2F800 <= cp <= 0x2FA1F
    )


def whitespace_tokenize(text: str) -> list[str]:
    text = text.strip()
    return text.split() if text else []


class BasicTokenizer:
    """Whitespace/punctuation splitting + lowercase/accent-strip + CJK
    isolation (reference tokenization.py:60-173). SQuAD's character-level
    answer realignment assumes exactly these semantics.

    ``never_split`` tokens (the special tokens, reference
    tokenization.py:64-75) pass through verbatim: no lowercasing, no
    accent-stripping, no punctuation split — "[MASK]" must stay one token,
    not become "[", "mask", "]".
    """

    def __init__(
        self,
        do_lower_case: bool = True,
        never_split=("[UNK]", "[SEP]", "[PAD]", "[CLS]", "[MASK]"),
    ):
        self.do_lower_case = do_lower_case
        self.never_split = never_split

    def tokenize(self, text: str) -> list[str]:
        text = self._clean_text(text)
        text = self._pad_cjk(text)
        tokens = []
        for token in whitespace_tokenize(text):
            if self.do_lower_case and token not in self.never_split:
                token = token.lower()
                token = self._strip_accents(token)
            tokens.extend(self._split_on_punc(token))
        return whitespace_tokenize(" ".join(tokens))

    @staticmethod
    def _clean_text(text: str) -> str:
        out = []
        for char in text:
            cp = ord(char)
            if cp == 0 or cp == 0xFFFD or _is_control(char):
                continue
            out.append(" " if _is_whitespace(char) else char)
        return "".join(out)

    @staticmethod
    def _pad_cjk(text: str) -> str:
        out = []
        for char in text:
            if _is_cjk(ord(char)):
                out.append(f" {char} ")
            else:
                out.append(char)
        return "".join(out)

    @staticmethod
    def _strip_accents(text: str) -> str:
        text = unicodedata.normalize("NFD", text)
        return "".join(c for c in text if unicodedata.category(c) != "Mn")

    def _split_on_punc(self, token: str) -> list[str]:
        if token in self.never_split:
            return [token]
        pieces: list[list[str]] = []
        start_new = True
        for char in token:
            if _is_punctuation(char):
                pieces.append([char])
                start_new = True
            else:
                if start_new:
                    pieces.append([])
                    start_new = False
                pieces[-1].append(char)
        return ["".join(p) for p in pieces]


class WordpieceTokenizer:
    """Greedy longest-match-first subword split with '##' continuations
    (reference tokenization.py:176-229)."""

    def __init__(
        self,
        vocab,
        unk_token: str = "[UNK]",
        max_input_chars_per_word: int = 100,
    ):
        self.vocab = vocab
        self.unk_token = unk_token
        self.max_input_chars_per_word = max_input_chars_per_word

    def tokenize(self, text: str) -> list[str]:
        output = []
        for token in whitespace_tokenize(text):
            chars = list(token)
            if len(chars) > self.max_input_chars_per_word:
                output.append(self.unk_token)
                continue
            pieces = []
            start = 0
            bad = False
            while start < len(chars):
                end = len(chars)
                found = None
                while start < end:
                    substr = "".join(chars[start:end])
                    if start > 0:
                        substr = "##" + substr
                    if substr in self.vocab:
                        found = substr
                        break
                    end -= 1
                if found is None:
                    bad = True
                    break
                pieces.append(found)
                start = end
            output.extend([self.unk_token] if bad else pieces)
        return output


class BertTokenizer:
    """Basic + WordPiece composition with ids conversion
    (reference tokenization.py:232-318)."""

    def __init__(
        self,
        vocab_file: str,
        do_lower_case: bool = True,
        max_len: Optional[int] = None,
    ):
        self.vocab = load_vocab(vocab_file)
        self.ids_to_tokens = {v: k for k, v in self.vocab.items()}
        self.basic_tokenizer = BasicTokenizer(do_lower_case=do_lower_case)
        self.wordpiece_tokenizer = WordpieceTokenizer(vocab=self.vocab)
        self.max_len = max_len if max_len is not None else int(1e12)

    def tokenize(self, text: str) -> list[str]:
        tokens = []
        for token in self.basic_tokenizer.tokenize(text):
            tokens.extend(self.wordpiece_tokenizer.tokenize(token))
        return tokens

    def convert_tokens_to_ids(self, tokens: Iterable[str]) -> list[int]:
        ids = [self.vocab[t] for t in tokens]
        if len(ids) > self.max_len:
            raise ValueError(
                f"Sequence length {len(ids)} exceeds model max {self.max_len}"
            )
        return ids

    def convert_ids_to_tokens(self, ids: Iterable[int]) -> list[str]:
        return [self.ids_to_tokens[i] for i in ids]
