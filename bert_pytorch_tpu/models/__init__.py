"""Model library — the TPU-native twin of reference src/modeling.py.

Every public class of the reference model library (modeling.py:188-1327) has a
counterpart here. Differences are deliberate TPU-first design, not omissions:

  - Modules are pure flax.linen; loss computation lives in
    :mod:`bert_pytorch_tpu.models.losses` (functional JAX style) rather than
    inside ``forward`` branches keyed on whether labels were passed.
  - The encoder is a single ``nn.scan`` over layers (one trace, one compile,
    stacked [L, ...] params) with optional rematerialization — replacing the
    reference's Python layer loop + √N-chunked ``checkpointed_forward``
    (modeling.py:495-536).
  - Parameters carry logical axis names consumed by
    :mod:`bert_pytorch_tpu.parallel` for pjit sharding.
"""

from bert_pytorch_tpu.models.bert import (
    BertEmbeddings,
    BertEncoder,
    BertForMaskedLM,
    BertForMultipleChoice,
    BertForNextSentencePrediction,
    BertForPreTraining,
    BertForQuestionAnswering,
    BertForSequenceClassification,
    BertForTokenClassification,
    BertLayer,
    BertModel,
    BertPooler,
    LayerNorm,
    LinearActivation,
)
from bert_pytorch_tpu.models.convert import (
    convert_torch_state_dict,
    export_torch_state_dict,
    from_pretrained,
    is_foreign_checkpoint,
    load_encoder_params,
    load_pretrained_encoder,
    load_tf_checkpoint,
    merge_params,
)
from bert_pytorch_tpu.models.losses import (
    masked_lm_loss,
    next_sentence_loss,
    pretraining_loss,
    span_loss,
    token_classification_loss,
)

__all__ = [
    "BertEmbeddings",
    "BertEncoder",
    "BertForMaskedLM",
    "BertForMultipleChoice",
    "BertForNextSentencePrediction",
    "BertForPreTraining",
    "BertForQuestionAnswering",
    "BertForSequenceClassification",
    "BertForTokenClassification",
    "BertLayer",
    "BertModel",
    "BertPooler",
    "LayerNorm",
    "LinearActivation",
    "convert_torch_state_dict",
    "export_torch_state_dict",
    "from_pretrained",
    "is_foreign_checkpoint",
    "load_encoder_params",
    "load_pretrained_encoder",
    "load_tf_checkpoint",
    "merge_params",
    "masked_lm_loss",
    "next_sentence_loss",
    "pretraining_loss",
    "span_loss",
    "token_classification_loss",
]
