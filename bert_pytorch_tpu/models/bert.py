"""BERT model family in flax.linen, designed TPU-first.

Component parity with reference src/modeling.py (cited per class). Key design
choices (vs the reference's torch modules):

  - **bf16 compute / fp32 params**: every module takes ``dtype`` (activation
    dtype, default bf16 on TPU) and keeps parameters in fp32; LayerNorm and
    softmax statistics run in fp32. This replaces torch.cuda.amp autocast
    (reference run_pretraining.py:424-434).
  - **Logical axis names** on every parameter via
    ``nn.with_logical_partitioning`` — the parallel layer maps them to mesh
    axes (data/fsdp/tensor) without touching model code.
  - **nn.scan over layers** with optional remat: one compiled layer body for
    all ``num_hidden_layers`` layers (stacked params, leading 'layers' axis),
    replacing the Python loop at modeling.py:522-536 and the √N-chunked
    ``checkpointed_forward`` at modeling.py:503-520.
  - Attention/LayerNorm route through :mod:`bert_pytorch_tpu.ops` so Pallas
    kernels can be swapped in without touching model code (the Apex
    fused-or-fallback pattern of modeling.py:299-336).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu import ops
from bert_pytorch_tpu.ops import quant as quant_ops
from bert_pytorch_tpu.ops.activations import ACT2FN

Array = jnp.ndarray
Dtype = Any


def bert_normal_init(stddev: float):
    """weight ~ Normal(0, initializer_range) — reference modeling.py:635-640."""
    return nn.initializers.normal(stddev=stddev)


def _kfac_input_stat(x: Array, feature_ndim: int = 1) -> Array:
    """Sum over tokens of x̃x̃ᵀ with the homogeneous bias coordinate appended
    — the K-FAC 'A' factor statistic for a dense layer consuming ``x``.

    The JAX-native analog of kfac_pytorch's forward-hook input capture
    (driven at reference run_pretraining.py:320-355): instead of a module
    hook saving activations, the model sows the already-reduced (d+1, d+1)
    second-moment — under ``nn.scan`` these stack into an (L, d+1, d+1)
    batch that a single batched eigendecomposition inverts on the MXU.
    """
    d = 1
    for s in x.shape[-feature_ndim:]:
        d *= s
    a = x.astype(jnp.float32).reshape(-1, d)
    a = jnp.concatenate([a, jnp.ones_like(a[:, :1])], axis=-1)
    return a.T @ a


# Collections used by the K-FAC taps (see optim/kfac.py).
KFAC_A_COLLECTION = "kfac_a"
KFAC_TAPS_COLLECTION = "kfac_taps"


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _g_factor_probe(y: Array, probe: Array, feature_ndim: int) -> Array:
    """Identity on ``y`` whose gradient w.r.t. ``probe`` is the G-factor
    statistic Σᵣ ĝᵣĝᵣᵀ of ``y``'s cotangent.

    The JAX-native analog of kfac_pytorch's *backward* hooks (driven at
    reference run_pretraining.py:320-355): a torch hook computes the
    (d, d) outer product layer-by-layer as autograd walks the graph, so
    the full cotangent is never kept. Differentiating a plain additive
    tap would instead materialize every layer's stacked cotangent under
    ``nn.scan`` — for BERT-large ~2 GB per tap group. This custom_vjp
    moves the outer product INTO the backward pass: the cotangent for
    ``probe`` (shape (d, d)) is the already-reduced factor, so the scan
    accumulates (L, d, d) statistics instead of (L, B, S, d) gradients,
    and a training step can harvest factors from its own backward at the
    cost of the outer-product FLOPs alone (optim/kfac.py, pretrain.py
    ``make_train_step(kfac_capture_model=...)``).
    """
    del probe
    return y


def _g_factor_probe_fwd(y, probe, feature_ndim):
    del probe
    return y, None


def _g_factor_probe_bwd(feature_ndim, _, ct):
    d = 1
    for s in ct.shape[-feature_ndim:]:
        d *= s
    g = ct.reshape(-1, d).astype(jnp.float32)
    return ct, jnp.einsum("ri,rj->ij", g, g)


_g_factor_probe.defvjp(_g_factor_probe_fwd, _g_factor_probe_bwd)


def _kfac_g_tap(mdl: nn.Module, name: str, y: Array,
                feature_ndim: int = 1) -> Array:
    """Register a (d, d) zero probe variable in ``kfac_taps`` and thread
    ``y`` through :func:`_g_factor_probe` so grad-w.r.t.-taps yields the
    per-layer G factors directly. Tap names encode
    '<dense submodule>__<A-factor name>' (see optim/kfac.py
    ``build_layer_specs``)."""
    d = 1
    for s in y.shape[-feature_ndim:]:
        d *= s
    probe = mdl.variable(
        KFAC_TAPS_COLLECTION, name, lambda: jnp.zeros((d, d), jnp.float32))
    return _g_factor_probe(y, probe.value, feature_ndim)


class LayerNorm(nn.Module):
    """Affine LayerNorm; parity with ``BertLayerNorm`` (modeling.py:311-336).

    Calls :func:`bert_pytorch_tpu.ops.layer_norm`, the TPU-native analog of
    Apex ``FusedLayerNormAffineFunction``.
    """

    epsilon: float = 1e-12
    dtype: Dtype = jnp.float32
    backend: str = "xla"

    @nn.compact
    def __call__(self, x: Array) -> Array:
        dim = x.shape[-1]
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(nn.initializers.ones, ("embed",)),
            (dim,),
            jnp.float32,
        )
        bias = self.param(
            "bias",
            nn.with_logical_partitioning(nn.initializers.zeros, ("embed",)),
            (dim,),
            jnp.float32,
        )
        return ops.layer_norm(x, scale, bias, self.epsilon, backend=self.backend)


class LinearActivation(nn.Module):
    """Fused linear + activation; parity with modeling.py:141-180.

    On TPU the bias-add and activation fuse into the matmul's epilogue under
    XLA, so this is a Dense followed by ``ACT2FN[act]`` — fusion is the
    compiler's job, matching the intent of the reference's jit-scripted
    ``bias_gelu`` path.
    """

    features: int
    act: str = "gelu"
    dtype: Dtype = jnp.float32
    kernel_init_stddev: float = 0.02
    kernel_axes: tuple = ("embed", "mlp")
    # Inference weight quantization (ops/quant.py): None keeps the exact
    # fp32-param training module; "bf16"/"int8" are serve-only storage
    # modes selected by serve/engine.py.
    quant: Optional[str] = None

    @nn.compact
    def __call__(self, x: Array) -> Array:
        y = quant_ops.make_dense(
            self.quant,
            self.features,
            dtype=self.dtype,
            init_stddev=self.kernel_init_stddev,
            kernel_axes=self.kernel_axes,
            name="dense",
        )(x)
        # 'bias_gelu'/'bias_tanh' name the reference's fused bias+act CUDA
        # path (modeling.py:161-171); the Dense above already added the bias,
        # so the plain activation is the mathematically identical form.
        act = self.act[5:] if self.act.startswith("bias_") else self.act
        return ACT2FN[act](y)


class BertEmbeddings(nn.Module):
    """word + position (+ token-type iff next_sentence) embeddings → LN → dropout.

    Parity with modeling.py:338-373; token-type embeddings are only
    materialized when ``config.next_sentence`` (the RoBERTa config path drops
    them, config/roberta_large_cased_config.json).
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16

    def setup(self):
        cfg = self.config
        init = bert_normal_init(cfg.initializer_range)
        self.word_embeddings = nn.Embed(
            cfg.vocab_size,
            cfg.hidden_size,
            embedding_init=nn.with_logical_partitioning(init, ("vocab", "embed")),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="word_embeddings",
        )
        self.position_embeddings = nn.Embed(
            cfg.max_position_embeddings,
            cfg.hidden_size,
            embedding_init=nn.with_logical_partitioning(init, ("pos", "embed")),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="position_embeddings",
        )
        if cfg.next_sentence:
            self.token_type_embeddings = nn.Embed(
                cfg.type_vocab_size,
                cfg.hidden_size,
                embedding_init=nn.with_logical_partitioning(init, ("types", "embed")),
                dtype=self.dtype,
                param_dtype=jnp.float32,
                name="token_type_embeddings",
            )
        self.layer_norm = LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="layer_norm"
        )
        self.dropout = nn.Dropout(rate=cfg.hidden_dropout_prob)

    def __call__(
        self,
        input_ids: Array,
        token_type_ids: Optional[Array] = None,
        deterministic: bool = True,
        sequence_ids: Optional[Array] = None,
    ) -> Array:
        seq_len = input_ids.shape[-1]
        if sequence_ids is not None:
            # Packed rows (data/packing.py): position embeddings restart at
            # 0 for every packed sequence, so a sequence embeds identically
            # whether it rides alone or packed at some row offset (the
            # positional half of Krell 2021's no-cross-contamination
            # requirement; the attention half is the block-diagonal mask).
            idx = jnp.arange(seq_len, dtype=jnp.int32)[None, :]
            is_start = jnp.concatenate(
                [jnp.ones_like(sequence_ids[:, :1], dtype=bool),
                 sequence_ids[:, 1:] != sequence_ids[:, :-1]], axis=-1)
            starts = jnp.where(is_start, idx, 0)
            seg_start = jax.lax.cummax(starts, axis=starts.ndim - 1)
            position_ids = idx - seg_start
        else:
            position_ids = jnp.arange(seq_len, dtype=jnp.int32)[None, :]
        x = self.word_embeddings(input_ids) + self.position_embeddings(position_ids)
        if self.config.next_sentence:
            if token_type_ids is None:
                token_type_ids = jnp.zeros_like(input_ids)
            x = x + self.token_type_embeddings(token_type_ids)
        x = self.layer_norm(x)
        return self.dropout(x, deterministic=deterministic)


class BertSelfAttention(nn.Module):
    """Multi-head self-attention; parity with modeling.py:376-443
    (``BertSelfAttention`` + ``BertSelfOutput`` fused into one module).

    QKV are DenseGeneral projections to [heads, head_dim] (the tensor-parallel
    sharding unit); the attention core routes through
    :func:`bert_pytorch_tpu.ops.dot_product_attention`.
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16
    attention_backend: str = "xla"
    kfac_tap: bool = False
    quant: Optional[str] = None

    @nn.compact
    def __call__(
        self, hidden: Array, bias: Array, deterministic: bool = True,
        sequence_ids: Optional[Array] = None,
    ) -> Array:
        cfg = self.config
        heads, head_dim = cfg.num_attention_heads, cfg.head_dim

        def qkv_proj(name):
            return quant_ops.make_dense(
                self.quant,
                (heads, head_dim),
                dtype=self.dtype,
                init_stddev=cfg.initializer_range,
                kernel_axes=("embed", "heads", "kv"),
                bias_axes=("heads", "kv"),
                name=name,
            )

        if self.kfac_tap:
            # q/k/v share the input, hence one A factor for all three — the
            # values kfac_pytorch computes three identical copies of.
            self.sow(KFAC_A_COLLECTION, "attn_in_a", _kfac_input_stat(hidden))
        q = qkv_proj("query")(hidden)
        k = qkv_proj("key")(hidden)
        v = qkv_proj("value")(hidden)
        if self.kfac_tap:
            # tap name encodes '<dense submodule>__<A-factor name>'.
            q = _kfac_g_tap(self, "query__attn_in", q, feature_ndim=2)
            k = _kfac_g_tap(self, "key__attn_in", k, feature_ndim=2)
            v = _kfac_g_tap(self, "value__attn_in", v, feature_ndim=2)

        dropout_rng = None
        if not deterministic and cfg.attention_probs_dropout_prob > 0.0:
            dropout_rng = self.make_rng("dropout")
        context = ops.dot_product_attention(
            q,
            k,
            v,
            bias=bias,
            dropout_rng=dropout_rng,
            dropout_rate=cfg.attention_probs_dropout_prob,
            deterministic=deterministic,
            backend=self.attention_backend,
            sequence_ids=sequence_ids,
        )
        if self.kfac_tap:
            self.sow(
                KFAC_A_COLLECTION, "attn_ctx_a",
                _kfac_input_stat(context, feature_ndim=2),
            )
        # Output projection [B,S,H,D] -> [B,S,hidden] (BertSelfOutput dense).
        out = quant_ops.make_dense(
            self.quant,
            cfg.hidden_size,
            axis=(-2, -1),
            dtype=self.dtype,
            init_stddev=cfg.initializer_range,
            kernel_axes=("heads", "kv", "embed"),
            bias_axes=("embed",),
            name="output",
        )(context)
        if self.kfac_tap:
            out = _kfac_g_tap(self, "output__attn_ctx", out)
        out = nn.Dropout(rate=cfg.hidden_dropout_prob)(
            out, deterministic=deterministic
        )
        return LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="output_layer_norm"
        )(out + hidden)


class BertLayer(nn.Module):
    """One transformer block: attention → intermediate (bias-GELU) → output.

    Parity with modeling.py:482-493 (``BertLayer`` = ``BertAttention`` +
    ``BertIntermediate`` + ``BertOutput``). Written scan-compatible: called as
    ``carry, _ = layer(carry, bias, deterministic)``.
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16
    attention_backend: str = "xla"
    kfac_tap: bool = False
    quant: Optional[str] = None

    @nn.compact
    def __call__(self, hidden: Array, bias: Array, deterministic: bool = True,
                 sequence_ids: Optional[Array] = None):
        cfg = self.config
        attn_out = BertSelfAttention(
            cfg,
            dtype=self.dtype,
            attention_backend=self.attention_backend,
            kfac_tap=self.kfac_tap,
            quant=self.quant,
            name="attention",
        )(hidden, bias, deterministic, sequence_ids)
        intermediate = LinearActivation(
            cfg.intermediate_size,
            act=cfg.hidden_act,
            dtype=self.dtype,
            kernel_init_stddev=cfg.initializer_range,
            kernel_axes=("embed", "mlp"),
            quant=self.quant,
            name="intermediate",
        )(attn_out)
        if self.kfac_tap:
            self.sow(KFAC_A_COLLECTION, "mlp_in_a", _kfac_input_stat(intermediate))
        out = quant_ops.make_dense(
            self.quant,
            cfg.hidden_size,
            dtype=self.dtype,
            init_stddev=cfg.initializer_range,
            kernel_axes=("mlp", "embed"),
            name="output",
        )(intermediate)
        if self.kfac_tap:
            out = _kfac_g_tap(self, "output__mlp_in", out)
        out = nn.Dropout(rate=cfg.hidden_dropout_prob)(
            out, deterministic=deterministic
        )
        out = LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="output_layer_norm"
        )(out + attn_out)
        return out, None


class BertEncoder(nn.Module):
    """num_hidden_layers × BertLayer under one ``nn.scan``.

    Replaces the Python loop of modeling.py:522-536 and, when
    ``remat != 'none'``, the √N-chunked ``checkpointed_forward``
    (modeling.py:503-520) — on TPU, per-layer remat under scan is the
    memory/compute trade XLA handles natively.
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16
    remat: str = "none"  # 'none' | 'full' | 'dots'
    attention_backend: str = "xla"
    kfac_tap: bool = False
    quant: Optional[str] = None

    @nn.compact
    def __call__(self, hidden: Array, bias: Array, deterministic: bool = True,
                 sequence_ids: Optional[Array] = None):
        cfg = self.config
        if self.remat not in ("none", "dots", "full"):
            raise ValueError(f"remat must be none|dots|full, got {self.remat!r}")
        layer_cls = BertLayer
        if self.remat != "none":
            policy = (
                jax.checkpoint_policies.nothing_saveable
                if self.remat == "full"
                else jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
            )
            layer_cls = nn.remat(
                BertLayer,
                policy=policy,
                prevent_cse=False,
                static_argnums=(3,),  # deterministic
            )
        scanned = nn.scan(
            layer_cls,
            # kfac collections scan to (L, ...) stacks; empty when taps are
            # off, so the extra axes are free.
            variable_axes={"params": 0, KFAC_A_COLLECTION: 0,
                           KFAC_TAPS_COLLECTION: 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(nn.broadcast, nn.broadcast, nn.broadcast),
            length=cfg.num_hidden_layers,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )(
            cfg,
            dtype=self.dtype,
            attention_backend=self.attention_backend,
            kfac_tap=self.kfac_tap,
            quant=self.quant,
            name="layers",
        )
        hidden, _ = scanned(hidden, bias, deterministic, sequence_ids)
        return hidden


class BertPooler(nn.Module):
    """tanh dense over the [CLS] token; parity with modeling.py:538-549.

    For PACKED rows (data/packing.py), ``positions`` [B, K] gathers the
    pooled vector at each packed sequence's own [CLS] offset instead of
    position 0, returning [B, K, hidden]; empty pack slots point at
    offset 0 and are neutralized downstream by their -1 NSP label.
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16
    quant: Optional[str] = None

    @nn.compact
    def __call__(self, sequence_output: Array,
                 positions: Optional[Array] = None) -> Array:
        if positions is None:
            cls = sequence_output[:, 0]
        else:
            # One-hot matmul instead of gather — the same MXU-friendly
            # trick as the masked-positions MLM gather (BertForPreTraining).
            onehot = jax.nn.one_hot(
                positions, sequence_output.shape[1], dtype=self.dtype)
            cls = jnp.einsum("bks,bsh->bkh", onehot, sequence_output)
        return LinearActivation(
            self.config.hidden_size,
            act="tanh",
            dtype=self.dtype,
            kernel_init_stddev=self.config.initializer_range,
            kernel_axes=("embed", "embed_out"),
            quant=self.quant,
            name="dense_act",
        )(cls)


class BertModel(nn.Module):
    """Encoder backbone: embeddings → encoder → (pooler iff next_sentence).

    Parity with modeling.py:802-883. Returns ``(sequence_output, pooled)``;
    ``pooled`` is None when ``config.next_sentence`` is False
    (modeling.py:875-879).
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16
    remat: str = "none"
    attention_backend: str = "xla"
    kfac_tap: bool = False
    # Inference weight quantization (ops/quant.py; serve/engine.py sets
    # it). None = the fp32-param training layout, untouched.
    quant: Optional[str] = None

    def setup(self):
        cfg = self.config
        self.embeddings = BertEmbeddings(cfg, dtype=self.dtype)
        self.encoder = BertEncoder(
            cfg,
            dtype=self.dtype,
            remat=self.remat,
            attention_backend=self.attention_backend,
            kfac_tap=self.kfac_tap,
            quant=self.quant,
        )
        if cfg.next_sentence:
            self.pooler = BertPooler(cfg, dtype=self.dtype,
                                     quant=self.quant)

    def __call__(
        self,
        input_ids: Array,
        token_type_ids: Optional[Array] = None,
        attention_mask: Optional[Array] = None,
        deterministic: bool = True,
        sequence_ids: Optional[Array] = None,
        cls_positions: Optional[Array] = None,
    ):
        """``sequence_ids``/``cls_positions`` mark a PACKED batch
        (data/packing.py): block-diagonal attention, per-sequence position
        restart, and — when ``cls_positions`` [B, K] is given — a pooled
        output per packed sequence ([B, K, hidden]) instead of one per row.
        """
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        bias = ops.attention.make_attention_bias(
            attention_mask, dtype=jnp.float32, sequence_ids=sequence_ids)
        hidden = self.embeddings(
            input_ids, token_type_ids, deterministic, sequence_ids)
        sequence_output = self.encoder(
            hidden, bias, deterministic, sequence_ids)
        pooled = (
            self.pooler(sequence_output, cls_positions)
            if self.config.next_sentence else None
        )
        return sequence_output, pooled


class BertPredictionHeadTransform(nn.Module):
    """dense → act → LayerNorm; parity with modeling.py:551-561."""

    config: BertConfig
    dtype: Dtype = jnp.bfloat16
    quant: Optional[str] = None

    @nn.compact
    def __call__(self, hidden: Array) -> Array:
        cfg = self.config
        x = LinearActivation(
            cfg.hidden_size,
            act=cfg.hidden_act,
            dtype=self.dtype,
            kernel_init_stddev=cfg.initializer_range,
            kernel_axes=("embed", "embed_out"),
            quant=self.quant,
            name="dense_act",
        )(hidden)
        return LayerNorm(
            epsilon=cfg.layer_norm_eps, dtype=self.dtype, name="layer_norm"
        )(x)


class BertLMPredictionHead(nn.Module):
    """MLM head with the decoder weight-tied to the word embeddings.

    Parity with modeling.py:563-599: ``transform`` then a decoder whose weight
    IS the embedding matrix (570-574) plus a free bias. The tied matrix is
    passed in by the caller (functional tying — no parameter copy exists).
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16
    quant: Optional[str] = None

    @nn.compact
    def __call__(self, hidden: Array, word_embedding: Array) -> Array:
        cfg = self.config
        x = BertPredictionHeadTransform(cfg, dtype=self.dtype,
                                        quant=self.quant, name="transform")(
            hidden
        )
        bias = self.param(
            "bias",
            nn.with_logical_partitioning(nn.initializers.zeros, ("vocab",)),
            (cfg.vocab_size,),
            jnp.float32,
        )
        logits = jnp.einsum(
            "bsh,vh->bsv", x, word_embedding.astype(self.dtype)
        ) + bias.astype(self.dtype)
        return logits


class BertForPreTraining(nn.Module):
    """MLM + NSP pretraining model; parity with modeling.py:886-947.

    Returns ``(prediction_logits, seq_relationship_logits)``;
    ``seq_relationship_logits`` is None when ``config.next_sentence`` is False
    (the RoBERTa path).
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16
    remat: str = "none"
    attention_backend: str = "xla"
    # K-FAC factor-capture taps (optim/kfac.py). Covers the encoder's dense
    # layers — the same set kfac_pytorch hooks in the reference (q/k/v,
    # attention output, MLP output are nn.Linear; LinearActivation modules
    # and the skipped predictions head / embeddings are not registered there
    # either, reference run_pretraining.py:343-346, modeling.py:141-180).
    kfac_tap: bool = False

    def setup(self):
        cfg = self.config
        self.bert = BertModel(
            cfg,
            dtype=self.dtype,
            remat=self.remat,
            attention_backend=self.attention_backend,
            kfac_tap=self.kfac_tap,
        )
        self.predictions = BertLMPredictionHead(cfg, dtype=self.dtype)
        if cfg.next_sentence:
            self.seq_relationship = nn.Dense(
                2,
                dtype=self.dtype,
                param_dtype=jnp.float32,
                kernel_init=nn.with_logical_partitioning(
                    bert_normal_init(cfg.initializer_range), ("embed", "classes")
                ),
                bias_init=nn.with_logical_partitioning(
                    nn.initializers.zeros, ("classes",)
                ),
            )

    def __call__(
        self,
        input_ids: Array,
        token_type_ids: Optional[Array] = None,
        attention_mask: Optional[Array] = None,
        deterministic: bool = True,
        masked_positions: Optional[Array] = None,
        sequence_ids: Optional[Array] = None,
        cls_positions: Optional[Array] = None,
    ):
        """When ``masked_positions`` [B, P] is given, MLM logits are computed
        only at those positions ([B, P, V] instead of [B, S, V]) — the
        TPU-native optimization the reference lacks (its head projects every
        position into the 30k vocab, modeling.py:611-617, though only
        max_pred<=80 of 512 carry loss). ~6x less decoder matmul FLOPs at
        phase-2 shapes.

        ``sequence_ids``/``cls_positions`` select the PACKED-batch path
        (data/packing.py): block-diagonal attention, restarted positions,
        and [B, K, 2] NSP logits — one per packed sequence — whose -1
        labels on empty slots the loss already ignores."""
        sequence_output, pooled = self.bert(
            input_ids, token_type_ids, attention_mask, deterministic,
            sequence_ids, cls_positions,
        )
        if masked_positions is not None:
            # One-hot matmul instead of gather: TPU lowers gather/scatter
            # poorly (scatter-add backward), while [B,P,S]x[B,S,H] batched
            # matmuls ride the MXU in both directions.
            onehot = jax.nn.one_hot(
                masked_positions, sequence_output.shape[1], dtype=self.dtype
            )
            sequence_output = jnp.einsum("bps,bsh->bph", onehot, sequence_output)
        word_embedding = self.bert.embeddings.word_embeddings.embedding
        prediction_logits = self.predictions(sequence_output, word_embedding)
        seq_logits = (
            self.seq_relationship(pooled) if self.config.next_sentence else None
        )
        return prediction_logits, seq_logits


class BertForMaskedLM(nn.Module):
    """MLM only; parity with modeling.py:950-1008.

    ``sequence_ids`` selects the PACKED-row path (data/packing.py):
    block-diagonal attention + per-sequence position restart, so several
    short requests can share one row at serve time (serve/engine.py) with
    per-token logits demultiplexed by segment. No extra parameters — the
    unpacked call compiles the identical program.
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16
    remat: str = "none"
    attention_backend: str = "xla"
    quant: Optional[str] = None

    def setup(self):
        self.bert = BertModel(
            self.config,
            dtype=self.dtype,
            remat=self.remat,
            attention_backend=self.attention_backend,
            quant=self.quant,
        )
        self.predictions = BertLMPredictionHead(self.config, dtype=self.dtype,
                                                quant=self.quant)

    def __call__(
        self,
        input_ids: Array,
        token_type_ids: Optional[Array] = None,
        attention_mask: Optional[Array] = None,
        deterministic: bool = True,
        sequence_ids: Optional[Array] = None,
        output_positions: Optional[Array] = None,
    ):
        """``output_positions`` [B, P] selects the FUSED-EPILOGUE path
        (docs/serving.md "Raw-speed kernels"): the hidden states are
        gathered at those positions BEFORE the vocab projection, so the
        head emits [B, P, V] instead of [B, S, V] — serve fill_mask only
        ever reads its [MASK] slots, and projecting the other S-P
        positions into the 30k vocab is pure HBM traffic (the serving
        twin of BertForPreTraining's ``masked_positions``). The gather
        is a one-hot matmul: rows multiply by exactly 1.0 and sum with
        exact zeros, so gather-then-project is bit-equal to
        project-then-gather for every param dtype (the matmul is linear
        and row-independent; tests/test_kernels_fastpath.py asserts
        fp32 bit-equality)."""
        sequence_output, _ = self.bert(
            input_ids, token_type_ids, attention_mask, deterministic,
            sequence_ids,
        )
        if output_positions is not None:
            onehot = jax.nn.one_hot(
                output_positions, sequence_output.shape[1],
                dtype=self.dtype)
            sequence_output = jnp.einsum(
                "bps,bsh->bph", onehot, sequence_output)
        word_embedding = self.bert.embeddings.word_embeddings.embedding
        return self.predictions(sequence_output, word_embedding)


class BertForNextSentencePrediction(nn.Module):
    """NSP only; parity with modeling.py:1011-1069."""

    config: BertConfig
    dtype: Dtype = jnp.bfloat16
    remat: str = "none"
    attention_backend: str = "xla"

    def setup(self):
        self.bert = BertModel(
            self.config,
            dtype=self.dtype,
            remat=self.remat,
            attention_backend=self.attention_backend,
        )
        self.seq_relationship = nn.Dense(
            2,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                bert_normal_init(self.config.initializer_range), ("embed", "classes")
            ),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros, ("classes",)
            ),
        )

    def __call__(
        self,
        input_ids: Array,
        token_type_ids: Optional[Array] = None,
        attention_mask: Optional[Array] = None,
        deterministic: bool = True,
    ):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask, deterministic)
        return self.seq_relationship(pooled)


class _ClassifierHead(nn.Module):
    """Dropout + Dense classifier shared by the task heads."""

    num_labels: int
    dropout_rate: float
    initializer_range: float
    dtype: Dtype = jnp.bfloat16
    quant: Optional[str] = None

    @nn.compact
    def __call__(self, x: Array, deterministic: bool = True) -> Array:
        x = nn.Dropout(rate=self.dropout_rate)(x, deterministic=deterministic)
        # Output layers skip int8 (ops/quant.py EXCLUDE_MODULES): a
        # [hidden, num_labels] kernel saves no bytes worth pre-softmax
        # quantization noise; int8 engines store it bf16 instead.
        return quant_ops.make_dense(
            quant_ops.exclude(self.quant),
            self.num_labels,
            dtype=self.dtype,
            init_stddev=self.initializer_range,
            kernel_axes=("embed", "classes"),
            name="classifier",
        )(x)


class BertForSequenceClassification(nn.Module):
    """Pooled-output classifier; parity with modeling.py:1072-1128.

    ``sequence_ids`` + ``cls_positions`` select the PACKED-row path
    (data/packing.py): K requests share one row, the pooler gathers each
    request's own [CLS] vector, and the head returns [B, K, num_labels]
    (serve/engine.py demultiplexes by pack slot). No extra parameters.
    """

    config: BertConfig
    num_labels: int
    dtype: Dtype = jnp.bfloat16
    remat: str = "none"
    attention_backend: str = "xla"
    quant: Optional[str] = None

    def setup(self):
        self.bert = BertModel(
            self.config,
            dtype=self.dtype,
            remat=self.remat,
            attention_backend=self.attention_backend,
            quant=self.quant,
        )
        self.head = _ClassifierHead(
            self.num_labels,
            self.config.hidden_dropout_prob,
            self.config.initializer_range,
            dtype=self.dtype,
            quant=self.quant,
        )

    def __call__(
        self,
        input_ids: Array,
        token_type_ids: Optional[Array] = None,
        attention_mask: Optional[Array] = None,
        deterministic: bool = True,
        sequence_ids: Optional[Array] = None,
        cls_positions: Optional[Array] = None,
    ):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask,
                              deterministic, sequence_ids, cls_positions)
        return self.head(pooled, deterministic)


class BertForMultipleChoice(nn.Module):
    """[B, C, S] choices → flattened batch → per-choice score;
    parity with modeling.py:1131-1197."""

    config: BertConfig
    num_choices: int
    dtype: Dtype = jnp.bfloat16
    remat: str = "none"
    attention_backend: str = "xla"

    def setup(self):
        self.bert = BertModel(
            self.config,
            dtype=self.dtype,
            remat=self.remat,
            attention_backend=self.attention_backend,
        )
        self.head = _ClassifierHead(
            1,
            self.config.hidden_dropout_prob,
            self.config.initializer_range,
            dtype=self.dtype,
        )

    def __call__(
        self,
        input_ids: Array,
        token_type_ids: Optional[Array] = None,
        attention_mask: Optional[Array] = None,
        deterministic: bool = True,
    ):
        batch, choices, seq = input_ids.shape
        flat = lambda t: None if t is None else t.reshape(batch * choices, seq)
        _, pooled = self.bert(
            flat(input_ids), flat(token_type_ids), flat(attention_mask), deterministic
        )
        scores = self.head(pooled, deterministic)
        return scores.reshape(batch, choices)


class BertForTokenClassification(nn.Module):
    """Per-token classifier; parity with modeling.py:1200-1271.

    ``sequence_ids`` selects the PACKED-row path (data/packing.py): the
    per-token logits of several packed requests ride one row, demultiplexed
    by segment (serve/engine.py). No extra parameters.
    """

    config: BertConfig
    num_labels: int
    dtype: Dtype = jnp.bfloat16
    remat: str = "none"
    attention_backend: str = "xla"
    quant: Optional[str] = None

    def setup(self):
        self.bert = BertModel(
            self.config,
            dtype=self.dtype,
            remat=self.remat,
            attention_backend=self.attention_backend,
            quant=self.quant,
        )
        self.head = _ClassifierHead(
            self.num_labels,
            self.config.hidden_dropout_prob,
            self.config.initializer_range,
            dtype=self.dtype,
            quant=self.quant,
        )

    def __call__(
        self,
        input_ids: Array,
        token_type_ids: Optional[Array] = None,
        attention_mask: Optional[Array] = None,
        deterministic: bool = True,
        sequence_ids: Optional[Array] = None,
    ):
        sequence_output, _ = self.bert(
            input_ids, token_type_ids, attention_mask, deterministic,
            sequence_ids,
        )
        return self.head(sequence_output, deterministic)


class BertForQuestionAnswering(nn.Module):
    """Start/end span logits; parity with modeling.py:1274-1327.

    Returns ``(start_logits, end_logits)`` each [B, S].

    ``sequence_ids`` selects the PACKED-row path (data/packing.py): each
    packed request's start/end logits occupy its own row segment
    (serve/engine.py demultiplexes and decodes spans per request). No
    extra parameters.
    """

    config: BertConfig
    dtype: Dtype = jnp.bfloat16
    remat: str = "none"
    attention_backend: str = "xla"
    quant: Optional[str] = None

    def setup(self):
        self.bert = BertModel(
            self.config,
            dtype=self.dtype,
            remat=self.remat,
            attention_backend=self.attention_backend,
            quant=self.quant,
        )
        self.qa_outputs = quant_ops.make_dense(
            quant_ops.exclude(self.quant),
            2,
            dtype=jnp.float32,
            init_stddev=self.config.initializer_range,
            kernel_axes=("embed", "classes"),
            name="qa_outputs",
        )

    def __call__(
        self,
        input_ids: Array,
        token_type_ids: Optional[Array] = None,
        attention_mask: Optional[Array] = None,
        deterministic: bool = True,
        sequence_ids: Optional[Array] = None,
    ):
        sequence_output, _ = self.bert(
            input_ids, token_type_ids, attention_mask, deterministic,
            sequence_ids,
        )
        logits = self.qa_outputs(sequence_output)
        start_logits, end_logits = jnp.split(logits, 2, axis=-1)
        return start_logits.squeeze(-1), end_logits.squeeze(-1)
