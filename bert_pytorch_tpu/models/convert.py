"""Pretrained-weight import/export: torch / HF / TF checkpoints ↔ our params.

Capability parity with the reference's weight-loading stack:
  - ``BertPreTrainedModel.from_pretrained`` (modeling.py:659-799): load a
    pretrained archive directory (config + weights) into a model;
  - ``load_tf_weights_in_bert`` (modeling.py:58-116): import Google BERT
    TensorFlow checkpoints (the archives WeightsDownloader fetches).

Layout notes. Torch linear weights are [out, in]; flax kernels are
[in, out] (TF convention), so torch weights transpose on the way in. Our
encoder is a single ``nn.scan`` stack, so L per-layer tensors become one
(L, ...) array; attention projections are DenseGeneral kernels of shape
(H, heads, head_dim) / (heads, head_dim, H). When the target vocab is
padded (MXU %8 padding, run_pretraining.py:157), word embeddings and the
prediction bias are zero-padded to match.
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import numpy as np

from bert_pytorch_tpu.config import BertConfig


def _np(x) -> np.ndarray:
    if hasattr(x, "detach"):
        x = x.detach().cpu().numpy()
    return np.asarray(x)


def _get(sd: Dict[str, np.ndarray], *names: str) -> np.ndarray:
    """First match among naming variants (dense_act vs dense,
    LayerNorm.weight vs LayerNorm.gamma, ...)."""
    for name in names:
        if name in sd:
            return _np(sd[name])
    raise KeyError(f"none of {names} found in state dict")


def _layer_norm(sd, prefix: str) -> dict:
    return {
        "scale": _get(sd, f"{prefix}.weight", f"{prefix}.gamma"),
        "bias": _get(sd, f"{prefix}.bias", f"{prefix}.beta"),
    }


def _pad_vocab(arr: np.ndarray, vocab_size: int) -> np.ndarray:
    """Zero-pad the vocab (leading) dimension up to ``vocab_size``."""
    if arr.shape[0] == vocab_size:
        return arr
    if arr.shape[0] > vocab_size:
        raise ValueError(
            f"checkpoint vocab {arr.shape[0]} larger than config vocab "
            f"{vocab_size}")
    pad = [(0, vocab_size - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, pad)


def convert_torch_state_dict(
    state_dict: Dict, config: BertConfig
) -> Dict[str, dict]:
    """Reference/HF torch BERT state dict -> our flax params tree.

    Accepts the naming of reference src/modeling.py (``dense_act``,
    gamma/beta LayerNorms) and of HF transformers (``dense``,
    weight/bias LayerNorms). Heads not present in the checkpoint
    (e.g. loading a bare ``BertModel`` into ``BertForPreTraining``) are
    simply absent from the result — merge over freshly initialized params
    with :func:`merge_params`.
    """
    sd = {k[7:] if k.startswith("module.") else k: v
          for k, v in state_dict.items()}
    hidden = config.hidden_size
    heads = config.num_attention_heads
    head_dim = config.head_dim
    n_layers = config.num_hidden_layers

    def qkv_kernel(i, name):
        w = _get(sd, f"bert.encoder.layer.{i}.attention.self.{name}.weight")
        return w.T.reshape(hidden, heads, head_dim)

    def qkv_bias(i, name):
        return _get(
            sd, f"bert.encoder.layer.{i}.attention.self.{name}.bias"
        ).reshape(heads, head_dim)

    def stack(fn):
        return np.stack([fn(i) for i in range(n_layers)])

    layers = {
        "attention": {
            "query": {"kernel": stack(lambda i: qkv_kernel(i, "query")),
                      "bias": stack(lambda i: qkv_bias(i, "query"))},
            "key": {"kernel": stack(lambda i: qkv_kernel(i, "key")),
                    "bias": stack(lambda i: qkv_bias(i, "key"))},
            "value": {"kernel": stack(lambda i: qkv_kernel(i, "value")),
                      "bias": stack(lambda i: qkv_bias(i, "value"))},
            "output": {
                "kernel": stack(lambda i: _get(
                    sd, f"bert.encoder.layer.{i}.attention.output.dense.weight"
                ).T.reshape(heads, head_dim, hidden)),
                "bias": stack(lambda i: _get(
                    sd, f"bert.encoder.layer.{i}.attention.output.dense.bias")),
            },
            "output_layer_norm": {
                k: stack(lambda i, k=k: _layer_norm(
                    sd, f"bert.encoder.layer.{i}.attention.output.LayerNorm")[k])
                for k in ("scale", "bias")
            },
        },
        "intermediate": {"dense": {
            "kernel": stack(lambda i: _get(
                sd, f"bert.encoder.layer.{i}.intermediate.dense_act.weight",
                f"bert.encoder.layer.{i}.intermediate.dense.weight").T),
            "bias": stack(lambda i: _get(
                sd, f"bert.encoder.layer.{i}.intermediate.dense_act.bias",
                f"bert.encoder.layer.{i}.intermediate.dense.bias")),
        }},
        "output": {
            "kernel": stack(lambda i: _get(
                sd, f"bert.encoder.layer.{i}.output.dense.weight").T),
            "bias": stack(lambda i: _get(
                sd, f"bert.encoder.layer.{i}.output.dense.bias")),
        },
        "output_layer_norm": {
            k: stack(lambda i, k=k: _layer_norm(
                sd, f"bert.encoder.layer.{i}.output.LayerNorm")[k])
            for k in ("scale", "bias")
        },
    }

    embeddings = {
        "word_embeddings": {"embedding": _pad_vocab(
            _get(sd, "bert.embeddings.word_embeddings.weight"),
            config.vocab_size)},
        "position_embeddings": {"embedding": _get(
            sd, "bert.embeddings.position_embeddings.weight")},
        "layer_norm": _layer_norm(sd, "bert.embeddings.LayerNorm"),
    }
    if config.next_sentence and "bert.embeddings.token_type_embeddings.weight" in sd:
        embeddings["token_type_embeddings"] = {"embedding": _get(
            sd, "bert.embeddings.token_type_embeddings.weight")}

    bert = {"embeddings": embeddings, "encoder": {"layers": layers}}
    if "bert.pooler.dense_act.weight" in sd or "bert.pooler.dense.weight" in sd:
        bert["pooler"] = {"dense_act": {"dense": {
            "kernel": _get(sd, "bert.pooler.dense_act.weight",
                           "bert.pooler.dense.weight").T,
            "bias": _get(sd, "bert.pooler.dense_act.bias",
                         "bert.pooler.dense.bias"),
        }}}

    params: Dict[str, dict] = {"bert": bert}
    if "cls.predictions.bias" in sd:
        params["predictions"] = {
            "bias": _pad_vocab(_get(sd, "cls.predictions.bias"),
                               config.vocab_size),
            "transform": {
                "dense_act": {"dense": {
                    "kernel": _get(
                        sd, "cls.predictions.transform.dense_act.weight",
                        "cls.predictions.transform.dense.weight").T,
                    "bias": _get(
                        sd, "cls.predictions.transform.dense_act.bias",
                        "cls.predictions.transform.dense.bias"),
                }},
                "layer_norm": _layer_norm(
                    sd, "cls.predictions.transform.LayerNorm"),
            },
        }
    if "cls.seq_relationship.weight" in sd:
        params["seq_relationship"] = {
            "kernel": _get(sd, "cls.seq_relationship.weight").T,
            "bias": _get(sd, "cls.seq_relationship.bias"),
        }
    return params


def export_torch_state_dict(params, config: BertConfig) -> Dict[str, np.ndarray]:
    """Inverse of :func:`convert_torch_state_dict` (HF naming) — interop out:
    hand a model pretrained here to any torch/HF consumer."""
    p = {k: np.asarray(v) for k, v in _flatten(params).items()}
    hidden = config.hidden_size
    sd: Dict[str, np.ndarray] = {}

    def put(name, arr):
        sd[name] = np.asarray(arr)

    emb = "bert/embeddings"
    put("bert.embeddings.word_embeddings.weight",
        p[f"{emb}/word_embeddings/embedding"])
    put("bert.embeddings.position_embeddings.weight",
        p[f"{emb}/position_embeddings/embedding"])
    if f"{emb}/token_type_embeddings/embedding" in p:
        put("bert.embeddings.token_type_embeddings.weight",
            p[f"{emb}/token_type_embeddings/embedding"])
    put("bert.embeddings.LayerNorm.weight", p[f"{emb}/layer_norm/scale"])
    put("bert.embeddings.LayerNorm.bias", p[f"{emb}/layer_norm/bias"])

    enc = "bert/encoder/layers"
    n_layers = config.num_hidden_layers
    for i in range(n_layers):
        pre = f"bert.encoder.layer.{i}"
        for name in ("query", "key", "value"):
            put(f"{pre}.attention.self.{name}.weight",
                p[f"{enc}/attention/{name}/kernel"][i].reshape(hidden, -1).T)
            put(f"{pre}.attention.self.{name}.bias",
                p[f"{enc}/attention/{name}/bias"][i].reshape(-1))
        put(f"{pre}.attention.output.dense.weight",
            p[f"{enc}/attention/output/kernel"][i].reshape(-1, hidden).T)
        put(f"{pre}.attention.output.dense.bias",
            p[f"{enc}/attention/output/bias"][i])
        put(f"{pre}.attention.output.LayerNorm.weight",
            p[f"{enc}/attention/output_layer_norm/scale"][i])
        put(f"{pre}.attention.output.LayerNorm.bias",
            p[f"{enc}/attention/output_layer_norm/bias"][i])
        put(f"{pre}.intermediate.dense.weight",
            p[f"{enc}/intermediate/dense/kernel"][i].T)
        put(f"{pre}.intermediate.dense.bias",
            p[f"{enc}/intermediate/dense/bias"][i])
        put(f"{pre}.output.dense.weight", p[f"{enc}/output/kernel"][i].T)
        put(f"{pre}.output.dense.bias", p[f"{enc}/output/bias"][i])
        put(f"{pre}.output.LayerNorm.weight",
            p[f"{enc}/output_layer_norm/scale"][i])
        put(f"{pre}.output.LayerNorm.bias",
            p[f"{enc}/output_layer_norm/bias"][i])

    if "bert/pooler/dense_act/dense/kernel" in p:
        put("bert.pooler.dense.weight", p["bert/pooler/dense_act/dense/kernel"].T)
        put("bert.pooler.dense.bias", p["bert/pooler/dense_act/dense/bias"])
    if "predictions/bias" in p:
        put("cls.predictions.bias", p["predictions/bias"])
        put("cls.predictions.transform.dense.weight",
            p["predictions/transform/dense_act/dense/kernel"].T)
        put("cls.predictions.transform.dense.bias",
            p["predictions/transform/dense_act/dense/bias"])
        put("cls.predictions.transform.LayerNorm.weight",
            p["predictions/transform/layer_norm/scale"])
        put("cls.predictions.transform.LayerNorm.bias",
            p["predictions/transform/layer_norm/bias"])
        # tied decoder, exported for consumers that expect it
        put("cls.predictions.decoder.weight",
            p["bert/embeddings/word_embeddings/embedding"])
    if "seq_relationship/kernel" in p:
        put("cls.seq_relationship.weight", p["seq_relationship/kernel"].T)
        put("cls.seq_relationship.bias", p["seq_relationship/bias"])
    return sd


def _flatten(tree, prefix=""):
    out = {}
    for key, value in tree.items():
        path = f"{prefix}/{key}" if prefix else key
        if isinstance(value, dict):
            out.update(_flatten(value, path))
        else:
            out[path] = value
    return out


def load_tf_checkpoint(ckpt_path: str) -> Dict[str, np.ndarray]:
    """Google BERT TF checkpoint -> torch-style state dict (then convert with
    :func:`convert_torch_state_dict`). Name mapping per reference
    load_tf_weights_in_bert (modeling.py:58-116): layer_N -> layer.N,
    kernel -> weight (transposed to torch layout), gamma/beta ->
    weight/bias, output_bias/output_weights -> bias/weight; optimizer
    slots skipped."""
    try:
        import tensorflow as tf
    except ImportError as exc:  # pragma: no cover
        raise ImportError(
            "Loading TF checkpoints requires tensorflow; convert the archive "
            "to a torch state dict elsewhere or install tensorflow.") from exc

    reader = tf.train.load_checkpoint(ckpt_path)
    sd: Dict[str, np.ndarray] = {}
    skip = ("adam_v", "adam_m", "global_step", "lamb", "bad_steps",
            "loss_scale", "good_steps")
    for tf_name in reader.get_variable_to_shape_map():
        if any(s in tf_name.lower() for s in skip):
            continue
        arr = reader.get_tensor(tf_name)
        parts = []
        for piece in tf_name.split("/"):
            if piece.startswith("layer_"):
                parts.append("layer." + piece[len("layer_"):])
            elif piece == "kernel":
                arr = np.asarray(arr).T
                parts.append("weight")
            elif piece == "gamma":
                parts.append("weight")
            elif piece == "beta":
                parts.append("bias")
            elif piece == "output_bias":
                parts.append("bias")
            elif piece == "output_weights":
                parts.append("weight")
            elif piece == "squad":
                parts.append("classifier")
            else:
                parts.append(piece)
        sd[".".join(parts)] = np.asarray(arr)
    # embedding tables are [vocab, hidden] in both layouts; the decoder is
    # tied so 'cls.predictions.decoder' never materializes.
    return sd


def merge_params(initialized, loaded):
    """Overlay ``loaded`` (possibly partial — e.g. backbone only) onto a
    freshly initialized tree: the non-strict load_state_dict role
    (reference run_pretraining.py:257, run_squad.py:957-961)."""
    merged = dict(initialized)
    for key, value in loaded.items():
        if key in merged and isinstance(value, dict) and isinstance(
                merged[key], dict):
            merged[key] = merge_params(merged[key], value)
        else:
            merged[key] = value
    return merged


def is_foreign_checkpoint(path: str) -> bool:
    """True for pretrained archives this module loads (directory, torch
    .bin/.pt/.pth, TF ckpt prefix) as opposed to our msgpack checkpoints."""
    return (os.path.isdir(path)
            or path.endswith((".bin", ".pt", ".pth"))
            or os.path.exists(path + ".index"))


def load_encoder_params(path: str, config: BertConfig, target: Dict) -> Dict:
    """Overlay the 'bert' encoder subtree of a foreign archive onto a
    freshly initialized param tree (shared by the finetuning runners'
    --init_checkpoint handling; reference run_squad.py:957-961's
    strict=False load)."""
    _, loaded = from_pretrained(path, config=config)
    return merge_params(target, {"bert": loaded["bert"]})


def load_pretrained_encoder(
    path: str,
    config: BertConfig,
    target: Dict,
    fallback_full_tree: bool = False,
) -> Dict:
    """The finetuning runners' shared ``--init_checkpoint`` handling: accept
    a foreign archive (dir / torch .bin / TF prefix — :func:`from_pretrained`
    surface) or one of our msgpack checkpoints, and overlay its 'bert'
    encoder subtree onto freshly initialized ``target`` params (the
    strict=False analog of reference run_squad.py:957-961).

    ``fallback_full_tree`` restores the whole tree when the checkpoint has no
    'bert' subtree (resuming a finetuned head, not just an encoder); without
    it that case raises — a silent skip would leave random init in place
    while claiming success.
    """
    from bert_pytorch_tpu.utils import checkpoint as ckpt

    if is_foreign_checkpoint(path):
        return load_encoder_params(path, config, target)
    state = ckpt.load_checkpoint(path)
    source = state.get("model", state)
    if "bert" in source:
        target = dict(target)
        target["bert"] = ckpt.restore_tree(target["bert"], source["bert"])
        return target
    if fallback_full_tree:
        return ckpt.restore_tree(target, source)
    raise ValueError(
        f"checkpoint {path} has no 'bert' encoder subtree "
        f"(top-level keys: {sorted(source)[:8]})")


def from_pretrained(
    path: str, config: Optional[BertConfig] = None
) -> Tuple[BertConfig, Dict]:
    """Load a pretrained archive directory or weights file.

    Accepts (reference from_pretrained semantics, modeling.py:659-799):
      - a directory holding ``config.json``/``bert_config.json`` plus
        ``pytorch_model.bin`` (torch) or ``bert_model.ckpt*`` (TF);
      - a ``.bin``/``.pt`` torch weights file (config required);
      - a TF checkpoint prefix (config required);
      - an http(s)/s3 URL of a weights file, resolved through the ETag
        download cache (utils/file_utils.py; reference :687-699's
        cached_path step).
    Returns ``(config, params)``; merge over initialized params with
    :func:`merge_params` before use.
    """
    kind_hint = path  # cache filenames are hashes; type comes from the URL
    if path.split("://", 1)[0] in ("http", "https", "s3"):
        from bert_pytorch_tpu.utils.file_utils import cached_path

        path = cached_path(path)
    weights: Optional[str] = None
    if os.path.isdir(path):
        for name in ("config.json", "bert_config.json"):
            candidate = os.path.join(path, name)
            if config is None and os.path.exists(candidate):
                config = BertConfig.from_json_file(candidate)
                break
        if os.path.exists(os.path.join(path, "pytorch_model.bin")):
            weights = os.path.join(path, "pytorch_model.bin")
        elif os.path.exists(os.path.join(path, "bert_model.ckpt.index")):
            weights = os.path.join(path, "bert_model.ckpt")
        else:
            raise FileNotFoundError(
                f"no pytorch_model.bin or bert_model.ckpt.* under {path}")
    else:
        weights = path
    if config is None:
        raise ValueError("no config.json found; pass config explicitly")

    from urllib.parse import urlparse

    hint_path = urlparse(kind_hint).path if "://" in kind_hint else kind_hint
    if weights.endswith((".bin", ".pt", ".pth")) or (
            weights != kind_hint
            and hint_path.endswith((".bin", ".pt", ".pth"))):
        import torch

        sd = torch.load(weights, map_location="cpu", weights_only=True)
        if isinstance(sd.get("model"), dict):
            sd = sd["model"]  # reference checkpoint dict layout (run_squad.py:958)
        return config, convert_torch_state_dict(sd, config)
    return config, convert_torch_state_dict(load_tf_checkpoint(weights), config)
