"""Loss functions for the model family — the functional JAX counterpart of the
reference's in-module loss branches.

Parity targets:
  - ``BertPretrainingCriterion`` (run_pretraining.py:58-72): masked-LM CE with
    ignore_index −1 plus NSP CE, summed.
  - SQuAD span loss (run_squad.py:1085-1092): positions clamped to sequence
    length, (start CE + end CE) / 2.
  - Token classification CE with ignore_index −100 for special tokens
    (ner_dataset.py:13-44, modeling.py:1200-1271).

All cross-entropies are computed in fp32 regardless of logit dtype.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def _xent_ignore(logits: jnp.ndarray, labels: jnp.ndarray, ignore_index: int):
    """Mean CE over positions where label != ignore_index (torch CE semantics:
    mean over non-ignored elements; 0 if none)."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    per_pos = optax.softmax_cross_entropy_with_integer_labels(logits, safe_labels)
    per_pos = jnp.where(valid, per_pos, 0.0)
    count = jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(per_pos) / count


def _xent_sums(logits: jnp.ndarray, labels: jnp.ndarray, ignore_index: int):
    """(sum, count) building blocks of :func:`_xent_ignore`: the fp32 CE
    SUM over non-ignored positions and how many there were. The bucketed
    data-parallel path (pretrain.py overlap_grad_buckets) needs the sum
    unnormalized so it can divide by the GLOBAL count before the local
    backward — that is what makes per-shard gradients psum to exactly the
    global-mean gradient regardless of how masked positions split across
    shards."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    per_pos = optax.softmax_cross_entropy_with_integer_labels(
        logits, safe_labels)
    per_pos = jnp.where(valid, per_pos, 0.0)
    return jnp.sum(per_pos), jnp.sum(valid)


def pretraining_loss_sums(
    prediction_logits,
    seq_relationship_logits,
    masked_lm_labels,
    next_sentence_labels=None,
):
    """Unnormalized pieces of :func:`pretraining_loss`:
    ``(mlm_sum, mlm_count, nsp_sum, nsp_count, mlm_correct)`` — per-shard
    sums the overlap path reduces with explicit per-bucket collectives.
    ``pretraining_loss == mlm_sum/max(mlm_count,1) +
    nsp_sum/max(nsp_count,1)`` and ``mlm_accuracy ==
    mlm_correct/max(mlm_count,1)`` by construction (same masking, same
    fp32 CE)."""
    vocab = prediction_logits.shape[-1]
    labels_flat = masked_lm_labels.reshape(-1)
    mlm_sum, mlm_count = _xent_sums(
        prediction_logits.reshape(-1, vocab), labels_flat, -1)
    preds = jnp.argmax(prediction_logits, axis=-1).reshape(-1)
    mlm_correct = jnp.sum((preds == labels_flat) & (labels_flat != -1))
    if seq_relationship_logits is not None and next_sentence_labels is not None:
        nsp_sum, nsp_count = _xent_sums(
            seq_relationship_logits.reshape(-1, 2),
            next_sentence_labels.reshape(-1), -1)
    else:
        nsp_sum = jnp.zeros((), jnp.float32)
        nsp_count = jnp.zeros((), jnp.int32)
    return mlm_sum, mlm_count, nsp_sum, nsp_count, mlm_correct


def masked_lm_loss(prediction_logits, masked_lm_labels, ignore_index: int = -1):
    """CE over [B, S, V] logits with ignore_index (run_pretraining.py:64-69)."""
    vocab = prediction_logits.shape[-1]
    return _xent_ignore(
        prediction_logits.reshape(-1, vocab),
        masked_lm_labels.reshape(-1),
        ignore_index,
    )


def next_sentence_loss(seq_relationship_logits, next_sentence_labels):
    """CE over [B, 2] NSP logits (run_pretraining.py:70-71)."""
    return _xent_ignore(
        seq_relationship_logits.reshape(-1, 2),
        next_sentence_labels.reshape(-1),
        ignore_index=-1,
    )


def pretraining_loss(
    prediction_logits,
    seq_relationship_logits,
    masked_lm_labels,
    next_sentence_labels=None,
):
    """MLM + NSP total (run_pretraining.py:58-72); MLM-only when NSP is off."""
    loss = masked_lm_loss(prediction_logits, masked_lm_labels)
    if seq_relationship_logits is not None and next_sentence_labels is not None:
        loss = loss + next_sentence_loss(seq_relationship_logits, next_sentence_labels)
    return loss


def span_loss(start_logits, end_logits, start_positions, end_positions):
    """SQuAD loss: clamp positions into [0, S], CE on start and end, averaged
    (run_squad.py:1085-1092 — clamped index == ignored index S)."""
    seq_len = start_logits.shape[-1]
    start_positions = jnp.clip(start_positions, 0, seq_len)
    end_positions = jnp.clip(end_positions, 0, seq_len)
    # The reference sets ignored_index = seq_len and clamps into it; emulate by
    # padding logits with one extra (ignored) class.
    pad = jnp.full(start_logits.shape[:-1] + (1,), -10000.0, start_logits.dtype)
    start_l = jnp.concatenate([start_logits, pad], axis=-1).astype(jnp.float32)
    end_l = jnp.concatenate([end_logits, pad], axis=-1).astype(jnp.float32)
    s = _xent_ignore(start_l, start_positions, ignore_index=seq_len)
    e = _xent_ignore(end_l, end_positions, ignore_index=seq_len)
    return (s + e) / 2.0


def token_classification_loss(logits, labels, ignore_index: int = -100):
    """Per-token CE skipping special-token labels (run_ner.py via
    modeling.py:1200-1271)."""
    num_labels = logits.shape[-1]
    return _xent_ignore(
        logits.reshape(-1, num_labels), labels.reshape(-1), ignore_index
    )


def mlm_accuracy(prediction_logits, masked_lm_labels, ignore_index: int = -1):
    """Fraction of masked positions predicted correctly (for eval logging)."""
    preds = jnp.argmax(prediction_logits, axis=-1)
    valid = masked_lm_labels != ignore_index
    correct = jnp.logical_and(preds == masked_lm_labels, valid)
    return jnp.sum(correct) / jnp.maximum(jnp.sum(valid), 1)
