"""Fused-op library: the TPU-native equivalents of the reference's Apex CUDA
kernels (SURVEY.md §2.3).

Every op has an XLA reference implementation (the default — XLA already fuses
elementwise chains into the surrounding matmuls on TPU) and, where profitable,
a Pallas kernel selected with ``backend='pallas'``. This mirrors the
reference's pattern of a fused CUDA path with an unfused Python fallback
(src/modeling.py:299-336).
"""

from bert_pytorch_tpu.ops.activations import ACT2FN, bias_gelu, bias_tanh, gelu, swish
from bert_pytorch_tpu.ops.layernorm import layer_norm
from bert_pytorch_tpu.ops.attention import dot_product_attention
from bert_pytorch_tpu.ops.grad_utils import global_norm, clip_by_global_norm

__all__ = [
    "ACT2FN",
    "gelu",
    "bias_gelu",
    "bias_tanh",
    "swish",
    "layer_norm",
    "dot_product_attention",
    "global_norm",
    "clip_by_global_norm",
]
