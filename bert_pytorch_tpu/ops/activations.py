"""Activation functions.

Parity with reference src/modeling.py:118-139 (``gelu``/``bias_gelu``/
``bias_tanh``/``swish`` + ``ACT2FN``). The reference ships jit-scripted fused
bias+activation CUDA paths; on TPU, XLA fuses the bias add and the activation
into the producing matmul automatically, so these stay plain jnp expressions.
"""

import jax
import jax.numpy as jnp
from jax import nn as jnn


def gelu(x):
    """Exact (erf) GELU — the reference's formulation (modeling.py:118-124)."""
    return x * 0.5 * (1.0 + jax.lax.erf(x / jnp.sqrt(2.0).astype(x.dtype)))


def bias_gelu(bias, y):
    """Fused bias + GELU (reference modeling.py:126-130)."""
    return gelu(y + bias)


def bias_tanh(bias, y):
    """Fused bias + tanh (reference modeling.py:132-134)."""
    return jnp.tanh(y + bias)


def swish(x):
    """x * sigmoid(x) (reference modeling.py:136-137)."""
    return x * jnn.sigmoid(x)


def relu(x):
    return jnn.relu(x)


ACT2FN = {
    "gelu": gelu,
    "bias_gelu": bias_gelu,
    "bias_tanh": bias_tanh,
    "relu": relu,
    "swish": swish,
    "tanh": jnp.tanh,
}
