"""Multi-head dot-product attention core.

TPU-native replacement for the reference's unfused score/softmax/context chain
(src/modeling.py:403-429 ``BertSelfAttention``): batched einsums land on the
MXU, the softmax runs in fp32 for bf16 safety, and the additive mask uses the
reference's ``(1 - mask) * -10000`` bias convention (modeling.py:862-870).

``backend='pallas'`` routes to a fused flash-style kernel for long sequences;
at BERT's seq<=512 the XLA path is already MXU-bound, so it is the default.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_attention_bias(input_mask: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """[B, S] {0,1} mask -> [B, 1, 1, S] additive bias, (1-m) * -10000.

    Parity with reference modeling.py:862-870 (``extended_attention_mask``).
    """
    bias = (1.0 - input_mask.astype(jnp.float32)) * -10000.0
    return bias[:, None, None, :].astype(dtype)


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    dropout_rng=None,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    backend: str = "xla",
) -> jnp.ndarray:
    """Attention over [B, S, H, D] query/key/value tensors.

    Returns [B, S, H, D]. Scores are scaled by 1/sqrt(D) and softmaxed in
    fp32 (modeling.py:403-429's score path, bf16-safe).
    """
    if backend == "pallas" and (deterministic or dropout_rate == 0.0):
        # The fused kernel does not implement attention dropout; when dropout
        # is active we fall back to the XLA path (same fused-or-fallback
        # policy as reference modeling.py:327-335).
        from bert_pytorch_tpu.ops.pallas.attention import flash_attention

        return flash_attention(q, k, v, bias=bias)
    if backend == "ring":
        # Context parallelism: sequence sharded over the mesh 'seq' axis
        # with K/V ring rotation (ops/ring.py). Falls back to dense when no
        # seq sharding is active (e.g. single-device tests of an sp model).
        from bert_pytorch_tpu.ops.ring import ring_attention
        from bert_pytorch_tpu.parallel.mesh import current_mesh

        mesh = current_mesh()
        if mesh is not None and mesh.shape.get("seq", 1) > 1:
            if q.shape[1] % mesh.shape["seq"] != 0:
                # Silently densifying here would materialize the O(S²)
                # scores exactly in the long-context regime ring exists for.
                raise ValueError(
                    f"backend='ring': sequence length {q.shape[1]} is not "
                    f"divisible by the mesh 'seq' axis ({mesh.shape['seq']}); "
                    "pad the sequence or resize the mesh")
            return ring_attention(
                q, k, v, bias=bias,
                dropout_rng=None if deterministic else dropout_rng,
                dropout_rate=0.0 if deterministic else dropout_rate,
                mesh=mesh,
            )

    depth = q.shape[-1]
    scale = 1.0 / jnp.sqrt(depth).astype(q.dtype)
    # [B, H, Sq, Sk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
    scores = scores.astype(jnp.float32)
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = probs.astype(q.dtype)
    if not deterministic and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep.astype(probs.dtype) / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
