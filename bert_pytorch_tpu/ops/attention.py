"""Multi-head dot-product attention core.

TPU-native replacement for the reference's unfused score/softmax/context chain
(src/modeling.py:403-429 ``BertSelfAttention``): batched einsums land on the
MXU, the softmax runs in fp32 for bf16 safety, and the additive mask uses the
reference's ``(1 - mask) * -10000`` bias convention (modeling.py:862-870).

``backend='pallas'`` routes to the fused flash-style kernel with in-kernel
dropout (ops/pallas/attention.py). Measured on one v5e chip, BERT-large
training with dropout: at seq 512 the fused kernel wins by ~60% (84 vs ~52
seq/s — the XLA path materializes the [B,H,S,S] probabilities/masks); at
seq 128 the XLA path still wins (396 vs 366). Rule of thumb: 'xla' for
phase-1 (seq<=128), 'pallas' for phase-2 (seq>=256) and anything longer.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def make_attention_bias(
    input_mask: jnp.ndarray,
    dtype=jnp.float32,
    sequence_ids: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """[B, S] {0,1} mask -> [B, 1, 1, S] additive bias, (1-m) * -10000.

    Parity with reference modeling.py:862-870 (``extended_attention_mask``).

    With ``sequence_ids`` ([B, S] int, 0 = pad, k = k-th packed sequence;
    data/packing.py), returns the BLOCK-DIAGONAL [B, 1, S, S] bias instead:
    position q may attend to position k iff both carry the same nonzero
    sequence id — the cross-contamination-free packing mask of Krell et al.
    2021 (arXiv:2107.02027). Padding is excluded by id 0, so ``input_mask``
    is redundant (and ignored) on this path.
    """
    if sequence_ids is not None:
        seg = sequence_ids
        same = (seg[:, :, None] == seg[:, None, :]) & (seg[:, :, None] > 0)
        bias = (1.0 - same.astype(jnp.float32)) * -10000.0
        return bias[:, None, :, :].astype(dtype)
    bias = (1.0 - input_mask.astype(jnp.float32)) * -10000.0
    return bias[:, None, None, :].astype(dtype)


def dot_product_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: jnp.ndarray | None = None,
    dropout_rng=None,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    backend: str = "xla",
    sequence_ids: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Attention over [B, S, H, D] query/key/value tensors.

    Returns [B, S, H, D]. Scores are scaled by 1/sqrt(D) and softmaxed in
    fp32 (modeling.py:403-429's score path, bf16-safe).

    ``sequence_ids`` ([B, S], 0 = pad) marks a PACKED batch
    (data/packing.py): on the XLA path the caller's ``bias`` is then the
    [B, 1, S, S] block-diagonal mask from :func:`make_attention_bias`; the
    Pallas path ignores that bias and regenerates the block-diagonal tile
    mask inside the kernel from the per-token id vectors, preserving its
    no-[B,H,S,S]-in-HBM property.
    """
    if backend == "auto":
        # Measured crossover (module docstring): the fused kernel wins from
        # seq ~256 up; below that the XLA path is faster. Off-TPU the kernel
        # would run in pure-Python interpret mode, so auto never picks it.
        from bert_pytorch_tpu.ops.pallas.common import interpret_mode

        backend = (
            "pallas" if q.shape[1] >= 256 and not interpret_mode() else "xla"
        )
    if backend in ("pallas_infer", "pallas_infer_int8"):
        # INFERENCE-ONLY fused forwards (ops/pallas/attention.py
        # flash_attention_infer / flash_attention_infer_int8): no dropout
        # plumbing, no lse/residuals for a backward that never runs —
        # selected by serve/engine.py's forwards. Deliberately NOT
        # reachable from training (no vjp is defined); dropout args are
        # rejected rather than ignored so a misrouted training call
        # fails loudly. The int8 variant quantizes QK^T with per-head
        # symmetric scales (softmax and PV stay higher precision —
        # docs/serving.md "Raw-speed kernels" for the parity bounds).
        from bert_pytorch_tpu.ops.pallas.attention import (
            flash_attention_infer, flash_attention_infer_int8)

        if not deterministic and dropout_rate > 0.0:
            raise ValueError(
                f"backend={backend!r} is forward-only; training "
                "dropout needs backend='pallas' or 'xla'")
        kbias = None if sequence_ids is not None else bias
        kernel = (flash_attention_infer_int8
                  if backend == "pallas_infer_int8"
                  else flash_attention_infer)
        return kernel(q, k, v, bias=kbias, sequence_ids=sequence_ids)
    if backend == "pallas":
        # Fused kernel incl. in-kernel dropout from the TPU hardware PRNG
        # (the [B,H,S,S] mask never reaches HBM; see ops/pallas/attention.py).
        # Interpret mode (CPU tests) has no PRNG lowering, so dropout falls
        # back to the XLA path there (the fused-or-fallback policy of
        # reference modeling.py:327-335).
        from bert_pytorch_tpu.ops.pallas.attention import flash_attention
        from bert_pytorch_tpu.ops.pallas.common import interpret_mode

        # Packed batches: the caller's bias is the [B, 1, S, S] block
        # diagonal, which the kernel must NOT consume — it rebuilds the
        # tile mask from the id vectors (pad keys carry id 0, so no
        # separate key bias is needed).
        kbias = None if sequence_ids is not None else bias
        active = not deterministic and dropout_rate > 0.0
        if not active:
            return flash_attention(q, k, v, bias=kbias,
                                   sequence_ids=sequence_ids)
        if not interpret_mode():
            return flash_attention(
                q, k, v, bias=kbias,
                dropout_rate=dropout_rate, dropout_rng=dropout_rng,
                sequence_ids=sequence_ids)
    if backend in ("ring", "ring_manual") and sequence_ids is not None:
        # Ring attention shards the sequence axis across chips; the
        # block-diagonal mask would need per-shard id exchange alongside
        # the K/V rotation — not implemented. Packing targets the padded
        # phase-1/2 shapes, context parallelism targets long single
        # documents; the combination has no workload yet.
        raise ValueError(
            "sequence packing (sequence_ids) is not supported with "
            "backend='ring'/'ring_manual'; use 'xla' or 'pallas'")
    if backend == "ring_manual":
        # Ring attention's per-shard body, for callers ALREADY inside a
        # region that is manual over the mesh 'seq' axis (the pipeline
        # engine's {pipe, seq} shard_map). q/k/v here are the LOCAL
        # [B, S/n, H, D] sequence shards and bias is the local
        # [B, 1, 1, S/n] key-bias slice; the K/V rotation happens via
        # ppermute over the ambient manual axis, with no nested shard_map
        # (Shardy rejects the nested-manual backward — parallel/pipeline.py).
        from bert_pytorch_tpu.ops.ring import _ring_shard
        from bert_pytorch_tpu.parallel.mesh import AXIS_SEQ

        batch, s_local = q.shape[0], q.shape[1]
        if bias is None:
            kbias = jnp.zeros((batch, s_local), jnp.float32)
        else:
            kbias = bias.reshape(batch, s_local).astype(jnp.float32)
        active = not deterministic and dropout_rate > 0.0
        return _ring_shard(
            q, k, v, kbias,
            dropout_rng if active else None,
            axis_name=AXIS_SEQ,
            dropout_rate=dropout_rate if active else 0.0,
        )
    if backend == "ring":
        # Context parallelism: sequence sharded over the mesh 'seq' axis
        # with K/V ring rotation (ops/ring.py). Falls back to dense when no
        # seq sharding is active (e.g. single-device tests of an sp model).
        from bert_pytorch_tpu.ops.ring import ring_attention
        from bert_pytorch_tpu.parallel.mesh import AXIS_SEQ, current_mesh

        mesh = current_mesh()
        if mesh is not None and mesh.shape.get(AXIS_SEQ, 1) > 1:
            if q.shape[1] % mesh.shape[AXIS_SEQ] != 0:
                # Silently densifying here would materialize the O(S²)
                # scores exactly in the long-context regime ring exists for.
                raise ValueError(
                    f"backend='ring': sequence length {q.shape[1]} is not "
                    f"divisible by the mesh 'seq' axis "
                    f"({mesh.shape[AXIS_SEQ]}); "
                    "pad the sequence or resize the mesh")
            return ring_attention(
                q, k, v, bias=bias,
                dropout_rng=None if deterministic else dropout_rng,
                dropout_rate=0.0 if deterministic else dropout_rate,
                mesh=mesh,
            )

    depth = q.shape[-1]
    scale = 1.0 / jnp.sqrt(depth).astype(q.dtype)
    # [B, H, Sq, Sk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k)
    scores = scores.astype(jnp.float32)
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = probs.astype(q.dtype)
    if not deterministic and dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, probs.shape)
        probs = probs * keep.astype(probs.dtype) / (1.0 - dropout_rate)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
