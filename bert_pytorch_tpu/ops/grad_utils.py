"""Gradient-norm utilities — TPU-native equivalent of the reference's
``amp_C.multi_tensor_l2norm`` / ``multi_tensor_scale`` fused global-norm
clipping (src/optimization.py:27-33, run_squad.py:703-725 ``GradientClipper``).

On TPU a global norm is one fused XLA reduction tree over the gradient pytree —
there is no multi-tensor-apply problem to solve; XLA flattens the per-leaf
square-sums into a handful of kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def global_norm(tree) -> jnp.ndarray:
    """L2 norm over every leaf of a pytree, accumulated in fp32."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(leaf.astype(jnp.float32))) for leaf in leaves)
    )


def clip_by_global_norm(tree, max_norm: float):
    """Scale the pytree so its global norm is at most ``max_norm``.

    Matches ``GradientClipper`` semantics (run_squad.py:703-725): a no-op scale
    when already within bounds.
    """
    norm = global_norm(tree)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype), tree), norm
