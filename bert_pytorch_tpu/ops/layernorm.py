"""LayerNorm — TPU-native equivalent of Apex ``FusedLayerNormAffineFunction``
(reference src/modeling.py:299-336).

The default backend is plain XLA: mean/variance reductions and the affine
transform fuse into one kernel on TPU, with statistics computed in fp32
regardless of the activation dtype (the bf16-safe policy replacing the
reference's fp16 AMP handling). A Pallas kernel backend is provided behind the
same function, mirroring the reference's fused-with-fallback structure
(modeling.py:327-335).
"""

from __future__ import annotations

import jax.lax
import jax.numpy as jnp


def layer_norm(
    x: jnp.ndarray,
    scale: jnp.ndarray,
    bias: jnp.ndarray,
    eps: float = 1e-12,
    backend: str = "xla",
) -> jnp.ndarray:
    """Normalize the last axis of ``x`` and apply the affine transform.

    Statistics are computed in fp32; the result is cast back to ``x.dtype``.
    """
    if backend == "pallas":
        from bert_pytorch_tpu.ops.pallas.layernorm import layer_norm_pallas

        return layer_norm_pallas(x, scale, bias, eps)

    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    normed = (x32 - mean) * jax.lax.rsqrt(var + eps)
    out = normed * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return out.astype(x.dtype)
