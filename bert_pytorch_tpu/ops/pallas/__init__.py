"""Pallas TPU kernels — the hand-written fused paths behind
:mod:`bert_pytorch_tpu.ops`.

These are the TPU-native counterparts of the reference's Apex CUDA kernels
(SURVEY.md §2.3): fused LayerNorm (``FusedLayerNormAffineFunction``,
modeling.py:299-336) and fused attention. Each is selected with
``backend='pallas'`` on the corresponding :mod:`bert_pytorch_tpu.ops`
function; the XLA path remains the default and the numerical reference.

On CPU (tests, smoke runs) the kernels run in Pallas interpret mode
automatically.
"""

from bert_pytorch_tpu.ops.pallas.layernorm import layer_norm_pallas
from bert_pytorch_tpu.ops.pallas.attention import flash_attention

__all__ = ["layer_norm_pallas", "flash_attention"]
