"""Flash-style fused attention Pallas kernel.

The fused-attention path of the framework (SURVEY.md §7 stage 8): scores,
masking, online softmax, and the value contraction happen in one kernel, so
the [B, H, S, S] score matrix never touches HBM. At BERT's seq<=512 the XLA
path is already MXU-bound, so this kernel's payoff is long-context headroom
(it is the single-chip building block under ring attention in
bert_pytorch_tpu/parallel/ring.py).

Forward is a Pallas kernel that also emits the log-sum-exp residual; the
backward recomputes probabilities from (q, k, bias, lse) with XLA einsums —
O(S²) memory in the backward only, an explicit v1 trade documented here.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bert_pytorch_tpu.ops.pallas.common import interpret_mode, pick_block

_NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, bias_ref, out_ref, lse_ref, *, block_k, scale):
    # q_ref: [1, block_q, D]; k_ref/v_ref: [1, S, D]; bias_ref: [1, 1, S]
    q = q_ref[0].astype(jnp.float32) * scale
    seq_k = k_ref.shape[1]
    block_q, depth = q.shape
    num_kb = seq_k // block_k

    def body(j, carry):
        m_prev, l_prev, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        b = bias_ref[0, 0, pl.ds(j * block_k, block_k)].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        s = s + b[None, :]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc

    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, depth), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    out_ref[0] = (acc / l[:, None]).astype(out_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)


def _flash_forward(q3, k3, v3, bias3, scale):
    """q3/k3/v3: [BH, S, D]; bias3: [BH, 1, S] additive key bias."""
    bh, seq, depth = q3.shape
    block_q = pick_block(seq, (256, 128, 64, 32, 16, 8))
    block_k = pick_block(seq, (256, 128, 64, 32, 16, 8))
    grid = (bh, seq // block_q)
    out, lse = pl.pallas_call(
        partial(_flash_fwd_kernel, block_k=block_k, scale=scale),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, depth), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, seq, depth), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, seq, depth), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, seq), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, depth), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, depth), q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(q3, k3, v3, bias3)
    return out, lse


@partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash(q3, k3, v3, bias3, scale):
    out, _ = _flash_forward(q3, k3, v3, bias3, scale)
    return out


def _flash_fwd(q3, k3, v3, bias3, scale):
    out, lse = _flash_forward(q3, k3, v3, bias3, scale)
    return out, (q3, k3, v3, bias3, out, lse)


def _flash_bwd(scale, residuals, g):
    q3, k3, v3, bias3, out, lse = residuals
    q = q3.astype(jnp.float32) * scale
    k = k3.astype(jnp.float32)
    v = v3.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    o32 = out.astype(jnp.float32)
    s = jnp.einsum("bqd,bkd->bqk", q, k) + bias3.astype(jnp.float32)
    p = jnp.exp(s - lse[:, 0, :, None])  # [BH, Sq, Sk]
    dv = jnp.einsum("bqk,bqd->bkd", p, g32)
    dp = jnp.einsum("bqd,bkd->bqk", g32, v)
    delta = jnp.sum(g32 * o32, axis=-1, keepdims=True)
    ds = p * (dp - delta)
    dq = jnp.einsum("bqk,bkd->bqd", ds, k) * scale
    dk = jnp.einsum("bqk,bqd->bkd", ds, q)
    dbias = jnp.sum(ds, axis=1, keepdims=True)  # [BH, 1, Sk]
    return (
        dq.astype(q3.dtype),
        dk.astype(k3.dtype),
        dv.astype(v3.dtype),
        dbias.astype(bias3.dtype),
    )


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, bias=None):
    """Fused attention over [B, S, H, D] tensors.

    ``bias`` is the [B, 1, 1, S] additive mask from
    :func:`bert_pytorch_tpu.ops.attention.make_attention_bias` (key-only bias;
    a full [B, H, Sq, Sk] bias is not supported by this kernel). Attention
    dropout is not applied here — callers fall back to the XLA path when
    dropout is active (see ops/attention.py).
    """
    batch, seq, heads, depth = q.shape
    scale = 1.0 / float(depth) ** 0.5

    def to3(t):
        return t.transpose(0, 2, 1, 3).reshape(batch * heads, seq, depth)

    if bias is None:
        bias3 = jnp.zeros((batch * heads, 1, seq), jnp.float32)
    else:
        key_bias = bias.reshape(batch, -1)[:, -seq:]  # [B, S]
        bias3 = jnp.repeat(key_bias.astype(jnp.float32), heads, axis=0)[:, None, :]
    out3 = _flash(to3(q), to3(k), to3(v), bias3, scale)
    return out3.reshape(batch, heads, seq, depth).transpose(0, 2, 1, 3)
