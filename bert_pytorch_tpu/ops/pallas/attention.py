"""Flash-style fused attention Pallas kernels (forward AND backward).

The fused-attention path of the framework (SURVEY.md §7 stage 8): scores,
masking, online softmax, dropout, and the value contraction happen in one
kernel, so neither the [B, H, S, S] score matrix nor the dropout mask ever
touches HBM. This is the capability Apex's fused kernels give the reference
on GPU (SURVEY §2.3) — built TPU-native:

  - **In-kernel dropout from the TPU hardware PRNG** (``pltpu.prng_seed`` /
    ``prng_random_bits``). The reference's attention dropout
    (modeling.py:424-427) materializes a [B, H, S, S] mask; at seq 512 that
    mask traffic alone costs ~30% of the training step. Here each
    [block_q, block_k] tile's mask is (re)generated from
    ``seed ^ (batch*head, q_block, k_block)`` on demand — the backward pass
    regenerates bit-identical masks instead of loading them.
  - **Pallas backward**: two kernels (dq; dk/dv/dbias) recompute
    probabilities from (q, k, bias, lse) blockwise — O(S) memory end to end,
    replacing the v1 XLA backward that materialized [B*H, S, S].
  - **Packed-batch block-diagonal masking** (``sequence_ids``; sequence
    packing, data/packing.py): each tile regenerates its
    cross-contamination mask from the per-token sequence-id vectors
    ([BH, 1, S] fp32, the bias layout) — the [B, 1, S, S] mask the XLA
    path materializes never exists in HBM, exactly like the dropout mask;
    the backward kernels rebuild the identical mask when recomputing
    probabilities. Statically gated (``segmented``), so unpacked callers
    compile the same kernel as before.

Derivation with dropout (rate r, keep mask D ∈ {0,1}, P = softmax(S)):
  out   = (D ⊙ P) V / (1-r)
  dV    = (D ⊙ P)ᵀ dO / (1-r)
  dA    = dO Vᵀ;   delta = rowsum(dO ⊙ out)
  dS    = P ⊙ (D ⊙ dA / (1-r) − delta)       (softmax vjp; delta absorbs the
  dQ    = dS K · scale;  dK = dSᵀ Q · scale    rowsum(P ⊙ dP) term exactly as
  dbias = Σ_q dS                               in the dropout-free case)

The streaming forward accumulates ``l`` with *unmasked* probabilities (so
lse stays the true log-sum-exp) and the output accumulator with masked ones;
the 1/(1-r) scale is applied once in the final normalization.

Interpret-mode (CPU) limitation: the TPU PRNG primitives have no CPU
lowering, so ``dropout_rate > 0`` requires a real TPU; rate 0 runs everywhere
(tests compare it against the XLA path, and the dropout statistics are
validated on-chip).

Measured (one v5e chip, BERT-large training step, remat='dots', rbg host
dropout for the non-attention dropouts): seq 512 batch 28 — XLA attention
~52 seq/s with dropout; this kernel 84.3 with dropout (512-wide tiles +
8 bh pairs per program; the original 256x256 single-bh tiles measured
70.7). Seq 128 still favors the XLA path (366 vs 396 seq/s at the phase-1
bench shape) — bh-batching closes most but not all of the short-seq grid
overhead. See ops/attention.py for routing.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bert_pytorch_tpu.ops.pallas import autotune
from bert_pytorch_tpu.ops.pallas.common import interpret_mode, pick_block

_NEG_INF = -1e30


def _keep_mask(seed_ref, tile_id, shape, rate):
    """Regenerable [block_q, block_k] keep mask for one score tile.

    Seeding per tile (rather than streaming one generator) is what lets the
    backward kernels iterate tiles in any order and still reproduce the
    forward's draws. ``tile_id`` linearizes (batch*head, q_block, k_block);
    Mosaic supports at most 2 seed words, hence the fold.
    """
    pltpu.prng_seed(seed_ref[0], tile_id)
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    threshold = jnp.uint32(min(int(rate * (1 << 32)), (1 << 32) - 1))
    return bits >= threshold


def _tile_id(bh, qb, kb, num_qb, num_kb):
    return (bh * num_qb + qb) * num_kb + kb


def _pick_blocks(seq):
    """(block_q, block_k) for a sequence length. Forward and backward MUST
    use the same blocks: the dropout keep-mask is regenerated per tile from
    (bh, q_block, k_block), so differing tile boundaries would silently
    compute gradients under a different mask than the forward applied."""
    # 512-wide tiles win at seq 512 (5.0 vs 7.2 ms fwd+bwd for the
    # BERT-large shape with 256x256): fewer grid steps amortize the
    # pipeline, and VMEM stays modest (512x512 fp32 scores = 1MB).
    # pick_block's default candidate ladder tops out at 512 for this reason.
    return pick_block(seq), pick_block(seq)


def _pick_bh_block(seq, bh):
    """How many (batch*head) pairs each program processes (an unrolled loop
    in the kernel). Short sequences make per-bh tiles tiny, so the grid —
    not the MXU — bounds throughput; batching pairs per program amortizes
    it. G does NOT affect the dropout masks: tile ids are derived from the
    recovered global bh index and the block_q/block_k grid, so any G (even
    different ones for forward and backward) regenerates identical masks —
    the load-bearing invariant is block agreement, documented on
    _pick_blocks.

    Measured, BERT-large phase-2 shape (seq 512, one v5e): G=1 82.4,
    G=4 84.0, G=8 84.25 seq/s; G=16 exhausts VMEM (tile footprint scales
    with G x seq, hence the 4096 budget). At seq 128 G=16 is the best of
    the sweep (314 -> 366 seq/s), though the XLA path still wins there and
    stays the router default (ops/attention.py).

    PALLAS_ATTN_BH_BLOCK overrides the target cap (not the divisibility
    walk) so the capture sweep can probe past the conservative VMEM
    heuristic at short sequence lengths — e.g. G=32 at seq 128, where the
    4096 budget leaves half of VMEM unused. The env var is read at TRACE
    time: changing it mid-process has no effect on shapes already
    compiled, so sweeps must probe each value in a fresh subprocess (the
    capture sweep does)."""
    import os

    env = os.environ.get("PALLAS_ATTN_BH_BLOCK")
    if env:
        try:
            target = int(env)
        except ValueError:
            raise ValueError(
                f"PALLAS_ATTN_BH_BLOCK must be an integer, got {env!r}"
            ) from None
    else:
        target = min(16, max(1, 4096 // max(seq, 1)))
    g = 1
    while g * 2 <= target and bh % (g * 2) == 0:
        g *= 2
    return g


def _infer_geometry(kernel, seq, bh, geometry):
    """Resolve the (block_q, block_k, bh_block) triple for one inference
    kernel call: an explicit ``geometry`` (the autotune measurement loop
    forcing a candidate) wins, then a persisted autotune winner
    (ops/pallas/autotune.py — read at TRACE time, so winners must load
    before the first forward traces), then the hand-written heuristic.
    Divisibility is validated here because a winner loaded from a file
    is data, not code: a ragged grid must fail at trace with a real
    message, not inside Mosaic."""
    if geometry is not None:
        block_q, block_k, g = geometry
    else:
        cached = autotune.lookup(kernel, seq, bh)
        if cached is not None:
            block_q, block_k, g = cached
        else:
            block_q, block_k = _pick_blocks(seq)
            g = _pick_bh_block(seq, bh)
    if seq % block_q or seq % block_k or bh % g:
        raise ValueError(
            f"attention geometry (block_q={block_q}, block_k={block_k}, "
            f"bh_block={g}) does not tile seq={seq}, bh={bh}")
    return int(block_q), int(block_k), int(g)


def _seg_mask(q_seg, k_seg):
    """Additive block-diagonal tile mask from per-token sequence-id
    vectors (packing, data/packing.py): q may attend to k iff both carry
    the same NONZERO id. Ids travel as fp32 [G, 1, S] rows — the exact
    layout of bias_ref, so Mosaic sees nothing new — and small-int
    equality in fp32 is exact. The -10000 additive convention matches
    make_attention_bias, keeping the XLA and Pallas packed paths
    numerically aligned (masked scores underflow to exactly 0 after the
    fp32 exp in both)."""
    same = (q_seg[:, None] == k_seg[None, :]) & (q_seg[:, None] > 0.5)
    return jnp.where(same, 0.0, -10000.0)


def _flash_fwd_kernel(
    seed_ref, q_ref, k_ref, v_ref, bias_ref, seg_ref, out_ref, lse_ref,
    *, block_k, scale, rate, bh_block, segmented
):
    # q_ref: [G, block_q, D]; k_ref/v_ref: [G, S, D]; bias_ref/seg_ref:
    # [G, 1, S], where G = bh_block (batch*head) pairs per program — an
    # unrolled loop that amortizes the grid at short sequence lengths
    # (_pick_bh_block). ``segmented`` statically gates the packed
    # block-diagonal mask (_seg_mask); unpacked callers pay nothing.
    # Matmul operands stay in the input dtype (bf16 in training) with fp32
    # accumulation — a single MXU pass per dot; casting inputs up to fp32
    # first would decompose each matmul into several passes. The softmax
    # chain (max/exp/sum) runs in fp32 throughout.
    qb = pl.program_id(1)
    seq_k = k_ref.shape[1]
    num_kb = seq_k // block_k

    for g in range(bh_block):
        bh = pl.program_id(0) * bh_block + g
        q = q_ref[g]
        block_q = q.shape[0]
        if segmented:
            q_seg = seg_ref[g, 0, pl.ds(qb * block_q, block_q)]

        def body(j, carry):
            m_prev, l_prev, acc = carry
            k = k_ref[g, pl.ds(j * block_k, block_k), :]
            v = v_ref[g, pl.ds(j * block_k, block_k), :]
            b = bias_ref[g, 0, pl.ds(j * block_k, block_k)].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale  # [block_q, block_k]
            s = s + b[None, :]
            if segmented:
                k_seg = seg_ref[g, 0, pl.ds(j * block_k, block_k)]
                s = s + _seg_mask(q_seg, k_seg)
            m_cur = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(s - m_new[:, None])
            # l accumulates the TRUE softmax denominator (unmasked) so lse
            # is exact; only the value accumulation sees the dropout mask.
            l_new = l_prev * alpha + jnp.sum(p, axis=-1)
            if rate > 0.0:
                tid = _tile_id(bh, qb, j, pl.num_programs(1), num_kb)
                p_v = jnp.where(_keep_mask(seed_ref, tid, p.shape, rate), p, 0.0)
            else:
                p_v = p
            acc = acc * alpha[:, None] + jax.lax.dot_general(
                p_v.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc

        m0 = jnp.full((q.shape[0],), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((q.shape[0],), jnp.float32)
        acc0 = jnp.zeros(q.shape, jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
        out_ref[g] = (acc / (l[:, None] * (1.0 - rate))).astype(out_ref.dtype)
        lse_ref[g, 0] = m + jnp.log(l)


def _flash_dq_kernel(
    seed_ref, q_ref, k_ref, v_ref, bias_ref, seg_ref, lse_ref, delta_ref,
    do_ref, dq_ref, *, block_k, scale, rate, bh_block, segmented
):
    """dq for [G, block_q, D] tiles (G bh pairs/program); loops over k blocks."""
    qb = pl.program_id(1)
    seq_k = k_ref.shape[1]
    num_kb = seq_k // block_k
    inv_keep = 1.0 / (1.0 - rate)

    for g in range(bh_block):
        bh = pl.program_id(0) * bh_block + g
        q = q_ref[g]
        lse = lse_ref[g, 0]  # [block_q]
        delta = delta_ref[g, 0]  # [block_q]
        do = do_ref[g]  # [block_q, D]
        if segmented:
            q_seg = seg_ref[g, 0, pl.ds(qb * q.shape[0], q.shape[0])]

        def body(j, dq_acc):
            k = k_ref[g, pl.ds(j * block_k, block_k), :]
            v = v_ref[g, pl.ds(j * block_k, block_k), :]
            b = bias_ref[g, 0, pl.ds(j * block_k, block_k)].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale + b[None, :]
            if segmented:
                # Identical mask regeneration as the forward — the
                # probabilities below must be the ones the forward used.
                s = s + _seg_mask(
                    q_seg, seg_ref[g, 0, pl.ds(j * block_k, block_k)])
            p = jnp.exp(s - lse[:, None])  # normalized probabilities
            da = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [block_q, block_k]
            if rate > 0.0:
                tid = _tile_id(bh, qb, j, pl.num_programs(1), num_kb)
                keep = _keep_mask(seed_ref, tid, p.shape, rate)
                da = jnp.where(keep, da * inv_keep, 0.0)
            ds = p * (da - delta[:, None])
            return dq_acc + jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        dq = jax.lax.fori_loop(0, num_kb, body, jnp.zeros(q.shape, jnp.float32))
        dq_ref[g] = (dq * scale).astype(dq_ref.dtype)


def _flash_dkv_kernel(
    seed_ref, q_ref, k_ref, v_ref, bias_ref, seg_ref, lse_ref, delta_ref,
    do_ref, dk_ref, dv_ref, dbias_ref, *, block_q, scale, rate, bh_block,
    segmented
):
    """dk/dv/dbias for [G, block_k, D] tiles; loops over q blocks."""
    kb = pl.program_id(1)
    seq_q = q_ref.shape[1]
    num_qb = seq_q // block_q
    inv_keep = 1.0 / (1.0 - rate)

    for g in range(bh_block):
        bh = pl.program_id(0) * bh_block + g
        k = k_ref[g]  # [block_k, D]
        v = v_ref[g]
        b = bias_ref[g, 0].astype(jnp.float32)  # [block_k]
        block_k, depth = k.shape
        if segmented:
            k_seg = seg_ref[g, 0, pl.ds(kb * block_k, block_k)]

        def body(i, carry):
            dk_acc, dv_acc, db_acc = carry
            q = q_ref[g, pl.ds(i * block_q, block_q), :]
            lse = lse_ref[g, 0, pl.ds(i * block_q, block_q)]
            delta = delta_ref[g, 0, pl.ds(i * block_q, block_q)]
            do = do_ref[g, pl.ds(i * block_q, block_q), :]
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale + b[None, :]
            if segmented:
                s = s + _seg_mask(
                    seg_ref[g, 0, pl.ds(i * block_q, block_q)], k_seg)
            p = jnp.exp(s - lse[:, None])  # [block_q, block_k]
            da = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            if rate > 0.0:
                tid = _tile_id(bh, i, kb, num_qb, pl.num_programs(1))
                keep = _keep_mask(seed_ref, tid, p.shape, rate)
                p_v = jnp.where(keep, p * inv_keep, 0.0)
                da = jnp.where(keep, da * inv_keep, 0.0)
            else:
                p_v = p
            # dV += (D ⊙ P)ᵀ dO / (1-r)
            dv_acc = dv_acc + jax.lax.dot_general(
                p_v.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            ds = p * (da - delta[:, None])
            dk_acc = dk_acc + jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return dk_acc, dv_acc, db_acc + jnp.sum(ds, axis=0)

        dk, dv, db = jax.lax.fori_loop(
            0,
            num_qb,
            body,
            (
                jnp.zeros((block_k, depth), jnp.float32),
                jnp.zeros((block_k, depth), jnp.float32),
                jnp.zeros((block_k,), jnp.float32),
            ),
        )
        dk_ref[g] = (dk * scale).astype(dk_ref.dtype)
        dv_ref[g] = dv.astype(dv_ref.dtype)
        dbias_ref[g, 0] = db.astype(dbias_ref.dtype)


def _seed_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _flash_forward(q3, k3, v3, bias3, seg3, seed, scale, rate, segmented):
    """q3/k3/v3: [BH, S, D]; bias3: [BH, 1, S] additive key bias; seg3:
    [BH, 1, S] fp32 sequence ids (all-zero dummy when not segmented)."""
    bh, seq, depth = q3.shape
    block_q, block_k = _pick_blocks(seq)
    g = _pick_bh_block(seq, bh)
    grid = (bh // g, seq // block_q)
    out, lse = pl.pallas_call(
        partial(_flash_fwd_kernel, block_k=block_k, scale=scale, rate=rate,
                bh_block=g, segmented=segmented),
        grid=grid,
        in_specs=[
            _seed_spec(),
            pl.BlockSpec((g, block_q, depth), lambda b, i: (b, i, 0)),
            pl.BlockSpec((g, seq, depth), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((g, seq, depth), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((g, 1, seq), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((g, 1, seq), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((g, block_q, depth), lambda b, i: (b, i, 0)),
            pl.BlockSpec((g, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, depth), q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(seed, q3, k3, v3, bias3, seg3)
    return out, lse


@partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _flash(q3, k3, v3, bias3, seg3, seed, scale, rate, segmented):
    out, _ = _flash_forward(q3, k3, v3, bias3, seg3, seed, scale, rate,
                            segmented)
    return out


def _flash_fwd(q3, k3, v3, bias3, seg3, seed, scale, rate, segmented):
    out, lse = _flash_forward(q3, k3, v3, bias3, seg3, seed, scale, rate,
                              segmented)
    return out, (q3, k3, v3, bias3, seg3, seed, out, lse)


def _flash_bwd(scale, rate, segmented, residuals, g):
    q3, k3, v3, bias3, seg3, seed, out, lse = residuals
    bh, seq, depth = q3.shape
    block_q, block_k = _pick_blocks(seq)
    # delta = rowsum(dO ⊙ O): one cheap fused XLA reduction, [BH, 1, S].
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )[:, None, :]

    gb = _pick_bh_block(seq, bh)
    dq = pl.pallas_call(
        partial(_flash_dq_kernel, block_k=block_k, scale=scale, rate=rate,
                bh_block=gb, segmented=segmented),
        grid=(bh // gb, seq // block_q),
        in_specs=[
            _seed_spec(),
            pl.BlockSpec((gb, block_q, depth), lambda b, i: (b, i, 0)),
            pl.BlockSpec((gb, seq, depth), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((gb, seq, depth), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((gb, 1, seq), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((gb, 1, seq), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((gb, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((gb, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((gb, block_q, depth), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((gb, block_q, depth), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq, depth), q3.dtype),
        interpret=interpret_mode(),
    )(seed, q3, k3, v3, bias3, seg3, lse, delta, g)

    dk, dv, dbias = pl.pallas_call(
        partial(_flash_dkv_kernel, block_q=block_q, scale=scale, rate=rate,
                bh_block=gb, segmented=segmented),
        grid=(bh // gb, seq // block_k),
        in_specs=[
            _seed_spec(),
            pl.BlockSpec((gb, seq, depth), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((gb, block_k, depth), lambda b, j: (b, j, 0)),
            pl.BlockSpec((gb, block_k, depth), lambda b, j: (b, j, 0)),
            pl.BlockSpec((gb, 1, block_k), lambda b, j: (b, 0, j)),
            # seg needs the k tile AND every q block: full row, like lse.
            pl.BlockSpec((gb, 1, seq), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((gb, 1, seq), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((gb, 1, seq), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((gb, seq, depth), lambda b, j: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((gb, block_k, depth), lambda b, j: (b, j, 0)),
            pl.BlockSpec((gb, block_k, depth), lambda b, j: (b, j, 0)),
            pl.BlockSpec((gb, 1, block_k), lambda b, j: (b, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, depth), k3.dtype),
            jax.ShapeDtypeStruct((bh, seq, depth), v3.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(seed, q3, k3, v3, bias3, seg3, lse, delta, g)

    dseed = np.zeros(seed.shape, dtype=jax.dtypes.float0)
    dseg = jnp.zeros_like(seg3)  # ids are data, not parameters
    return dq, dk, dv, dbias.astype(bias3.dtype), dseg, dseed


_flash.defvjp(_flash_fwd, _flash_bwd)


def _infer_stream(score_fn, v_ref, g, block_k, num_kb, q_shape, out_dtype):
    """The shared online-softmax + PV stream of the inference kernels:
    ``score_fn(j)`` returns the j-th fully-masked fp32
    [block_q, block_k] score tile, and everything downstream — the
    running max/exp/sum bookkeeping, the PV contraction in the value
    dtype with fp32 accumulation, the final normalization — is ONE body
    shared by the fp and int8 score paths, so a fix to the stream can
    never silently diverge between them."""

    def body(j, carry):
        m_prev, l_prev, acc = carry
        s = score_fn(j)
        v = v_ref[g, pl.ds(j * block_k, block_k), :]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc

    m0 = jnp.full((q_shape[0],), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((q_shape[0],), jnp.float32)
    acc0 = jnp.zeros(q_shape, jnp.float32)
    _, l, acc = jax.lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    return (acc / l[:, None]).astype(out_dtype)


def _infer_fwd_kernel(
    q_ref, k_ref, v_ref, bias_ref, seg_ref, out_ref,
    *, block_k, scale, bh_block, segmented
):
    """INFERENCE-ONLY forward (docs/serving.md "Inference fast path").

    The training kernel (:func:`_flash_fwd_kernel`) carries three things
    a serving forward never uses: the dropout PRNG plumbing (seed ref,
    per-tile mask regeneration), the ``lse`` output written for the
    backward kernels, and the unmasked-``l`` bookkeeping that keeps that
    lse exact. This variant drops all of it — no seed input, no second
    output, one accumulator pair (:func:`_infer_stream`) — while
    keeping the packed block-diagonal tile mask (``segmented``;
    serve-side request packing reuses it). Same tile geometry as
    training (_pick_blocks / _pick_bh_block) unless an autotune winner
    overrides it, so the VMEM/grid reasoning there carries over.
    """
    qb = pl.program_id(1)
    seq_k = k_ref.shape[1]
    num_kb = seq_k // block_k

    for g in range(bh_block):
        q = q_ref[g]
        if segmented:
            block_q = q.shape[0]
            q_seg = seg_ref[g, 0, pl.ds(qb * block_q, block_q)]

        def score(j, g=g, q=q):
            k = k_ref[g, pl.ds(j * block_k, block_k), :]
            b = bias_ref[g, 0, pl.ds(j * block_k, block_k)].astype(
                jnp.float32)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale + b[None, :]
            if segmented:
                k_seg = seg_ref[g, 0, pl.ds(j * block_k, block_k)]
                s = s + _seg_mask(q_seg, k_seg)
            return s

        out_ref[g] = _infer_stream(score, v_ref, g, block_k, num_kb,
                                   q.shape, out_ref.dtype)


def _infer_bias_seg(bias, sequence_ids, batch, seq, heads, name):
    """(bias3, seg3, segmented) — the shared [BH, 1, S] key-bias and
    sequence-id rows of the inference wrappers."""
    segmented = sequence_ids is not None
    if segmented and bias is not None:
        raise ValueError(
            f"{name}: pass either bias (padded batches) or "
            "sequence_ids (packed batches), not both")
    if segmented:
        seg3 = jnp.repeat(
            sequence_ids.astype(jnp.float32), heads, axis=0)[:, None, :]
    else:
        seg3 = jnp.zeros((batch * heads, 1, seq), jnp.float32)
    if bias is None:
        bias3 = jnp.zeros((batch * heads, 1, seq), jnp.float32)
    else:
        key_bias = bias.reshape(batch, -1)[:, -seq:]  # [B, S]
        bias3 = jnp.repeat(
            key_bias.astype(jnp.float32), heads, axis=0)[:, None, :]
    return bias3, seg3, segmented


def flash_attention_infer(q, k, v, bias=None, sequence_ids=None,
                          geometry=None):
    """Forward-only fused attention over [B, S, H, D] tensors — the
    serving path's kernel (``backend='pallas_infer'``,
    ops/attention.py). Contract matches :func:`flash_attention` at
    ``dropout_rate=0`` minus everything the backward needs: no residuals
    are saved, no lse is written, and no vjp is defined (differentiating
    through it is an error by design — training keeps its own kernel).
    ``sequence_ids`` retains the packed block-diagonal tile mask so
    packed serve batches (serve/engine.py) stay contamination-free
    without a [B, 1, S, S] mask in HBM. Runs in interpret mode on CPU
    (no PRNG primitives involved), which is how tier-1 tests parity.

    ``geometry`` forces one (block_q, block_k, bh_block) triple — the
    autotune measurement loop's hook; normal callers leave it None and
    get the persisted winner or the heuristic (:func:`_infer_geometry`).
    """
    batch, seq, heads, depth = q.shape
    scale = 1.0 / float(depth) ** 0.5

    def to3(t):
        return t.transpose(0, 2, 1, 3).reshape(batch * heads, seq, depth)

    bias3, seg3, segmented = _infer_bias_seg(
        bias, sequence_ids, batch, seq, heads, "flash_attention_infer")
    q3, k3, v3 = to3(q), to3(k), to3(v)
    bh = batch * heads
    block_q, block_k, g = _infer_geometry("infer", seq, bh, geometry)
    out3 = pl.pallas_call(
        partial(_infer_fwd_kernel, block_k=block_k, scale=scale,
                bh_block=g, segmented=segmented),
        grid=(bh // g, seq // block_q),
        in_specs=[
            pl.BlockSpec((g, block_q, depth), lambda b, i: (b, i, 0)),
            pl.BlockSpec((g, seq, depth), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((g, seq, depth), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((g, 1, seq), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((g, 1, seq), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((g, block_q, depth), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq, depth), q3.dtype),
        interpret=interpret_mode(),
    )(q3, k3, v3, bias3, seg3)
    return out3.reshape(batch, heads, seq, depth).transpose(0, 2, 1, 3)


def _infer_fwd_kernel_int8(
    q_ref, k_ref, v_ref, qs_ref, ks_ref, bias_ref, seg_ref, out_ref,
    *, block_k, scale, bh_block, segmented
):
    """Int8-score inference forward (ZeroQuant into the attention path,
    docs/serving.md "Raw-speed kernels").

    q_ref/k_ref are PRE-QUANTIZED int8 tiles ([G, block_q, D] /
    [G, S, D]) with one symmetric fp32 scale per (batch*head) row
    (qs_ref/ks_ref, [G, 1, 1] — the per-token dynamic-scale machinery
    of ops/quant.py ``int8_matmul`` generalized to a per-head grain:
    one head's q/k rows share dynamics, so one scale per head keeps the
    rescale a scalar per program instead of a [block_q, block_k] outer
    product). QK^T runs int8 x int8 -> int32 on the MXU; the rescale by
    ``q_scale * k_scale * softmax_scale`` happens once per tile in
    fp32, and everything downstream — the online softmax, the PV
    contraction (v untouched: P·V stays in the input dtype with fp32
    accumulation), the normalization — IS :func:`_infer_stream`, the
    same body the fp kernel runs; only the score tile differs.
    """
    qb = pl.program_id(1)
    seq_k = k_ref.shape[1]
    num_kb = seq_k // block_k

    for g in range(bh_block):
        q8 = q_ref[g]
        rescale = (qs_ref[g, 0, 0] * ks_ref[g, 0, 0]).astype(jnp.float32) \
            * scale
        if segmented:
            block_q = q8.shape[0]
            q_seg = seg_ref[g, 0, pl.ds(qb * block_q, block_q)]

        def score(j, g=g, q8=q8, rescale=rescale):
            k8 = k_ref[g, pl.ds(j * block_k, block_k), :]
            b = bias_ref[g, 0, pl.ds(j * block_k, block_k)].astype(
                jnp.float32)
            s32 = jax.lax.dot_general(
                q8, k8, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32,
            )  # [block_q, block_k] int32
            s = s32.astype(jnp.float32) * rescale + b[None, :]
            if segmented:
                k_seg = seg_ref[g, 0, pl.ds(j * block_k, block_k)]
                s = s + _seg_mask(q_seg, k_seg)
            return s

        out_ref[g] = _infer_stream(score, v_ref, g, block_k, num_kb,
                                   q8.shape, out_ref.dtype)


def flash_attention_infer_int8(q, k, v, bias=None, sequence_ids=None,
                               geometry=None):
    """Forward-only fused attention with INT8 QK^T over [B, S, H, D]
    tensors (``backend='pallas_infer_int8'``, ops/attention.py).

    Same contract as :func:`flash_attention_infer` (no vjp, packed
    ``sequence_ids`` masking, interpret-mode on CPU) with the score
    matmul quantized: q and k are dynamically quantized to int8 with one
    symmetric scale PER HEAD (per [batch*head] row — ops/quant.py
    ``quantize_symmetric``), the tile dot runs int8 x int8 -> int32,
    and a single fp32 rescale recovers the scores. Softmax and the PV
    contraction stay at the higher precision of the base kernel, so the
    only new error source is score rounding: |Δscore| <=
    (|q|·scale_k + |k|·scale_q + scale_q·scale_k·D/4) / sqrt(D) per
    element — model-level bounds are documented (docs/serving.md) and
    asserted by tests/test_kernels_fastpath.py on all four serve heads.
    """
    from bert_pytorch_tpu.ops import quant as quant_ops

    batch, seq, heads, depth = q.shape
    scale = 1.0 / float(depth) ** 0.5

    def to3(t):
        return t.transpose(0, 2, 1, 3).reshape(batch * heads, seq, depth)

    bias3, seg3, segmented = _infer_bias_seg(
        bias, sequence_ids, batch, seq, heads, "flash_attention_infer_int8")
    q3, k3, v3 = to3(q), to3(k), to3(v)
    bh = batch * heads
    # Per-head symmetric dynamic quantization, computed by XLA outside
    # the kernel (two cheap reductions fused into the surrounding
    # program); the kernel consumes the int8 tensors + [BH, 1, 1] scales.
    q8, q_scale = quant_ops.quantize_symmetric(q3, axes=(1, 2))
    k8, k_scale = quant_ops.quantize_symmetric(k3, axes=(1, 2))
    block_q, block_k, g = _infer_geometry("infer_int8", seq, bh, geometry)
    out3 = pl.pallas_call(
        partial(_infer_fwd_kernel_int8, block_k=block_k, scale=scale,
                bh_block=g, segmented=segmented),
        grid=(bh // g, seq // block_q),
        in_specs=[
            pl.BlockSpec((g, block_q, depth), lambda b, i: (b, i, 0)),
            pl.BlockSpec((g, seq, depth), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((g, seq, depth), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((g, 1, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((g, 1, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((g, 1, seq), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((g, 1, seq), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((g, block_q, depth), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, seq, depth), q3.dtype),
        interpret=interpret_mode(),
    )(q8, k8, v3, q_scale, k_scale, bias3, seg3)
    return out3.reshape(batch, heads, seq, depth).transpose(0, 2, 1, 3)


def flash_attention(q, k, v, bias=None, dropout_rate=0.0, dropout_rng=None,
                    sequence_ids=None):
    """Fused attention over [B, S, H, D] tensors.

    ``bias`` is the [B, 1, 1, S] additive mask from
    :func:`bert_pytorch_tpu.ops.attention.make_attention_bias` (key-only
    bias; a full [B, H, Sq, Sk] bias is not supported by this kernel).

    ``sequence_ids`` ([B, S] int, 0 = pad) enables PACKED-batch attention
    (data/packing.py): each [block_q, block_k] tile regenerates its
    block-diagonal mask from the per-token id vectors inside the kernel —
    the [B, 1, S, S] mask the XLA path materializes never exists in HBM,
    the same property the dropout mask already has. Padding is excluded by
    id 0, so ``bias`` is redundant (and must be None) on this path.

    ``dropout_rate > 0`` applies attention-probability dropout *inside* the
    kernel using the TPU hardware PRNG, seeded from ``dropout_rng`` — the
    [B, H, S, S] mask never exists in HBM and the backward regenerates it
    from the same seed. Requires a real TPU (no interpret-mode lowering).
    """
    batch, seq, heads, depth = q.shape
    scale = 1.0 / float(depth) ** 0.5

    def to3(t):
        return t.transpose(0, 2, 1, 3).reshape(batch * heads, seq, depth)

    segmented = sequence_ids is not None
    if segmented and bias is not None:
        raise ValueError(
            "flash_attention: pass either bias (padded batches) or "
            "sequence_ids (packed batches), not both — packed padding is "
            "already encoded as sequence id 0")
    if segmented:
        seg3 = jnp.repeat(
            sequence_ids.astype(jnp.float32), heads, axis=0)[:, None, :]
    else:
        seg3 = jnp.zeros((batch * heads, 1, seq), jnp.float32)
    if bias is None:
        bias3 = jnp.zeros((batch * heads, 1, seq), jnp.float32)
    else:
        key_bias = bias.reshape(batch, -1)[:, -seq:]  # [B, S]
        bias3 = jnp.repeat(key_bias.astype(jnp.float32), heads, axis=0)[:, None, :]
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        # Derive a scalar seed from the key's raw data — no PRNG computation,
        # just bits; tile indices decorrelate the per-tile streams. A
        # position-dependent multiply-xor hash, NOT a plain xor-fold:
        # threefry keys are [0, n] (first word constant) and rbg keys are two
        # duplicated halves [t0, t1, t0, t1] (xor-fold would cancel to 0 for
        # EVERY rbg key — the training default).
        data = jax.random.key_data(dropout_rng).ravel().astype(jnp.uint32)
        seed = jnp.uint32(0)
        for idx in range(data.shape[0]):  # static length, unrolls in trace
            seed = (seed * jnp.uint32(0x9E3779B1)
                    + jnp.uint32(2 * idx + 1)) ^ data[idx]
        seed = seed.astype(jnp.int32)[None]
    else:
        seed = jnp.zeros((1,), jnp.int32)
    out3 = _flash(to3(q), to3(k), to3(v), bias3, seg3, seed, scale,
                  float(dropout_rate), segmented)
    return out3.reshape(batch, heads, seq, depth).transpose(0, 2, 1, 3)
