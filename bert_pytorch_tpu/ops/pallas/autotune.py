"""Measured block-geometry autotune for the Pallas inference kernels.

``_pick_blocks`` / ``_pick_bh_block`` (ops/pallas/attention.py) are
hand-written heuristics: good defaults, but FlashAttention's own result
(Dao 2022, arXiv:2205.14135) is that the IO-aware tiling choice is
worth measuring, not guessing — the best (block_q, block_k, bh_block)
triple shifts with sequence length, batch*heads, and backend. This
module lets serving PAY FOR THE MEASUREMENT ONCE and remember it:

* :func:`measure` times every candidate geometry of one inference
  kernel at one ``(seq, bh, depth)`` shape (median of ``repeats`` timed
  calls after an untimed compile call) and records the winner;
* winners persist as a small JSON file (:func:`save_winners` /
  :func:`load_winners`) next to the persisted AOT compile cache, with
  the SAME keying discipline: the registry key covers (kernel, seq, bh)
  and the file stamps the backend platform + interpret mode, and the
  serve engine folds the winner digest into its stable jitted-forward
  names (serve/engine.py) — the compile-cache key derives from the
  fn-name-derived HLO module name, so a warm restart that loads the
  winners file compiles the SAME programs under the SAME names and
  warms entirely from the persistent cache (``compiles_cold == 0``
  still holds, the PR-8 acceptance);
* :func:`lookup` is the kernels' consult point: a cached winner wins,
  otherwise the caller falls back to the heuristic. Winners are read at
  TRACE time (the same property as PALLAS_ATTN_BH_BLOCK): load them
  BEFORE the first forward traces — the serve engine loads in
  ``__init__``, before warmup — because already-compiled shapes never
  re-read the registry.

The registry is PROCESS-GLOBAL, not per-engine: an engine built with
``autotune="off"`` in a process where another engine (or a test)
already loaded winners for the same (kernel, seq, bh) will trace with
those winners too. That is safe — the serve engine folds the winner
digest into its forward names regardless of its own autotune mode, so
names always describe the geometry actually compiled and the compile
cache never aliases — but it means heuristic-vs-winner A/B comparisons
must isolate processes or :func:`clear_winners` between legs (the
BENCH_KERNELS leg orders its engines accordingly; tests use a
clear_winners fixture).

On CPU the kernels run in interpret mode, so measured timings rank
pure-Python emulation, not MXU behavior — the mechanism (measure,
persist, reload, warm-restart) is what CPU proves; real geometry wins
ride the on-chip capture harness. The registry and file format are
platform-stamped so CPU winners never leak into a TPU process.

Module-level imports stay jax-free on purpose: the winners-file FORMAT
validator below is shared with the jax-free lint gate
(``bert_pytorch_tpu/analysis/check_all.py`` loads this module by file
path, the telemetry/schema.py technique).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

WINNERS_VERSION = 1

# Inference kernel variants the registry keys on (ops/pallas/attention.py).
KERNELS = ("infer", "infer_int8")

# (kernel, seq, bh) -> {"block_q": int, "block_k": int, "bh_block": int,
#                       "measured_ms": float}
_winners: Dict[Tuple[str, int, int], dict] = {}
_lock = threading.Lock()


def _key(kernel: str, seq: int, bh: int) -> str:
    """The file spelling of a registry key — one flat string so the
    winners JSON stays grep-able and diff-able."""
    return f"{kernel}:s{int(seq)}:bh{int(bh)}"


def _parse_key(key: str) -> Optional[Tuple[str, int, int]]:
    parts = key.split(":")
    if len(parts) != 3 or not parts[1].startswith("s") \
            or not parts[2].startswith("bh"):
        return None
    try:
        return parts[0], int(parts[1][1:]), int(parts[2][2:])
    except ValueError:
        return None


def lookup(kernel: str, seq: int, bh: int) -> Optional[Tuple[int, int, int]]:
    """Cached winner ``(block_q, block_k, bh_block)`` or None (caller
    falls back to the heuristic). Read at trace time by the kernels."""
    with _lock:
        entry = _winners.get((kernel, int(seq), int(bh)))
    if entry is None:
        return None
    return entry["block_q"], entry["block_k"], entry["bh_block"]


def record_winner(kernel: str, seq: int, bh: int, block_q: int,
                  block_k: int, bh_block: int,
                  measured_ms: Optional[float] = None) -> None:
    entry = {"block_q": int(block_q), "block_k": int(block_k),
             "bh_block": int(bh_block)}
    if measured_ms is not None:
        entry["measured_ms"] = round(float(measured_ms), 4)
    with _lock:
        _winners[(kernel, int(seq), int(bh))] = entry


def clear_winners() -> None:
    """Reset the process-global registry (tests)."""
    with _lock:
        _winners.clear()


def name_digest(kernel: str, seq: int, bh: int) -> str:
    """Short digest of the cached winner geometry, or "" when none.

    The serve engine appends this to its stable jitted-forward names
    (``serve_<task>_b<bucket>..._g<digest>``) so a GEOMETRY change
    invalidates exactly its own persistent-compile-cache entry — the
    cache keys on the fn-name-derived HLO module name, and without the
    suffix a new winner would recompile under the old name, silently
    aliasing two different programs to one cache identity. No winner →
    no suffix: the heuristic is deterministic per (seq, bh), so the
    plain name already names one program.
    """
    geom = lookup(kernel, seq, bh)
    if geom is None:
        return ""
    text = f"{kernel}:{seq}:{bh}:{geom[0]}x{geom[1]}g{geom[2]}"
    return hashlib.sha1(text.encode()).hexdigest()[:6]


# -- persistence ------------------------------------------------------------


def _platform() -> Tuple[str, bool]:
    import jax

    from bert_pytorch_tpu.ops.pallas.common import interpret_mode

    return jax.default_backend(), interpret_mode()


def save_winners(path: str) -> int:
    """Write the registry to ``path`` (atomic rename); returns the entry
    count. Stamps the backend platform + interpret mode so a loader on
    a different backend ignores the file instead of importing timings
    measured under a different execution model."""
    platform, interpret = _platform()
    with _lock:
        body = {_key(k, s, b): dict(entry)
                for (k, s, b), entry in sorted(_winners.items())}
    payload = {"version": WINNERS_VERSION, "platform": platform,
               "interpret": interpret, "winners": body}
    tmp = f"{path}.tmp.{os.getpid()}"
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return len(body)


def load_winners(path: str) -> int:
    """Merge a winners file into the registry; returns how many entries
    loaded. A missing file loads zero (fresh start); a file from another
    platform loads zero (its timings rank a different execution model);
    a malformed file raises ValueError — a corrupt cache must fail loud,
    not silently detune."""
    if not os.path.exists(path):
        return 0
    with open(path, "r", encoding="utf-8") as f:
        payload = json.load(f)
    errors = validate_winners(payload)
    if errors:
        raise ValueError(
            f"autotune winners file {path} is malformed: {errors[0]}")
    platform, interpret = _platform()
    if payload["platform"] != platform or \
            bool(payload.get("interpret")) != interpret:
        return 0
    loaded = 0
    with _lock:
        for key, entry in payload["winners"].items():
            parsed = _parse_key(key)
            if parsed is None:
                continue
            _winners[parsed] = {
                k: entry[k] for k in
                ("block_q", "block_k", "bh_block", "measured_ms")
                if k in entry}
            loaded += 1
    return loaded


def validate_winners(payload) -> List[str]:
    """Format errors for a decoded winners file (empty list = valid).

    jax-free on purpose: ``analysis/check_all.py`` loads this module by
    file path and runs this over every winners JSON it is given, the
    same lint-at-the-source discipline as the telemetry record schema.
    """
    if not isinstance(payload, dict):
        return [f"winners file is {type(payload).__name__}, not an object"]
    errors = []
    if payload.get("version") != WINNERS_VERSION:
        errors.append(f"unknown version {payload.get('version')!r}")
    if not isinstance(payload.get("platform"), str) \
            or not payload.get("platform"):
        errors.append("platform must be a non-empty string")
    if not isinstance(payload.get("interpret"), bool):
        errors.append("interpret must be a boolean")
    winners = payload.get("winners")
    if not isinstance(winners, dict):
        return errors + ["winners must be an object"]
    for key, entry in winners.items():
        parsed = _parse_key(key)
        if parsed is None:
            errors.append(f"winner key {key!r} is not "
                          "<kernel>:s<seq>:bh<bh>")
            continue
        kernel, seq, bh = parsed
        if kernel not in KERNELS:
            errors.append(f"winner key {key!r}: unknown kernel "
                          f"{kernel!r} (known: {KERNELS})")
        if not isinstance(entry, dict):
            errors.append(f"winner {key!r} must be an object")
            continue
        for field in ("block_q", "block_k", "bh_block"):
            v = entry.get(field)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                errors.append(
                    f"winner {key!r}.{field} must be a positive integer, "
                    f"got {v!r}")
                continue
            if field.startswith("block") and seq % v != 0:
                errors.append(
                    f"winner {key!r}.{field}={v} does not divide "
                    f"seq {seq} — the kernel grid would be ragged")
            if field == "bh_block" and bh % v != 0:
                errors.append(
                    f"winner {key!r}.bh_block={v} does not divide "
                    f"bh {bh} — the kernel grid would be ragged")
        ms = entry.get("measured_ms")
        if ms is not None and (not isinstance(ms, (int, float))
                               or isinstance(ms, bool) or ms < 0):
            errors.append(
                f"winner {key!r}.measured_ms must be a non-negative "
                f"number, got {ms!r}")
    return errors


def validate_winners_file(path: str) -> List[str]:
    """File-level wrapper for the lint gate: parse + validate."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            payload = json.load(f)
    except OSError as exc:
        return [f"unreadable: {exc}"]
    except ValueError as exc:
        return [f"not valid JSON: {exc}"]
    return validate_winners(payload)


# -- measurement ------------------------------------------------------------


def candidates(seq: int, bh: int, max_bh_block: int = 16
               ) -> List[Tuple[int, int, int]]:
    """The candidate ``(block_q, block_k, bh_block)`` grid for one shape:
    square q/k tiles over the hardware-friendly divisor ladder (the same
    ladder ``pick_block`` walks, inlined here to keep this module's
    import surface jax-free — forward-only kernels have no fwd/bwd
    block-agreement constraint, but square tiles keep the grid small and
    match the measured-best training geometry), crossed with every
    power-of-two bh grouping that divides ``bh``."""
    blocks = [c for c in (512, 256, 128, 64, 32, 16, 8)
              if c <= seq and seq % c == 0]
    if not blocks:
        blocks = [seq]
    groups = []
    g = 1
    while g <= min(bh, max_bh_block):
        if bh % g == 0:
            groups.append(g)
        g *= 2
    return [(b, b, g) for b in blocks for g in groups]


def measure(kernel: str, seq: int, bh: int, depth: int,
            dtype=None, repeats: int = 3,
            clock=None) -> dict:
    """Time every candidate geometry of one inference kernel at one
    shape; records (and returns) the winner.

    Each candidate compiles once (untimed) then runs ``repeats`` timed
    calls; the median wall time ranks it. Runs OUTSIDE any
    CompileMonitor-instrumented wrapper, so the candidate compiles never
    pollute the serve engine's warm/cold startup split.
    """
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bert_pytorch_tpu.ops.pallas import attention as pallas_attention

    if kernel not in KERNELS:
        raise ValueError(f"kernel must be one of {KERNELS}, got {kernel!r}")
    clock = clock or _time.perf_counter
    dtype = dtype or jnp.float32
    kernel_fn = (pallas_attention.flash_attention_infer_int8
                 if kernel == "infer_int8"
                 else pallas_attention.flash_attention_infer)
    # One bh-sized batch of single-head rows keeps the measured grid
    # identical to the serve forward's [B*H, S, D] kernel view.
    rng = np.random.default_rng(0)
    shape = (bh, seq, 1, depth)
    q, k, v = (jnp.asarray(rng.standard_normal(shape), dtype)
               for _ in range(3))
    results = []
    last_exc: Optional[Exception] = None
    for geom in candidates(seq, bh):
        fn = jax.jit(lambda q, k, v, _g=geom: kernel_fn(q, k, v,
                                                        geometry=_g))
        try:
            jax.block_until_ready(fn(q, k, v))  # compile, untimed
            times = []
            for _ in range(repeats):
                t0 = clock()
                jax.block_until_ready(fn(q, k, v))
                times.append(clock() - t0)
            results.append((sorted(times)[len(times) // 2], geom))
        except Exception as exc:
            # A geometry the backend rejects is not a winner; keep the
            # cause so an all-candidates failure is debuggable below.
            last_exc = exc
            continue
    if not results:
        raise RuntimeError(
            f"autotune: no candidate geometry for {kernel} seq={seq} "
            f"bh={bh} survived measurement") from last_exc
    best_ms, best = min(results, key=lambda r: r[0])
    best_ms *= 1000.0
    record_winner(kernel, seq, bh, *best, measured_ms=best_ms)
    return {"kernel": kernel, "seq": int(seq), "bh": int(bh),
            "winner": {"block_q": best[0], "block_k": best[1],
                       "bh_block": best[2]},
            "candidates": len(results), "measured_ms": round(best_ms, 4)}
