"""Shared helpers for Pallas kernels."""

from __future__ import annotations

import jax


def interpret_mode() -> bool:
    """Run kernels in interpreter mode on non-TPU backends so the same code
    paths are testable on the virtual CPU mesh (SURVEY.md §4's Gloo analog)."""
    return jax.default_backend() != "tpu"


def pick_block(size: int, candidates=(512, 256, 128, 64, 32, 16, 8)) -> int:
    """Largest hardware-friendly block that divides ``size``."""
    for c in candidates:
        if size % c == 0 and c <= size:
            return c
    return size
