"""Fused LayerNorm Pallas kernel.

TPU-native equivalent of Apex ``FusedLayerNormAffineFunction``
(reference src/modeling.py:299-336): one pass over each row computes the
moments in fp32 and applies the affine transform, tiled over rows so the
hidden dimension stays resident in VMEM.

Forward is a Pallas kernel; the backward is a custom VJP computed with plain
XLA ops from saved (x, mean, rstd) — the backward is bandwidth-bound
elementwise math that XLA fuses well, so a hand kernel buys nothing there.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bert_pytorch_tpu.ops.pallas.common import interpret_mode, pick_block


def _ln_fwd_kernel(x_ref, scale_ref, bias_ref, out_ref, mean_ref, rstd_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    centered = x - mean
    var = jnp.mean(centered * centered, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    normed = centered * rstd
    out = normed * scale_ref[:].astype(jnp.float32) + bias_ref[:].astype(jnp.float32)
    out_ref[:] = out.astype(out_ref.dtype)
    mean_ref[:] = mean
    rstd_ref[:] = rstd


def _ln_forward(x2d, scale, bias, eps):
    rows, hidden = x2d.shape
    block_rows = pick_block(rows, (256, 128, 64, 32, 16, 8, 4, 2, 1))
    grid = (rows // block_rows,)
    out, mean, rstd = pl.pallas_call(
        partial(_ln_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, hidden), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, hidden), x2d.dtype),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=interpret_mode(),
    )(x2d, scale, bias)
    return out, mean, rstd


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm_p(x2d, scale, bias, eps):
    out, _, _ = _ln_forward(x2d, scale, bias, eps)
    return out


def _layer_norm_p_fwd(x2d, scale, bias, eps):
    out, mean, rstd = _ln_forward(x2d, scale, bias, eps)
    return out, (x2d, scale, mean, rstd)


def _layer_norm_p_bwd(eps, residuals, g):
    x2d, scale, mean, rstd = residuals  # mean/rstd: [rows, 1]
    x = x2d.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    normed = (x - mean) * rstd
    dscale = jnp.sum(g32 * normed, axis=0)
    dbias = jnp.sum(g32, axis=0)
    # dx for y = normed*scale + bias, normed = (x-mean)*rstd:
    gs = g32 * scale.astype(jnp.float32)
    dx = rstd * (
        gs
        - jnp.mean(gs, axis=-1, keepdims=True)
        - normed * jnp.mean(gs * normed, axis=-1, keepdims=True)
    )
    return dx.astype(x2d.dtype), dscale.astype(scale.dtype), dbias.astype(scale.dtype)


_layer_norm_p.defvjp(_layer_norm_p_fwd, _layer_norm_p_bwd)


def layer_norm_pallas(x, scale, bias, eps: float = 1e-12):
    """LayerNorm over the last axis of arbitrary-rank ``x``."""
    hidden = x.shape[-1]
    x2d = x.reshape(-1, hidden)
    out = _layer_norm_p(x2d, scale, bias, eps)
    return out.reshape(x.shape)
