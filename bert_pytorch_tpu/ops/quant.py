"""Weight quantization for the inference fast path (docs/serving.md).

Serving never updates weights, so the fp32 master copies training needs
are pure overhead there: a BERT-large replica holds ~1.3 GB of fp32
matmul weights that int8 stores in ~330 MB. This module owns the two
inference quantization levels (ZeroQuant lineage, arXiv:2206.01861 —
see PAPERS.md):

* ``"bf16"`` — matmul kernels and dense biases stored bfloat16; a pure
  storage cast (the compute path already runs bf16 activations).
* ``"int8"`` — matmul kernels stored int8 with ONE symmetric per-tensor
  scale (per-layer for the encoder's ``nn.scan`` stacks, whose kernels
  carry a leading 'layers' axis); the serve forward quantizes
  activations per token on the fly and runs ``int8 x int8 -> int32``
  GEMMs, rescaling once by ``act_scale * kernel_scale``. Biases ride
  bf16.

Embeddings and LayerNorm parameters stay fp32 in BOTH modes: they are a
small fraction of the bytes, they feed normalization statistics where
precision matters, and the MLM decoder is weight-tied to the word
embedding. The tiny task-head output layers (``EXCLUDE_MODULES``) also
skip int8 — a 2-class classifier kernel saves nothing and sits right
before the softmax where quantization noise is least welcome.

The quantization RULES live here once and are consumed from both sides:
:func:`quantize_params` converts an in-memory fp32 pytree (the engine's
demo/random-init path), and :func:`convert_module` is the per-module
hook :func:`bert_pytorch_tpu.utils.checkpoint.load_params_only` calls
from its STREAMING msgpack decode — each tensor converts as its bytes
arrive, so the full fp32 tree never exists on the serving host.

Measured on this repo's CPU CI box (XLA CPU has no fast s8 GEMM): int8
is ~3x SLOWER than fp32 per matmul — the latency win is a TPU(MXU)
property; CPU tests prove parity and the 4x weight-byte reduction
(tests/test_inference_fastpath.py, bench.py BENCH_SERVE_QUANT leg).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

MODES = ("bf16", "int8")

# Dense modules whose kernels stay OUT of int8 (downgraded to bf16):
# the per-task output layers, each a [hidden, <=num_labels] matmul that
# is noise-sensitive (pre-softmax) and byte-irrelevant.
EXCLUDE_MODULES = frozenset({"classifier", "qa_outputs", "seq_relationship"})

# Symmetric int8 range. 127 (not 128) keeps the scale symmetric around
# zero so -w and +w quantize to -q and +q exactly.
_QMAX = 127.0


def check_mode(mode: Optional[str]) -> Optional[str]:
    if mode is not None and mode not in MODES:
        raise ValueError(f"quantize mode must be one of {MODES} or None, "
                         f"got {mode!r}")
    return mode


def quantize_array(w, per_axis0: bool = False
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """``(q_int8, scale_fp32)`` with symmetric per-tensor scaling.

    ``per_axis0=True`` treats the leading axis as a stack of independent
    tensors (the encoder's ``nn.scan`` layer stacks) and returns one
    scale per slice — shape ``(L,)`` — so a quiet layer is not forced
    onto a loud layer's grid. Host-side (numpy): this runs at load time,
    tensor by tensor, inside the streaming checkpoint decode.
    """
    w = np.asarray(w, dtype=np.float32)
    if per_axis0 and w.ndim >= 2:
        axes = tuple(range(1, w.ndim))
        amax = np.max(np.abs(w), axis=axes)
        scale = np.maximum(amax, 1e-12) / _QMAX
        bshape = (-1,) + (1,) * (w.ndim - 1)
        q = np.rint(w / scale.reshape(bshape))
    else:
        amax = np.max(np.abs(w)) if w.size else 0.0
        scale = np.float32(max(float(amax), 1e-12) / _QMAX)
        q = np.rint(w / scale)
    q = np.clip(q, -_QMAX, _QMAX).astype(np.int8)
    return q, np.asarray(scale, np.float32)


def dequantize_array(q, scale) -> np.ndarray:
    """Inverse of :func:`quantize_array` (tests / debugging)."""
    q = np.asarray(q, np.float32)
    scale = np.asarray(scale, np.float32)
    if scale.ndim:
        scale = scale.reshape((-1,) + (1,) * (q.ndim - 1))
    return q * scale


def int8_matmul(x, q_kernel, kernel_scale):
    """``x @ dequant(q_kernel)`` computed as an int8 GEMM.

    ``x`` [..., K] float activations; ``q_kernel`` [K, N] int8;
    ``kernel_scale`` a scalar (per-tensor). Activations are quantized
    PER TOKEN (last-axis abs-max) on the fly — dynamic quantization, no
    calibration pass — then one ``int8 x int8 -> int32`` dot runs on
    the MXU and the result rescales once by both scales. fp32 out; the
    caller casts to its activation dtype.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    a_scale = jnp.maximum(amax, 1e-8) / _QMAX
    qx = jnp.clip(jnp.round(xf / a_scale), -_QMAX, _QMAX).astype(jnp.int8)
    acc = jax.lax.dot_general(
        qx, q_kernel,
        (((xf.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * a_scale * kernel_scale.astype(jnp.float32)


def quantize_symmetric(x, axes: Union[int, Sequence[int]]):
    """``(q_int8, scale)`` symmetric dynamic quantization of a traced
    array, one scale per slice of the axes NOT in ``axes`` (kept as
    size-1 dims so the scale broadcasts back over ``q``).

    The jnp twin of :func:`int8_matmul`'s inline per-token activation
    quantization, factored out at a caller-chosen grain: the int8
    attention kernel (ops/pallas/attention.py
    ``flash_attention_infer_int8``) reduces over ``axes=(1, 2)`` of a
    [BH, S, D] tensor for one symmetric scale PER HEAD — the ZeroQuant
    activation-scale machinery generalized beyond the dense layers.
    """
    axes = (axes,) if isinstance(axes, int) else tuple(axes)
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axes, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / _QMAX
    q = jnp.clip(jnp.round(xf / scale), -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def _normalize_axis(axis: Union[int, Sequence[int]], ndim: int
                    ) -> Tuple[int, ...]:
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return tuple(a % ndim for a in axes)


class Int8Dense(nn.Module):
    """Drop-in for the serve heads' ``nn.Dense``/``nn.DenseGeneral``
    call sites with an int8 kernel + per-tensor scale + bf16 bias.

    ``features`` may be an int or a tuple (DenseGeneral-style), and
    ``axis`` the contracted input axes — the kernel parameter keeps the
    EXACT shape its fp32 counterpart has (``(*in_dims, *out_dims)``), so
    :func:`quantize_array` of a checkpoint kernel drops straight in.
    Parameter values from ``init`` are placeholders (zeros/ones): the
    engine always overwrites them, either from a checkpoint via the
    streaming quantized load or from a seeded fp32 init via
    :func:`quantize_params`. Never used in training — no custom_vjp, the
    rounding is non-differentiable by design.
    """

    features: Union[int, Tuple[int, ...]]
    axis: Union[int, Tuple[int, ...]] = -1
    dtype: Any = jnp.bfloat16
    kernel_axes: Tuple[str, ...] = ()
    bias_axes: Tuple[str, ...] = ()

    @nn.compact
    def __call__(self, x):
        features = (self.features,) if isinstance(self.features, int) \
            else tuple(self.features)
        axes = _normalize_axis(self.axis, x.ndim)
        in_dims = tuple(x.shape[a] for a in axes)
        kernel_shape = in_dims + features
        q = self.param(
            "kernel_q",
            nn.with_logical_partitioning(nn.initializers.zeros,
                                         self.kernel_axes),
            kernel_shape, jnp.int8)
        scale = self.param("kernel_scale", nn.initializers.ones,
                           (), jnp.float32)
        bias = self.param(
            "bias",
            nn.with_logical_partitioning(nn.initializers.zeros,
                                         self.bias_axes),
            features, jnp.bfloat16)
        # Collapse contracted/feature dims to one 2D GEMM; DenseGeneral
        # semantics (contracted axes are trailing at these call sites).
        batch_shape = tuple(s for i, s in enumerate(x.shape)
                            if i not in axes)
        k = int(np.prod(in_dims))
        n = int(np.prod(features))
        y = int8_matmul(x.reshape(batch_shape + (k,)),
                        q.reshape(k, n), scale)
        y = y.reshape(batch_shape + features)
        return y.astype(self.dtype) + bias.astype(self.dtype)


def make_dense(quant: Optional[str], features, *, dtype, init_stddev: float,
               kernel_axes: Tuple[str, ...], name: str,
               axis: Union[int, Tuple[int, ...]] = -1,
               bias_axes: Optional[Tuple[str, ...]] = None):
    """One factory for every dense call site the serve heads share with
    training (models/bert.py): ``quant=None`` builds the EXACT
    ``nn.Dense``/``nn.DenseGeneral`` training uses (fp32 params),
    ``"bf16"`` the same module with bf16 param storage, ``"int8"`` the
    :class:`Int8Dense` replacement. Parameter names/shapes per mode are
    what :func:`convert_module` produces from a checkpoint.
    """
    check_mode(quant)
    bias_axes = bias_axes if bias_axes is not None else (
        (kernel_axes[-1],) if kernel_axes else ())
    if quant == "int8":
        return Int8Dense(features=features, axis=axis, dtype=dtype,
                         kernel_axes=kernel_axes, bias_axes=bias_axes,
                         name=name)
    param_dtype = jnp.bfloat16 if quant == "bf16" else jnp.float32
    from bert_pytorch_tpu.models.bert import bert_normal_init

    kwargs = dict(
        features=features,
        dtype=dtype,
        param_dtype=param_dtype,
        kernel_init=nn.with_logical_partitioning(
            bert_normal_init(init_stddev), kernel_axes),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros, bias_axes),
        name=name,
    )
    if isinstance(features, int) and (axis == -1 or axis == (-1,)):
        return nn.Dense(**kwargs)
    return nn.DenseGeneral(axis=axis, **kwargs)


def exclude(quant: Optional[str]) -> Optional[str]:
    """Quant mode for the EXCLUDE_MODULES output layers: int8 downgrades
    to bf16 storage, bf16/None pass through."""
    return "bf16" if quant == "int8" else quant


# -- checkpoint/pytree conversion -------------------------------------------


def _is_stacked(path: Tuple[str, ...]) -> bool:
    # The encoder's nn.scan stacks per-layer params under a path
    # component named by nn.PARTITION_NAME ('layers'); those kernels
    # carry a leading L axis and want one scale per layer.
    return "layers" in path


def convert_module(path: Tuple[str, ...], module: dict,
                   mode: str) -> dict:
    """Apply the quantization rules to ONE decoded module dict (the
    innermost state-dict dicts holding array leaves). Called bottom-up
    by the streaming checkpoint decode and by :func:`quantize_params` —
    the single place the rules live.

    Only dicts containing a ``kernel`` leaf convert (Dense/DenseGeneral
    modules); everything else — embeddings, LayerNorm scale/bias, the
    MLM vocab bias — passes through at checkpoint precision (fp32).
    """
    check_mode(mode)
    kernel = module.get("kernel")
    if not hasattr(kernel, "dtype"):
        return module
    out = dict(module)
    excluded = any(p in EXCLUDE_MODULES for p in path)
    if mode == "int8" and not excluded:
        q, scale = quantize_array(kernel, per_axis0=_is_stacked(path))
        del out["kernel"]
        out["kernel_q"] = q
        out["kernel_scale"] = scale
    else:
        out["kernel"] = np.asarray(kernel).astype(jnp.bfloat16)
    if hasattr(out.get("bias"), "dtype"):
        out["bias"] = np.asarray(out["bias"]).astype(jnp.bfloat16)
    return out


def quantize_params(params: Any, mode: str) -> dict:
    """fp32 params pytree -> quantized plain-dict tree (the engine's
    random-init/demo path; the checkpoint path converts while streaming
    from disk instead — utils/checkpoint.py ``load_params_only``)."""
    check_mode(mode)
    from flax import serialization

    state = serialization.to_state_dict(params)

    def walk(path, node):
        if not isinstance(node, dict):
            return node
        out = {k: walk(path + (k,), v) for k, v in node.items()}
        leaves_only = {k: v for k, v in out.items()
                       if not isinstance(v, dict)}
        if "kernel" in leaves_only:
            for k in leaves_only:
                del out[k]
            out.update(convert_module(path, leaves_only, mode))
        return out

    return walk((), jax.tree_util.tree_map(np.asarray, state))


def weight_bytes(params: Any) -> int:
    """Total parameter bytes of a (possibly quantized) tree — the
    serving HBM the weights pin; /statsz and bench stamp it."""
    return int(sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(params)
        if hasattr(leaf, "nbytes")))
