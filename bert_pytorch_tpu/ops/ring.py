"""Ring attention — context parallelism over the mesh's ``seq`` axis.

The reference has no long-context support at all (SURVEY.md §5.7: plain
O(S²) dense attention, seq<=512). This op makes sequence length a sharded
dimension: each device holds an S/n slice of Q, K and V; K/V blocks rotate
around the ring with ``lax.ppermute`` while each device accumulates its
queries' attention with a numerically stable running softmax
(flash-attention-style m/num/den carry). Communication is nearest-neighbor
over ICI and overlaps with the block matmuls, so attention memory and
per-device compute scale as S/n with no S² materialization anywhere.

Usage: ``dot_product_attention(..., backend='ring')`` inside a
``with mesh:`` context whose mesh has a ``seq`` axis > 1 (see
``parallel.MeshConfig(seq=n)`` and the 'sp' strategy rules). Falls back to
the dense XLA path when no sequence sharding is active — the same
fused-or-fallback policy as the Pallas kernels (reference modeling.py's
Apex-or-Python pattern at :327-335).

Attention-probability dropout follows the dense semantics: probabilities
are dropped *after* softmax normalization, which in the streaming form
means the numerator accumulates dropped p while the denominator accumulates
the full p. Each (device, ring-step) block gets an independent rng stream.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _ring_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kbias: jnp.ndarray,
    rng: Optional[jnp.ndarray],
    *,
    axis_name: str,
    rng_axes: tuple = (),
    dropout_rate: float = 0.0,
) -> jnp.ndarray:
    """Per-shard body (runs inside shard_map).

    q/k/v: [B, S_local, H, D]; kbias: [B, S_local] additive key bias.
    ``rng_axes`` are the other mesh axes the inputs are sharded over —
    folded into the dropout stream so every (batch shard, head shard,
    q shard, k block) draws an independent mask.
    """
    n = jax.lax.psum(1, axis_name)
    batch, s_q, heads, depth = q.shape
    scale = 1.0 / jnp.sqrt(depth).astype(q.dtype)
    qs = q * scale

    if dropout_rate > 0.0 and rng is not None:
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis_name))
        for ax in rng_axes:
            rng = jax.random.fold_in(rng, jax.lax.axis_index(ax))

    def block(k, v, kb, m, num, den, step):
        scores = jnp.einsum("bqhd,bkhd->bhqk", qs, k).astype(jnp.float32)
        scores = scores + kb[:, None, None, :].astype(jnp.float32)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)  # 0 on the first block (m = -inf)
        if dropout_rate > 0.0 and rng is not None:
            keep = jax.random.bernoulli(
                jax.random.fold_in(rng, step), 1.0 - dropout_rate, p.shape
            )
            p_num = p * keep.astype(p.dtype) / (1.0 - dropout_rate)
        else:
            p_num = p
        blk = jnp.einsum(
            "bhqk,bkhd->bqhd", p_num.astype(v.dtype), v
        ).astype(jnp.float32)
        num = num * corr.transpose(0, 2, 1)[..., None] + blk
        den = den * corr + p.sum(axis=-1)
        return m_new, num, den

    m0 = jnp.full((batch, heads, s_q), -jnp.inf, jnp.float32)
    den0 = jnp.zeros((batch, heads, s_q), jnp.float32)
    num0 = jnp.zeros((batch, s_q, heads, depth), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Local block first, then n-1 rotate-and-accumulate steps (no wasted
    # final rotation).
    m, num, den = block(k, v, kbias, m0, num0, den0, 0)

    def body(carry, step):
        k, v, kb, m, num, den = carry
        k, v, kb = jax.lax.ppermute((k, v, kb), axis_name, perm)
        m, num, den = block(k, v, kb, m, num, den, step)
        return (k, v, kb, m, num, den), None

    (_, _, _, m, num, den), _ = jax.lax.scan(
        jax.checkpoint(body), (k, v, kbias, m, num, den), jnp.arange(1, n)
    )
    out = num / den.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,
    dropout_rng=None,
    dropout_rate: float = 0.0,
    mesh=None,
    seq_axis: str = "seq",
    batch_axes=("data", "fsdp"),
    heads_axis: str = "model",
) -> jnp.ndarray:
    """Sequence-sharded attention over global [B, S, H, D] tensors.

    ``bias`` is the [B, 1, 1, S] (or [B, S]) additive key mask from
    :func:`make_attention_bias`. Requires an ambient (or explicit) mesh with
    ``seq_axis`` size > 1; S must divide by that size.
    """
    from bert_pytorch_tpu.parallel.mesh import current_mesh

    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None or mesh.shape.get(seq_axis, 1) <= 1:
        raise ValueError(
            "ring attention needs an active mesh with a "
            f"'{seq_axis}' axis > 1 (got {None if mesh is None else dict(mesh.shape)})"
        )
    batch, seq, heads, _ = q.shape
    if seq % mesh.shape[seq_axis] != 0:
        raise ValueError(
            f"sequence length {seq} not divisible by mesh "
            f"'{seq_axis}' axis {mesh.shape[seq_axis]}"
        )
    if bias is None:
        kbias = jnp.zeros((batch, seq), jnp.float32)
    else:
        kbias = bias.reshape(batch, seq).astype(jnp.float32)

    # Shard batch/heads only when they divide (model init traces at batch 1;
    # replication there is free — it never runs real data).
    n_batch = 1
    for ax in batch_axes:
        n_batch *= mesh.shape.get(ax, 1)
    b_spec = batch_axes if n_batch > 1 and batch % n_batch == 0 else None
    h_spec = (heads_axis
              if heads % mesh.shape.get(heads_axis, 1) == 0 else None)

    rng_axes = tuple(batch_axes) if b_spec is not None else ()
    if h_spec is not None and mesh.shape.get(heads_axis, 1) > 1:
        rng_axes = rng_axes + (heads_axis,)

    qkv_spec = P(b_spec, seq_axis, h_spec, None)
    fn = jax.shard_map(
        functools.partial(
            _ring_shard, axis_name=seq_axis, rng_axes=rng_axes,
            dropout_rate=dropout_rate
        ),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, P(b_spec, seq_axis), P()),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, kbias, dropout_rng)
