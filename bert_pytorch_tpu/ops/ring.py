"""Ring attention — context parallelism over the mesh's ``seq`` axis.

The reference has no long-context support at all (SURVEY.md §5.7: plain
O(S²) dense attention, seq<=512). This op makes sequence length a sharded
dimension: each device holds an S/n slice of Q, K and V; K/V blocks rotate
around the ring with ``lax.ppermute`` while each device accumulates its
queries' attention with a numerically stable running softmax
(flash-attention-style m/num/den carry). Communication is nearest-neighbor
over ICI and overlaps with the block matmuls, so attention memory and
per-device compute scale as S/n with no S² materialization anywhere.

Usage: ``dot_product_attention(..., backend='ring')`` inside a
``with mesh:`` context whose mesh has a ``seq`` axis > 1 (see
``parallel.MeshConfig(seq=n)`` and the 'sp' strategy rules). Falls back to
the dense XLA path when no sequence sharding is active — the same
fused-or-fallback policy as the Pallas kernels (reference modeling.py's
Apex-or-Python pattern at :327-335).

Attention-probability dropout follows the dense semantics: probabilities
are dropped *after* softmax normalization, which in the streaming form
means the numerator accumulates dropped p while the denominator accumulates
the full p. Each (device, ring-step) block gets an independent rng stream.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from bert_pytorch_tpu.parallel.mesh import AXIS_SEQ


def _ring_shard(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kbias: jnp.ndarray,
    rng: Optional[jnp.ndarray],
    *,
    axis_name: str,
    dropout_rate: float = 0.0,
) -> jnp.ndarray:
    """Per-shard body (runs inside shard_map, manual over ``axis_name``
    ONLY — batch/head dims are global here, their sharding flows through
    the automatic axes).

    q/k/v: [B, S_local, H, D]; kbias: [B, S_local] additive key bias.
    The dropout stream folds in the seq-shard index so every (q shard,
    k block) draws an independent mask; across the automatic batch/head
    shards the partitionable PRNG decorrelates draws by position.
    """
    n = jax.lax.psum(1, axis_name)
    batch, s_q, heads, depth = q.shape
    scale = 1.0 / jnp.sqrt(depth).astype(q.dtype)
    qs = q * scale

    if dropout_rate > 0.0 and rng is not None:
        rng = jax.random.fold_in(rng, jax.lax.axis_index(axis_name))

    def block(k, v, kb, m, num, den, step):
        scores = jnp.einsum("bqhd,bkhd->bhqk", qs, k).astype(jnp.float32)
        scores = scores + kb[:, None, None, :].astype(jnp.float32)
        m_new = jnp.maximum(m, scores.max(axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)  # 0 on the first block (m = -inf)
        if dropout_rate > 0.0 and rng is not None:
            keep = jax.random.bernoulli(
                jax.random.fold_in(rng, step), 1.0 - dropout_rate, p.shape
            )
            p_num = p * keep.astype(p.dtype) / (1.0 - dropout_rate)
        else:
            p_num = p
        blk = jnp.einsum(
            "bhqk,bkhd->bqhd", p_num.astype(v.dtype), v
        ).astype(jnp.float32)
        num = num * corr.transpose(0, 2, 1)[..., None] + blk
        den = den * corr + p.sum(axis=-1)
        return m_new, num, den

    m0 = jnp.full((batch, heads, s_q), -jnp.inf, jnp.float32)
    den0 = jnp.zeros((batch, heads, s_q), jnp.float32)
    num0 = jnp.zeros((batch, s_q, heads, depth), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # Local block first, then n-1 rotate-and-accumulate steps (no wasted
    # final rotation).
    m, num, den = block(k, v, kbias, m0, num0, den0, 0)

    def body(carry, step):
        k, v, kb, m, num, den = carry
        k, v, kb = jax.lax.ppermute((k, v, kb), axis_name, perm)
        m, num, den = block(k, v, kb, m, num, den, step)
        return (k, v, kb, m, num, den), None

    (_, _, _, m, num, den), _ = jax.lax.scan(
        jax.checkpoint(body), (k, v, kbias, m, num, den), jnp.arange(1, n)
    )
    out = num / den.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,
    dropout_rng=None,
    dropout_rate: float = 0.0,
    mesh=None,
    seq_axis: str = AXIS_SEQ,
) -> jnp.ndarray:
    """Sequence-sharded attention over global [B, S, H, D] tensors.

    ``bias`` is the [B, 1, 1, S] (or [B, S]) additive key mask from
    :func:`make_attention_bias`. Requires an ambient (or explicit) mesh with
    ``seq_axis`` size > 1; S must divide by that size.

    Only ``seq_axis`` is manual: batch/head sharding flows through the
    automatic axes. This collective cannot NEST inside another
    partial-manual region over a different axis (the nesting type-checks,
    but Shardy's lowering rejects the composed backward pass) — which is
    why the pipeline composes with 'seq' differently: its shard_map goes
    manual over {pipe, seq} and calls :func:`_ring_shard` directly
    (``dot_product_attention(backend='ring_manual')``), one manual region,
    no nesting. See parallel/pipeline.py ``gpipe(seq_axis=...)``.
    Inside a non-empty mesh context shard_map must infer the context mesh
    (after consistency-checking it against the validation mesh); at top
    level the concrete mesh is passed explicitly.
    """
    from bert_pytorch_tpu.parallel.mesh import current_mesh

    mesh = mesh if mesh is not None else current_mesh()
    if mesh is None or mesh.shape.get(seq_axis, 1) <= 1:
        raise ValueError(
            "ring attention needs an active mesh with a "
            f"'{seq_axis}' axis > 1 (got {None if mesh is None else dict(mesh.shape)})"
        )
    batch, seq, heads, _ = q.shape
    if seq % mesh.shape[seq_axis] != 0:
        raise ValueError(
            f"sequence length {seq} not divisible by mesh "
            f"'{seq_axis}' axis {mesh.shape[seq_axis]}"
        )
    if bias is None:
        kbias = jnp.zeros((batch, seq), jnp.float32)
    else:
        kbias = bias.reshape(batch, seq).astype(jnp.float32)

    ctx = jax.sharding.get_abstract_mesh()
    if not ctx.empty:
        # shard_map must infer the (abstract) context mesh here; the
        # explicit mesh was only used for validation above, so they must
        # agree on the seq axis or the guards above checked the wrong mesh.
        ctx_seq = dict(getattr(ctx, "shape", {})).get(seq_axis, 1)
        if ctx_seq != mesh.shape[seq_axis]:
            raise ValueError(
                f"ring attention: the active mesh context has "
                f"'{seq_axis}'={ctx_seq} but the explicit/ambient mesh has "
                f"{mesh.shape[seq_axis]}; pass a consistent mesh")
        fn = _ring_fn(None, seq_axis, dropout_rate, jitted=False)
    else:
        fn = _ring_fn(mesh, seq_axis, dropout_rate, jitted=True)
    return fn(q, k, v, kbias, dropout_rng)


@functools.lru_cache(maxsize=16)
def _ring_fn(mesh, seq_axis: str, dropout_rate: float, jitted: bool):
    """Cached shard_map wrapper: rebuilding (and re-jitting) it per call
    would recompile the identical computation on every EAGER invocation
    (e.g. each of a 24-layer model.init's attention calls).

    ``jitted=True`` wraps in jax.jit — partial-manual shard_map needs it
    when invoked eagerly outside a trace; inside an outer trace the
    wrapper is inlined. check_vma stays ON: disabling it erases the
    varying-axes types autodiff needs for cotangents under nesting.
    """
    qkv_spec = P(None, seq_axis, None, None)
    fn = jax.shard_map(
        functools.partial(
            _ring_shard, axis_name=seq_axis, dropout_rate=dropout_rate
        ),
        mesh=mesh,
        axis_names=frozenset({seq_axis}),
        in_specs=(qkv_spec, qkv_spec, qkv_spec, P(None, seq_axis), P()),
        out_specs=qkv_spec,
    )
    return jax.jit(fn) if jitted else fn
