"""Optimization library: LAMB / AdamW / BertAdam + warmup schedules + K-FAC.

Replaces the reference's optimizer stack — Apex FusedLAMB/FusedAdam, BertAdam,
and src/schedulers.py — with jit-fused functional equivalents (SURVEY.md §2.3,
§7 stage 2).
"""

from bert_pytorch_tpu.optim.schedules import (
    SCHEDULES,
    make_schedule,
    warmup_constant_schedule,
    warmup_cosine_schedule,
    warmup_exp_decay_exp_schedule,
    warmup_linear_schedule,
    warmup_poly_schedule,
)
from bert_pytorch_tpu.optim.kfac import (
    KFAC,
    KFACState,
    kfac_state_shardings,
)
from bert_pytorch_tpu.optim.transforms import (
    LossScaleState,
    OptState,
    adamw,
    bert_adam,
    dynamic_loss_scale,
    lamb,
    no_decay_mask,
    opt_step_count,
    reset_count,
)

__all__ = [
    "KFAC",
    "KFACState",
    "LossScaleState",
    "dynamic_loss_scale",
    "opt_step_count",
    "kfac_state_shardings",
    "SCHEDULES",
    "make_schedule",
    "warmup_constant_schedule",
    "warmup_cosine_schedule",
    "warmup_exp_decay_exp_schedule",
    "warmup_linear_schedule",
    "warmup_poly_schedule",
    "OptState",
    "adamw",
    "bert_adam",
    "lamb",
    "no_decay_mask",
    "reset_count",
]
