"""Distributed K-FAC preconditioner, TPU-native.

The reference drives the external ``kfac_pytorch`` library with backward-hook
factor capture and NCCL factor communication (reference
run_pretraining.py:30-34, 320-355; SURVEY.md §2.2/§2.3). This is the
JAX/XLA re-design of the same capability:

- **Factor capture without hooks.** The model exposes taps
  (``models/bert.py`` ``kfac_tap`` flag): inputs of each covered dense layer
  are sown as already-reduced second moments x̃ᵀx̃ (bias-augmented) into the
  ``kfac_a`` collection, and each layer output is threaded through a
  ``_g_factor_probe`` custom_vjp whose (d, d) probe variable in
  ``kfac_taps`` receives the already-reduced Σ ĝĝᵀ as its cotangent — the
  outer product is computed INSIDE the backward pass, layer by layer, the
  functional analog of torch's forward/backward hooks. Both statistics are
  batch-shape-independent (d+1, d+1)/(d, d) reductions, so under the
  scanned encoder they stack to (L, d, d) and never materialize per-token
  cotangents. This makes factor harvest cheap enough to run inside the
  training step's own backward (``pretrain.make_train_step`` with
  ``kfac_capture_model=``) — the reference's free hook capture
  (run_pretraining.py:320-355), without a second forward/backward.
- **Stacked factors.** Under the scanned encoder every per-layer factor
  arrives as one (L, d, d) batch, so the eigendecompositions that
  kfac_pytorch schedules layer-by-layer across ranks run here as a single
  batched ``eigh`` — sharded over the mesh's data axes by the leading L axis
  (the HYBRID_OPT distributed-inverse analog; see
  :func:`kfac_state_shardings`).
- **Cadence.** Factors every ``factor_interval`` optimizer steps (EMA with
  ``factor_decay``, reference --kfac_stat_decay), eigendecompositions every
  ``inv_interval`` (--kfac_inv_interval), preconditioning every step.
- **Trust region.** Preconditioned gradients are rescaled by
  ν = min(1, sqrt(kl_clip / Σ ĝ·g·lr²)) — kfac_pytorch's kl_clip
  (--kfac_kl_clip).
- **Math.** For a dense layer y = x W + b with x̃ = [x, 1] and
  W̃ = [[W],[b]] ∈ R^{(d_in+1)×d_out}:  A = E[x̃x̃ᵀ], G = E[ĝĝᵀ] with
  ĝ the batch-size-rescaled output gradient (the per-sample gradient scale
  kfac_pytorch uses for batch-averaged losses). The preconditioned update is
  computed in the eigenbasis: with A = Q_A Λ_A Q_Aᵀ and G = Q_G Λ_G Q_Gᵀ,
  P = Q_A [ (Q_Aᵀ ∇W̃ Q_G) / (λ_A λ_Gᵀ + damping) ] Q_Gᵀ.
  Eigenvectors are stored in ``inv_dtype`` (default bf16 — the analog of
  kfac_pytorch's inv_dtype=torch.float16 memory optimization).
- **Inverse method.** ``inv_method='cholesky'`` (default) computes
  P = (A + √γ I)⁻¹ ∇W̃ (G + √γ I)⁻¹ with Cholesky-factored inverses —
  kfac_pytorch's 'inverse' computation method. On TPU this is the only
  practical choice at BERT-large scale: XLA's iterative ``eigh`` on the
  (24, 4097, 4097) MLP factor stack measures 16.4 s per update on a v5e
  (QR-iteration bound, no MXU) vs 0.4 s for the Cholesky solve (blocked
  triangular solves on the MXU) — 40x. ``inv_method='eigen'`` keeps the
  eigenbasis path (per-mode damping, exact kfac_pytorch 'eigen' parity);
  both store their (d, d) operator in the same state slots (``qa``/``qg``;
  eigenvalues ``la``/``lg`` are ones in cholesky mode), so checkpoints and
  shardings are layout-identical across methods.

Checkpointable: :class:`KFACState` is a flax dataclass pytree, saved as the
``preconditioner`` entry of the training checkpoint (reference
run_pretraining.py:351-352, 519-520).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Tuple

import flax.struct
import jax
import jax.numpy as jnp
from flax import traverse_util
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bert_pytorch_tpu.parallel.mesh import AXIS_DATA, AXIS_FSDP


@flax.struct.dataclass
class KFACState:
    """EMA Kronecker factors + their eigendecompositions.

    ``a``/``qa``/``la`` are keyed by the A-factor tap path (shared by layers
    with a common input, e.g. q/k/v); ``g``/``qg``/``lg`` by the output-tap
    path. Leaves are (d, d) or stacked (L, d, d).
    """

    count: jnp.ndarray  # number of factor updates applied
    a: Dict[str, jnp.ndarray]
    g: Dict[str, jnp.ndarray]
    qa: Dict[str, jnp.ndarray]
    la: Dict[str, jnp.ndarray]
    qg: Dict[str, jnp.ndarray]
    lg: Dict[str, jnp.ndarray]


@dataclasses.dataclass(frozen=True)
class LayerSpec:
    """One preconditioned dense layer, resolved from the tap naming
    convention '<dense submodule>__<A factor name>' (models/bert.py)."""

    g_key: str  # flat '/'-joined path of the output tap
    a_key: str  # flat path of the shared input-stat tap
    kernel_path: Tuple[str, ...]
    bias_path: Tuple[str, ...]
    a_dim: int  # d_in + 1
    g_dim: int
    stacked: bool  # True for scanned-encoder (L, ...) layers


def _flat_key(path: Tuple[str, ...]) -> str:
    return "/".join(path)


def _retain_shardings(new_state, like_state):
    """Pin ``new_state``'s leaves to ``like_state``'s shardings.

    The factor/inverse updates are jitted WITHOUT out_shardings, so GSPMD
    propagation chooses their output layouts — which can drift from the
    :func:`kfac_state_shardings` layout the (separately jitted) train step
    declares for its kfac_state argument: a hard in_shardings mismatch
    error under meshes with extra axes (e.g. K-FAC x pipeline). The
    device_put back to the input's sharding is a no-op when layouts agree.
    """

    def put(n, like):
        s = getattr(like, "sharding", None)
        if s is None or not hasattr(n, "sharding") or n.sharding == s:
            return n
        return jax.device_put(n, s)

    return jax.tree_util.tree_map(put, new_state, like_state)


def _unwrap_sown(leaf):
    """sow() stores values as a tuple per call-site; taps fire once."""
    if isinstance(leaf, tuple):
        (leaf,) = leaf
    return leaf


def build_layer_specs(tap_shapes, astat_shapes, params_shapes) -> Tuple[LayerSpec, ...]:
    """Resolve taps against the param tree (no model knowledge needed beyond
    the '<dense>__<afactor>' perturb naming convention)."""
    flat_params = traverse_util.flatten_dict(params_shapes)
    flat_astats = {
        path: _unwrap_sown(leaf)
        for path, leaf in traverse_util.flatten_dict(
            astat_shapes, is_leaf=lambda _, v: isinstance(v, tuple)
        ).items()
    }
    specs = []
    for path, leaf in sorted(traverse_util.flatten_dict(tap_shapes).items()):
        name = path[-1]
        dense, a_name = name.split("__")
        parent = path[:-1]
        a_path = parent + (a_name + "_a",)
        a_shape = flat_astats[a_path].shape
        stacked = len(a_shape) == 3
        a_dim = a_shape[-1]
        kernel_path = parent + (dense, "kernel")
        bias_path = parent + (dense, "bias")
        kernel_shape = flat_params[kernel_path].shape
        numel = 1
        for s in kernel_shape[1 if stacked else 0:]:
            numel *= s
        g_dim = numel // (a_dim - 1)
        specs.append(
            LayerSpec(
                g_key=_flat_key(path),
                a_key=_flat_key(a_path),
                kernel_path=kernel_path,
                bias_path=bias_path,
                a_dim=a_dim,
                g_dim=g_dim,
                stacked=stacked,
            )
        )
    return tuple(specs)


class KFAC:
    """K-FAC preconditioner bound to a tapped model's loss.

    Parameters
    ----------
    apply_loss:
        ``(params, taps, batch, rng) -> (loss, a_stats)`` — runs the tapped
        model forward with the zero output-taps inserted and the ``kfac_a``
        collection mutable (see :func:`bert_pytorch_tpu.pretrain.make_kfac_fns`).
    tap_shape_fn:
        ``(params, batch, rng) -> (tap_shapes, astat_shapes)`` via
        ``jax.eval_shape`` (trace-only, no FLOPs).
    grad_scale:
        ``batch -> scalar`` rescaling raw output gradients to per-sample
        scale; defaults to the batch size of ``input_ids`` (batch-averaged
        loss convention).
    skip_layers:
        substrings matched against tap paths; matching layers are excluded
        from preconditioning (reference --kfac_skip_layers; the default skip
        set — predictions head + embeddings — is never tapped to begin
        with, models/bert.py).
    """

    def __init__(
        self,
        apply_loss: Callable,
        tap_shape_fn: Callable,
        *,
        factor_decay: float = 0.95,
        damping: float = 0.003,
        kl_clip: float = 0.001,
        inv_dtype=jnp.bfloat16,
        inv_method: str = "cholesky",
        grad_scale: Callable[[dict], Any] | None = None,
        skip_layers: Tuple[str, ...] = (),
    ):
        if inv_method not in ("cholesky", "eigen"):
            raise ValueError(
                f"inv_method must be cholesky|eigen, got {inv_method!r}")
        self.apply_loss = apply_loss
        self.tap_shape_fn = tap_shape_fn
        self.factor_decay = factor_decay
        self.damping = damping
        self.kl_clip = kl_clip
        self.inv_dtype = inv_dtype
        self.inv_method = inv_method
        self.grad_scale = grad_scale or (
            lambda batch: batch["input_ids"].shape[0]
        )
        self.skip_layers = tuple(skip_layers)
        self.specs: Tuple[LayerSpec, ...] = ()
        self._abstract_params = None
        self._update_cache: dict = {}
        self._inv_jit = None

    # ------------------------------------------------------------------ init

    def init(self, params, sample_batch, rng=None) -> KFACState:
        """Discover taps (shape-only model trace) and build zeroed state."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._abstract_params = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params
        )
        abstract_batch = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), dict(sample_batch)
        )
        tap_shapes, astat_shapes = self.tap_shape_fn(
            self._abstract_params, abstract_batch, rng
        )
        self.specs = build_layer_specs(
            tap_shapes, astat_shapes, self._abstract_params
        )
        if self.skip_layers:
            self.specs = tuple(
                s for s in self.specs
                if not any(skip in s.g_key for skip in self.skip_layers)
            )
        if not self.specs:
            raise ValueError(
                "no K-FAC taps found — was the model built with kfac_tap=True "
                "(and did skip_layers exclude everything)?"
            )
        # Probe statistics are batch-shape independent ((L, d, d) factor
        # reductions), so one zero-taps tree serves every batch shape —
        # the fused in-train capture path reads it via zero_taps(); the
        # all-microbatch capture additionally needs a zero A-stat tree to
        # seed its scan accumulator (zero_astats()).
        self._tap_shapes = tap_shapes
        self._astat_shapes = astat_shapes

        flat_astats = {
            _flat_key(p): _unwrap_sown(v)
            for p, v in traverse_util.flatten_dict(
                astat_shapes, is_leaf=lambda _, v: isinstance(v, tuple)
            ).items()
        }
        a, g, qa, la, qg, lg = {}, {}, {}, {}, {}, {}
        for spec in self.specs:
            lead = ()
            if spec.stacked:
                lead = (flat_astats[spec.a_key].shape[0],)
            if spec.a_key not in a:
                a[spec.a_key] = jnp.zeros(
                    lead + (spec.a_dim, spec.a_dim), jnp.float32
                )
                qa[spec.a_key] = jnp.broadcast_to(
                    jnp.eye(spec.a_dim, dtype=self.inv_dtype),
                    lead + (spec.a_dim, spec.a_dim),
                )
                la[spec.a_key] = jnp.ones(lead + (spec.a_dim,), jnp.float32)
            g[spec.g_key] = jnp.zeros(lead + (spec.g_dim, spec.g_dim), jnp.float32)
            qg[spec.g_key] = jnp.broadcast_to(
                jnp.eye(spec.g_dim, dtype=self.inv_dtype),
                lead + (spec.g_dim, spec.g_dim),
            )
            lg[spec.g_key] = jnp.ones(lead + (spec.g_dim,), jnp.float32)
        return KFACState(
            count=jnp.zeros((), jnp.int32), a=a, g=g, qa=qa, la=la, qg=qg, lg=lg
        )

    # --------------------------------------------------------------- factors

    def update_factors(self, state: KFACState, params, batch, rng) -> KFACState:
        """One tapped forward/backward on ``batch``; EMA the factors.

        jit-cached per batch shape (the runner feeds one microbatch).
        """
        key = tuple(sorted((k, v.shape, str(v.dtype)) for k, v in batch.items()))
        if key not in self._update_cache:
            abstract_batch = {
                k: jax.ShapeDtypeStruct(v.shape, v.dtype) for k, v in batch.items()
            }
            tap_shapes, _ = self.tap_shape_fn(
                self._abstract_params, abstract_batch, jax.random.PRNGKey(0)
            )
            self._update_cache[key] = jax.jit(
                self._build_update_impl(tap_shapes)
            )
        return _retain_shardings(
            self._update_cache[key](state, params, batch, rng), state)

    def _build_update_impl(self, tap_shapes):
        def impl(state, params, batch, rng):
            zeros = jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype), tap_shapes
            )

            def loss_of_taps(taps):
                return self.apply_loss(params, taps, batch, rng)

            (_, astats), gtaps = jax.value_and_grad(
                loss_of_taps, has_aux=True
            )(zeros)
            rows = batch["input_ids"].shape[0] * batch["input_ids"].shape[1]
            return self.ema_factors(
                state, astats, gtaps, rows, self.grad_scale(batch))

        return impl

    def zero_taps(self):
        """Zero probe tree for grad-w.r.t.-taps capture (batch-shape
        independent — see init)."""
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), self._tap_shapes)

    def zero_astats(self):
        """Zero A-stat tree (the sown ``kfac_a`` collection's structure,
        also batch-shape independent) — the scan accumulator seed for
        all-microbatch fused capture."""
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), self._astat_shapes)

    def ema_factors(self, state: KFACState, astats, gtaps, rows, scale
                    ) -> KFACState:
        """Pure traced factor EMA from already-reduced statistics.

        ``astats``: the mutated ``kfac_a`` collection (sown Σ x̃x̃ᵀ);
        ``gtaps``: grad-w.r.t.-taps tree — each leaf is the per-layer
        Σ ĝĝᵀ delivered by the ``_g_factor_probe`` backward.
        ``rows``: token rows the sums ran over. Every tapped layer sits in
        the encoder interior and consumes [B, S, ...] activations, so one
        row count (B*S) covers all specs; a future tap outside the encoder
        (e.g. the pooler, rows=B) would need per-spec rows.
        ``scale``: per-sample gradient rescale (grad_scale; batch size for
        batch-averaged losses).

        Callable from inside a jitted train step (the fused capture path,
        pretrain.make_train_step) or from the standalone stats pass
        (:meth:`update_factors`).
        """
        flat_a = {
            _flat_key(p): _unwrap_sown(v)
            for p, v in traverse_util.flatten_dict(
                astats, is_leaf=lambda _, v: isinstance(v, tuple)
            ).items()
        }
        flat_g = {
            _flat_key(p): v
            for p, v in traverse_util.flatten_dict(gtaps).items()
        }
        scale = jnp.asarray(scale, jnp.float32)

        decay = self.factor_decay
        first = state.count == 0

        def ema(old, new):
            return jnp.where(first, new, decay * old + (1.0 - decay) * new)

        new_a = dict(state.a)
        new_g = dict(state.g)
        for spec in self.specs:
            # The probe backward returns Σᵣ ĝᵣĝᵣᵀ of the RAW cotangent;
            # rescale to per-sample gradients (x scale on each ĝ, i.e.
            # scale² on the outer product) and average over rows.
            g_fac = (flat_g[spec.g_key].astype(jnp.float32)
                     * (scale * scale) / rows)
            new_g[spec.g_key] = ema(state.g[spec.g_key], g_fac)
            if spec.a_key in flat_a:  # compute each shared A once
                a_fac = flat_a.pop(spec.a_key) / rows
                new_a[spec.a_key] = ema(state.a[spec.a_key], a_fac)
        return state.replace(count=state.count + 1, a=new_a, g=new_g)

    # -------------------------------------------------------------- inverses

    def inverse_factors(self, state: KFACState) -> KFACState:
        """Pure traced inverse update — callable from inside a jitted
        train step (the fused-capture path runs it under a ``lax.cond``
        so inverse-due steps precondition with the factors THIS step
        captured, the reference's within-``optimizer.step()`` ordering)
        or via the standalone :meth:`update_inverses` wrapper."""

        def eig_one(fac):
            w, v = jnp.linalg.eigh(fac)
            return v.astype(self.inv_dtype), jnp.maximum(w, 0.0)

        def cho_one(fac):
            # (F + sqrt(damping) I)^-1 via Cholesky — 40x faster
            # than eigh on TPU for BERT-large factors (module
            # docstring); per-mode damping is traded for the
            # factor-wise Tikhonov term.
            d = fac.shape[-1]
            damped = fac + jnp.sqrt(self.damping) * jnp.eye(
                d, dtype=fac.dtype)
            c = jax.scipy.linalg.cho_factor(damped)
            inv = jax.scipy.linalg.cho_solve(
                c, jnp.eye(d, dtype=fac.dtype))
            return inv.astype(self.inv_dtype), jnp.ones(
                (d,), jnp.float32)

        one = eig_one if self.inv_method == "eigen" else cho_one

        def factor_op(fac):
            # lax.map over the stacked-layer axis instead of one
            # batched op: identical results, but the fp32 workspace
            # exists for ONE (d, d) factor at a time — for
            # BERT-large's (24, 4097, 4097) MLP factor that's the
            # difference between a multi-GB transient and ~130MB
            # (the inverse step runs every inv_interval steps, so
            # the serialization is off the hot path).
            if fac.ndim == 3:
                return jax.lax.map(one, fac)
            return one(fac)

        qa, la, qg, lg = {}, {}, {}, {}
        for k, fac in state.a.items():
            qa[k], la[k] = factor_op(fac)
        for k, fac in state.g.items():
            qg[k], lg[k] = factor_op(fac)
        return state.replace(qa=qa, la=la, qg=qg, lg=lg)

    def update_inverses(self, state: KFACState) -> KFACState:
        """Batched inverse update of all factors (the inverse-update of
        kfac_pytorch, distributed by the stacked-layer sharding instead of
        per-layer rank assignment). Host-callable jitted wrapper around
        :meth:`inverse_factors`."""
        if self._inv_jit is None:
            self._inv_jit = jax.jit(self.inverse_factors)
        return _retain_shardings(self._inv_jit(state), state)

    # --------------------------------------------------------- precondition

    def precondition(self, state: KFACState, grads, lr):
        """grads -> preconditioned grads with kl_clip trust scaling.

        Pure traced function — called inline from the jitted train step.
        Non-tapped parameters pass through unchanged (reference behavior for
        unregistered modules).
        """
        flat = traverse_util.flatten_dict(grads)
        lr = jnp.asarray(lr, jnp.float32)
        vg_sum = jnp.zeros((), jnp.float32)
        pre = {}
        for spec in self.specs:
            kg = flat[spec.kernel_path].astype(jnp.float32)
            bg = flat[spec.bias_path].astype(jnp.float32)
            lead = kg.shape[:1] if spec.stacked else ()
            k2 = kg.reshape(lead + (spec.a_dim - 1, spec.g_dim))
            b2 = bg.reshape(lead + (1, spec.g_dim))
            w = jnp.concatenate([k2, b2], axis=-2)  # (..., d_a, d_g)
            qa = state.qa[spec.a_key].astype(jnp.float32)
            qg = state.qg[spec.g_key].astype(jnp.float32)
            if self.inv_method == "cholesky":
                # qa/qg hold the damped factor inverses: P = A⁻¹ W G⁻¹.
                p = jnp.einsum("...ab,...bg->...ag", qa, w)
                p = jnp.einsum("...ag,...gh->...ah", p, qg)
            else:
                la = state.la[spec.a_key]
                lg = state.lg[spec.g_key]
                v = jnp.einsum("...ab,...ag->...bg", qa, w)
                v = jnp.einsum("...bg,...gh->...bh", v, qg)
                v = v / (la[..., :, None] * lg[..., None, :] + self.damping)
                p = jnp.einsum("...ab,...bh->...ah", qa, v)
                p = jnp.einsum("...ah,...gh->...ag", p, qg)
            vg_sum = vg_sum + jnp.sum(p * w) * lr * lr
            pre[spec] = p

        nu = jnp.minimum(
            1.0, jnp.sqrt(self.kl_clip / jnp.maximum(vg_sum, 1e-30))
        )
        for spec in self.specs:
            p = pre[spec] * nu
            kg = flat[spec.kernel_path]
            bg = flat[spec.bias_path]
            flat[spec.kernel_path] = p[..., :-1, :].reshape(kg.shape).astype(kg.dtype)
            flat[spec.bias_path] = p[..., -1, :].reshape(bg.shape).astype(bg.dtype)
        return traverse_util.unflatten_dict(flat)


def kfac_state_shardings(mesh: Mesh, state: KFACState) -> KFACState:
    """Shard stacked (L, d, d) factor batches over the data axes when L
    divides evenly — each data shard then eigendecomposes its slice of
    layers (the distributed-inverse placement of kfac_pytorch's
    HYBRID_OPT, expressed as a sharding instead of rank bookkeeping)."""
    shards = mesh.shape.get(AXIS_DATA, 1) * mesh.shape.get(AXIS_FSDP, 1)

    def rule(x):
        if x.ndim >= 3 and shards > 1 and x.shape[0] % shards == 0:
            return NamedSharding(mesh, P((AXIS_DATA, AXIS_FSDP)))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, state)
