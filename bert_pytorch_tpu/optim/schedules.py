"""Learning-rate schedules — parity with reference src/schedulers.py and the
schedule functions in src/optimization.py:36-62.

Design difference from the reference: there is no stateful Scheduler object.
The reference's schedulers read the optimizer's internal step count on every
``step()`` so that resume Just Works (schedulers.py:126-131). Here the step
count lives in the optimizer state and the schedule is a pure function
``step -> lr`` evaluated inside the jitted update, so the same resume
property holds by construction.

Offset semantics: the reference sets ``last_epoch = optimizer_step + 1``
before computing the lr (schedulers.py:97-105,126-134), i.e. the lr used at
0-indexed optimizer step t is schedule((t+1)/total). These factories
reproduce that with ``offset=1``.
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

Schedule = Callable[[jnp.ndarray], jnp.ndarray]


def _warmup(progress, warmup):
    return progress / jnp.maximum(warmup, 1e-12)


def warmup_poly_schedule(
    base_lr: float,
    warmup: float,
    total_steps: int,
    degree: float = 0.5,
    offset: int = 1,
) -> Schedule:
    """Warmup then (1-progress)^degree decay (PolyWarmUpScheduler,
    schedulers.py:115-141; degree 0.5 is the BERT recipe)."""

    def schedule(count):
        progress = (count + offset) / total_steps
        decay = jnp.maximum(1.0 - progress, 0.0) ** degree
        return base_lr * jnp.where(
            progress < warmup, _warmup(progress, warmup), decay
        )

    return schedule


def warmup_linear_schedule(
    base_lr: float, warmup: float, total_steps: int, offset: int = 1
) -> Schedule:
    """Warmup then linear decay to 0 at progress=1
    (LinearWarmUpScheduler, schedulers.py:87-112)."""

    def schedule(count):
        progress = (count + offset) / total_steps
        decay = jnp.maximum((progress - 1.0) / (warmup - 1.0), 0.0)
        return base_lr * jnp.where(
            progress < warmup, _warmup(progress, warmup), decay
        )

    return schedule


def warmup_cosine_schedule(
    base_lr: float, warmup: float, total_steps: int, offset: int = 1
) -> Schedule:
    """Warmup then 0.5*(1+cos(pi + progress)) decay — reproducing the
    reference's formula verbatim (schedulers.py:66; note the reference adds
    pi to progress rather than multiplying, we keep its behavior)."""

    def schedule(count):
        progress = (count + offset) / total_steps
        decay = 0.5 * (1.0 + jnp.cos(jnp.pi + progress))
        return base_lr * jnp.where(
            progress < warmup, _warmup(progress, warmup), decay
        )

    return schedule


def warmup_constant_schedule(
    base_lr: float, warmup: float, total_steps: int, offset: int = 1
) -> Schedule:
    """Warmup then constant (ConstantWarmUpScheduler, schedulers.py:69-84)."""

    def schedule(count):
        progress = (count + offset) / total_steps
        return base_lr * jnp.where(progress < warmup, _warmup(progress, warmup), 1.0)

    return schedule


def warmup_exp_decay_exp_schedule(
    base_lr: float,
    decay_rate: float,
    decay_steps: int,
    total_steps: int,
    warmup: float = 0.002,
    degree: float = 2.0,
) -> Schedule:
    """Polynomial warmup then exponential decay
    (``warmup_exp_decay_exp``, schedulers.py:144-158). No +1 offset: the
    reference calls this one with the raw global step."""

    def schedule(count):
        x = count / total_steps
        warmup_end = warmup * total_steps
        warm = _warmup(x, warmup) ** degree
        decay = decay_rate ** ((count - warmup_end) / decay_steps)
        if warmup == 0.0:
            return jnp.full_like(jnp.asarray(x, jnp.float32), base_lr)
        return base_lr * jnp.where(x < warmup, warm, decay)

    return schedule


SCHEDULES = {
    "poly": warmup_poly_schedule,
    "linear": warmup_linear_schedule,
    "cosine": warmup_cosine_schedule,
    "constant": warmup_constant_schedule,
}


def make_schedule(
    name: str, base_lr: float, warmup: float, total_steps: int, **kwargs
) -> Schedule:
    """Factory keyed the way ``--lr_decay`` is (run_pretraining.py:288-293)."""
    if name not in SCHEDULES:
        raise ValueError(f"Unknown lr decay '{name}'; options: {sorted(SCHEDULES)}")
    return SCHEDULES[name](base_lr, warmup, total_steps, **kwargs)
