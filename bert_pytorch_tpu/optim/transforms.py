"""Optimizers as optax-style gradient transformations.

TPU-native replacements for the reference's native optimizer stack
(SURVEY.md §2.3): Apex ``FusedLAMB``/``FusedAdam`` (run_pretraining.py:295,
src/optimization.py:25) and the pure-torch ``BertAdam``
(src/optimization.py:64-174). On TPU "fused" is what XLA does to any jitted
elementwise update chain — the multi-tensor-apply machinery has no analog to
build; what matters is matching the update *math* and keeping the state
checkpointable (a flat (count, mu, nu) pytree).

All three optimizers share the same state layout so checkpoints can swap
between them across phases (the reference's phase-2 surgery overwrites step
counts in place, run_pretraining.py:298-309 — see ``reset_count``).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

from bert_pytorch_tpu.ops.grad_utils import global_norm

ScalarOrSchedule = Union[float, Callable]


class OptState(NamedTuple):
    count: jnp.ndarray  # int32 optimizer-step counter (drives the schedule)
    mu: optax.Params  # first moment
    nu: optax.Params  # second moment


class LossScaleState(NamedTuple):
    """fp16 dynamic-loss-scaling wrapper state (reference GradScaler
    analog, run_pretraining.py:314-318; checkpointed like its 'scaler'
    entry at :519-523 — the whole tuple rides inside the checkpoint's
    'optimizer' tree)."""

    scale: jnp.ndarray         # f32 current loss scale
    growth_count: jnp.ndarray  # i32 consecutive finite steps since growth
    inner: OptState


def _lr_at(learning_rate: ScalarOrSchedule, count):
    return learning_rate(count) if callable(learning_rate) else learning_rate


def _update_moments(grads, state, b1, b2):
    mu = jax.tree_util.tree_map(
        lambda m, g: b1 * m + (1.0 - b1) * g.astype(m.dtype), state.mu, grads
    )
    nu = jax.tree_util.tree_map(
        lambda v, g: b2 * v + (1.0 - b2) * jnp.square(g.astype(v.dtype)),
        state.nu,
        grads,
    )
    return mu, nu


def _init_moments(params):
    zeros = lambda p: jnp.zeros_like(p, dtype=jnp.float32)
    return (
        jax.tree_util.tree_map(zeros, params),
        jax.tree_util.tree_map(zeros, params),
    )


def _mask_tree(params, mask):
    if mask is None:
        return jax.tree_util.tree_map(lambda _: True, params)
    return mask(params) if callable(mask) else mask


def lamb(
    learning_rate: ScalarOrSchedule,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    weight_decay_mask=None,
    max_grad_norm: Optional[float] = 1.0,
    bias_correction: bool = True,
    trust_clip: Optional[float] = None,
) -> optax.GradientTransformation:
    """LAMB — the large-batch optimizer of the BERT recipe.

    Semantics of Apex ``FusedLAMB`` (driven at run_pretraining.py:295 with the
    no-decay grouping of :279-286): global-norm gradient clipping to
    ``max_grad_norm``, bias-corrected Adam moments, update
    ``m̂/(√v̂+eps) + wd·p``, and a per-parameter trust ratio
    ``‖p‖/‖update‖`` scaling the learning rate (1.0 where either norm is 0).
    ``weight_decay_mask`` plays the role of the reference's two param groups.
    """

    def init(params):
        mu, nu = _init_moments(params)
        return OptState(jnp.zeros((), jnp.int32), mu, nu)

    def update(grads, state, params):
        if params is None:
            raise ValueError("lamb requires params")
        if max_grad_norm is not None and max_grad_norm > 0:
            gnorm = global_norm(grads)
            gscale = jnp.minimum(1.0, max_grad_norm / (gnorm + 1e-6))
            grads = jax.tree_util.tree_map(lambda g: g * gscale, grads)

        mu, nu = _update_moments(grads, state, b1, b2)
        count = state.count + 1
        if bias_correction:
            c1 = 1.0 - b1 ** count.astype(jnp.float32)
            c2 = 1.0 - b2 ** count.astype(jnp.float32)
        else:
            c1 = c2 = 1.0

        decay_mask = _mask_tree(params, weight_decay_mask)
        lr = _lr_at(learning_rate, state.count)

        def param_update(m, v, p, use_decay):
            m_hat = m / c1
            v_hat = v / c2
            upd = m_hat / (jnp.sqrt(v_hat) + eps)
            if weight_decay > 0:
                upd = upd + weight_decay * jnp.where(use_decay, 1.0, 0.0) * p.astype(
                    jnp.float32
                )
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p.astype(jnp.float32))))
            u_norm = jnp.sqrt(jnp.sum(jnp.square(upd)))
            ratio = jnp.where(
                (p_norm > 0) & (u_norm > 0), p_norm / u_norm, 1.0
            )
            if trust_clip is not None:
                ratio = jnp.minimum(ratio, trust_clip)
            return (-lr * ratio * upd).astype(p.dtype)

        updates = jax.tree_util.tree_map(param_update, mu, nu, params, decay_mask)
        return updates, OptState(count, mu, nu)

    return optax.GradientTransformation(init, update)


def adamw(
    learning_rate: ScalarOrSchedule,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    weight_decay_mask=None,
    bias_correction: bool = True,
) -> optax.GradientTransformation:
    """Adam with decoupled weight decay — the Apex ``FusedAdam`` role in
    finetuning (run_squad.py:982-988, run_ner.py:243 use
    bias_correction=False; the default here is True)."""

    def init(params):
        mu, nu = _init_moments(params)
        return OptState(jnp.zeros((), jnp.int32), mu, nu)

    def update(grads, state, params):
        mu, nu = _update_moments(grads, state, b1, b2)
        count = state.count + 1
        if bias_correction:
            c1 = 1.0 - b1 ** count.astype(jnp.float32)
            c2 = 1.0 - b2 ** count.astype(jnp.float32)
        else:
            c1 = c2 = 1.0
        decay_mask = _mask_tree(params, weight_decay_mask)
        lr = _lr_at(learning_rate, state.count)

        def param_update(m, v, p, use_decay):
            upd = (m / c1) / (jnp.sqrt(v / c2) + eps)
            if weight_decay > 0:
                upd = upd + weight_decay * jnp.where(use_decay, 1.0, 0.0) * p.astype(
                    jnp.float32
                )
            return (-lr * upd).astype(p.dtype)

        updates = jax.tree_util.tree_map(param_update, mu, nu, params, decay_mask)
        return updates, OptState(count, mu, nu)

    return optax.GradientTransformation(init, update)


def bert_adam(
    learning_rate: float,
    schedule: str = "warmup_linear",
    warmup: float = -1.0,
    t_total: int = -1,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    weight_decay_mask=None,
    max_grad_norm: float = 1.0,
) -> optax.GradientTransformation:
    """``BertAdam`` — Adam with the BERT weight-decay fix, schedule computed
    *inside* the optimizer, no bias correction, per-parameter grad clipping.

    Parity with src/optimization.py:64-174: lr at step t is
    ``base * schedule_fct(t/t_total, warmup)`` evaluated with the pre-update
    step count (optimization.py:163-170), clipping is per-parameter
    ``clip_grad_norm_(p, max_grad_norm)`` (optimization.py:144-145), and the
    decayed update is ``m/(√v+eps) + wd·p`` with no bias correction.
    Used by the fp32 SQuAD path (run_squad.py:999-1002).
    """
    from bert_pytorch_tpu.optim.schedules import (
        warmup_constant_schedule,
        warmup_cosine_schedule,
        warmup_linear_schedule,
        warmup_poly_schedule,
    )

    factories = {
        "warmup_linear": warmup_linear_schedule,
        "warmup_cosine": warmup_cosine_schedule,
        "warmup_constant": warmup_constant_schedule,
        "warmup_poly": warmup_poly_schedule,
    }
    if schedule not in factories:
        raise ValueError(f"Invalid schedule parameter: {schedule}")
    if t_total != -1:
        # offset=0: BertAdam reads state['step'] before incrementing it.
        sched = factories[schedule](learning_rate, warmup, t_total, offset=0)
    else:
        sched = lambda count: jnp.asarray(learning_rate, jnp.float32)

    def init(params):
        mu, nu = _init_moments(params)
        return OptState(jnp.zeros((), jnp.int32), mu, nu)

    def update(grads, state, params):
        # Per-parameter clipping (optimization.py:144-145).
        if max_grad_norm > 0:

            def clip(g):
                n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
                return g * jnp.minimum(1.0, max_grad_norm / (n + 1e-6)).astype(
                    g.dtype
                )

            grads = jax.tree_util.tree_map(clip, grads)
        mu, nu = _update_moments(grads, state, b1, b2)
        decay_mask = _mask_tree(params, weight_decay_mask)
        lr = sched(state.count)

        def param_update(m, v, p, use_decay):
            upd = m / (jnp.sqrt(v) + eps)
            if weight_decay > 0:
                upd = upd + weight_decay * jnp.where(use_decay, 1.0, 0.0) * p.astype(
                    jnp.float32
                )
            return (-lr * upd).astype(p.dtype)

        updates = jax.tree_util.tree_map(param_update, mu, nu, params, decay_mask)
        return updates, OptState(state.count + 1, mu, nu)

    return optax.GradientTransformation(init, update)


def no_decay_mask(params) -> optax.Params:
    """True where weight decay applies. The analog of the reference's no-decay
    param grouping (run_pretraining.py:279-286: names containing bias/gamma/
    beta/LayerNorm are excluded) — here: any 'bias' leaf and every LayerNorm
    parameter ('scale' lives only in LayerNorm modules)."""
    import flax.traverse_util as traverse_util

    flat = traverse_util.flatten_dict(params)
    mask = {
        path: not (
            path[-1] == "bias"
            or path[-1] == "scale"
            or any("layer_norm" in part for part in path)
        )
        for path in flat
    }
    return traverse_util.unflatten_dict(mask)


def reset_count(state, count: int):
    """Phase-switch surgery: overwrite the optimizer step counter, keeping
    moments — the analog of rewriting 'step'/'t_total'/'warmup'/'lr' in the
    loaded checkpoint (run_pretraining.py:298-309). t_total/warmup/lr live in
    the schedule closure here and are rebuilt from the new phase config.
    A loss-scaled (fp16) state keeps its scale across the phase switch,
    exactly like the reference's GradScaler surviving the surgery."""
    if isinstance(state, LossScaleState):
        return state._replace(inner=reset_count(state.inner, count))
    return OptState(jnp.asarray(count, jnp.int32), state.mu, state.nu)


def opt_step_count(state):
    """The optimizer-step counter, whether or not the state is wrapped in
    a :class:`LossScaleState` (fp16 mode)."""
    if isinstance(state, LossScaleState):
        return state.inner.count
    return state.count


def dynamic_loss_scale(
    tx: optax.GradientTransformation,
    init_scale: float = 2.0 ** 16,
    growth_factor: float = 2.0,
    backoff_factor: float = 0.5,
    growth_interval: int = 2000,
) -> optax.GradientTransformation:
    """Wrap ``tx`` with torch.cuda.amp.GradScaler semantics for fp16.

    The caller multiplies the LOSS by the current scale (read it off the
    state with ``state.scale``) before differentiating; this transform
    receives the scaled gradients, unscales them, and:

    - finite grads: applies the inner update; after ``growth_interval``
      consecutive finite steps the scale doubles;
    - any inf/nan: the step is SKIPPED (zero updates, inner state kept,
      its count not incremented) and the scale is halved.

    bf16 needs none of this (same exponent range as f32) — the wrapper
    exists as the reference-parity fp16 mode (SURVEY.md §2.3 "keep
    optional fp16+scaler for parity testing"; reference
    run_pretraining.py:314-318, 424-434). Defaults match
    ``torch.cuda.amp.GradScaler()``: init 2**16, growth 2x / backoff 0.5x,
    growth interval 2000.
    """

    def init(params):
        return LossScaleState(
            scale=jnp.asarray(init_scale, jnp.float32),
            growth_count=jnp.asarray(0, jnp.int32),
            inner=tx.init(params),
        )

    def update(grads, state, params=None):
        inv = (1.0 / state.scale).astype(jnp.float32)
        grads = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads)
        finite = jnp.asarray(True)
        for g in jax.tree_util.tree_leaves(grads):
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
        updates, inner_new = tx.update(grads, state.inner, params)
        updates = jax.tree_util.tree_map(
            lambda u: jnp.where(finite, u, jnp.zeros_like(u)), updates)
        inner = jax.tree_util.tree_map(
            lambda n, o: jnp.where(finite, n, o), inner_new, state.inner)
        growth_count = jnp.where(finite, state.growth_count + 1, 0)
        grew = growth_count >= growth_interval
        scale = jnp.where(
            finite,
            jnp.where(grew, state.scale * growth_factor, state.scale),
            state.scale * backoff_factor,
        )
        growth_count = jnp.where(grew, 0, growth_count)
        return updates, LossScaleState(scale, growth_count, inner)

    return optax.GradientTransformation(init, update)
