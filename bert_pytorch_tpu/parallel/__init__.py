"""Parallelism layer: device mesh, sharding rules, collectives, launcher.

The TPU-native replacement for the reference's NCCL/DDP/torchrun stack
(SURVEY.md §2.2/§5.8): instead of wrapping the model in DDP and letting NCCL
allreduce gradients (run_pretraining.py:185,270), we lay the pod out as a
`jax.sharding.Mesh` with axes ``('data', 'fsdp', 'seq', 'model')``, annotate
parameters/activations with logical axis names, and let XLA insert the
collectives (psum / all-gather / reduce-scatter) over ICI.

Strategies (rule sets):
  - ``dp``    — pure data parallelism: params replicated, batch sharded.
                The reference's only strategy (DDP), here with zero
                allreduce code — XLA emits the gradient psum.
  - ``fsdp``  — params sharded over the fsdp axis (ZeRO-3 analog); XLA
                all-gathers weights per layer and reduce-scatters grads.
  - ``tp``    — Megatron-style tensor parallelism over the model axis
                (heads/mlp/vocab sharded).
  - ``sp``    — sequence/context parallelism over the seq axis for
                long-context (ring attention lives in ops/pallas).
  - ``pp``    — pipeline parallelism over the pipe axis: the encoder's
                stacked layers shard into contiguous stage blocks and
                microbatches rotate through them on a GPipe schedule
                (parallel/pipeline.py).
These compose: a mesh may use several axes at once. The composition is
first-class via ``MeshSpec`` (``--mesh dp=4,fsdp=2,pipe=2`` style): the
legacy names above are aliases that lower onto specs, and any axis
product's rules derive from one template (docs/parallelism.md).
"""

from bert_pytorch_tpu.parallel.mesh import (
    MeshConfig,
    MeshSpec,
    MeshSpecError,
    create_mesh,
    current_mesh,
    derive_rules,
    logical_axis_rules,
    parse_mesh_spec,
)
from bert_pytorch_tpu.parallel.pipeline import gpipe, stage_layer_count
from bert_pytorch_tpu.parallel.sharding import (
    batch_sharding,
    mesh_sharding,
    params_shardings,
    shard_params,
)

__all__ = [
    "MeshConfig",
    "MeshSpec",
    "MeshSpecError",
    "create_mesh",
    "current_mesh",
    "derive_rules",
    "logical_axis_rules",
    "parse_mesh_spec",
    "gpipe",
    "stage_layer_count",
    "batch_sharding",
    "mesh_sharding",
    "params_shardings",
    "shard_params",
]
