"""Multi-host launcher — the TPU-native replacement for torchrun + the
SLURM/Cobalt ssh fan-out scripts (reference scripts/run_pretraining.sbatch:49-94,
run_pretraining.cobalt:46-91).

On a TPU pod there is one process per host; `jax.distributed.initialize`
performs the rendezvous (the c10d analog of sbatch:64-70), after which
`jax.devices()` spans the whole pod and a single SPMD program runs everywhere.
Coordinator discovery mirrors the reference's node-file inference: explicit
flags > environment (SLURM/COBALT nodefiles) > single-host default.
"""

from __future__ import annotations

import os
import subprocess
from typing import Optional

import jax

_INITIALIZED = False


def infer_coordinator(port: int = 9731) -> Optional[str]:
    """Infer the coordinator address the way the reference's sbatch infers the
    master node from $SLURM_NODELIST / $COBALT_NODEFILE (sbatch:49-62)."""
    nodelist = os.environ.get("SLURM_NODELIST")
    if nodelist:
        out = subprocess.run(
            ["scontrol", "show", "hostnames", nodelist],
            capture_output=True,
            text=True,
            check=False,
        )
        if out.returncode == 0 and out.stdout.strip():
            return f"{out.stdout.splitlines()[0].strip()}:{port}"
    nodefile = os.environ.get("COBALT_NODEFILE")
    if nodefile and os.path.exists(nodefile):
        with open(nodefile) as f:
            first = f.readline().strip()
        if first:
            return f"{first}:{port}"
    return None


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the pod-wide rendezvous. Safe to call on single-host runs (no-op
    when no multi-host environment is detected).

    On Cloud TPU VMs `jax.distributed.initialize()` auto-discovers everything;
    the explicit arguments cover SLURM-style clusters (the reference's target,
    sbatch:64-70).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    auto_env = len([h for h in hostnames.split(",") if h]) > 1 or (
        "MEGASCALE_COORDINATOR_ADDRESS" in os.environ
    )
    # Generic env override (the Cobalt ssh fan-out script sets these,
    # scripts/run_pretraining.cobalt; any launcher without SLURM vars can).
    # ANY of the three present marks the run as explicitly multi-host, so a
    # partially-configured rank fails loudly inside initialize() instead of
    # silently training solo while its peers block on the rendezvous.
    env_explicit = any(v in os.environ for v in (
        "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"))
    explicit = explicit or env_explicit
    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    slurm = "SLURM_NODELIST" in os.environ and int(os.environ.get("SLURM_NNODES", "1")) > 1
    if not (explicit or auto_env or slurm):
        return  # single host, single process: nothing to rendezvous
    kwargs = {}
    if coordinator_address or slurm or process_id is not None:
        kwargs["coordinator_address"] = coordinator_address or infer_coordinator()
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    elif slurm:
        kwargs["num_processes"] = int(os.environ["SLURM_NNODES"])
    if process_id is not None:
        kwargs["process_id"] = process_id
    elif slurm:
        kwargs["process_id"] = int(os.environ.get("SLURM_NODEID", "0"))
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        if "already initialized" in str(e).lower() and not env_explicit:
            # A harness touched jax.devices() first on a single-host run;
            # continue single-process rather than killing it.
            import warnings

            warnings.warn(f"jax.distributed.initialize skipped: {e}")
            return
        # Explicitly configured multi-host: a failed rendezvous must be
        # fatal, or this rank trains solo against its peers.
        raise
    _INITIALIZED = True
