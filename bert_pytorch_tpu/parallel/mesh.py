"""Device mesh construction and logical-axis rules.

Replaces the reference's process-group bootstrap (run_pretraining.py:183-185
``init_process_group('nccl')`` + torchrun rendezvous, sbatch:64-92). On TPU a
"process group" is a `jax.sharding.Mesh` over `jax.devices()`; multi-host
initialization is `jax.distributed.initialize` (see
bert_pytorch_tpu/parallel/launcher.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# The canonical mesh-axis names. Everything outside parallel/ must spell
# axes through these constants (enforced by jaxlint SD603, mirrored in
# analysis/axes.py): the one-mesh refactor then renames or splits an axis
# by editing this block, not by a repo-wide string hunt.
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_MODEL = "model"

MESH_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_PIPE, AXIS_SEQ, AXIS_MODEL)


@dataclasses.dataclass
class MeshConfig:
    """Sizes of each mesh axis; -1 on ``data`` means 'all remaining devices'.

    The product must equal the device count. The default is the reference's
    capability: pure data parallelism over every chip (§2.2). ``pipe`` is the
    pipeline-stage axis (parallel/pipeline.py).

    ``dcn_data`` > 1 builds a HYBRID mesh for multi-slice pods: that many
    data-parallel replicas span slices over DCN while every other axis
    (and the remaining data parallelism) stays within a slice on ICI —
    the standard multi-slice recipe (gradient all-reduce decomposes into
    a fast ICI phase and one small DCN phase per slice pair; XLA does the
    decomposition once the device order encodes slice adjacency).
    ``dcn_process_granule`` treats each PROCESS as the DCN granule instead
    of each TPU slice — the CPU multi-process test analog, where "slice"
    boundaries are process boundaries.
    """

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    seq: int = 1
    model: int = 1
    dcn_data: int = 1
    dcn_process_granule: bool = False

    def resolve(self, n_devices: int) -> tuple[int, int, int, int, int]:
        """Per-ICI-granule axis sizes (the full mesh's data axis is
        ``resolve()[0] * dcn_data``)."""
        fixed = self.fsdp * self.pipe * self.seq * self.model
        denom = fixed * self.dcn_data
        data = self.data
        if data == -1:
            if n_devices % denom != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"fsdp*pipe*seq*model*dcn_data={denom}"
                )
            data = n_devices // denom
        if data * denom != n_devices:
            raise ValueError(
                f"mesh {data}x{self.fsdp}x{self.pipe}x{self.seq}"
                f"x{self.model} (x{self.dcn_data} dcn)"
                f" != {n_devices} devices"
            )
        return (data, self.fsdp, self.pipe, self.seq, self.model)


def create_mesh(
    mesh_config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the ('data', 'fsdp', 'pipe', 'seq', 'model') mesh.

    Device order comes from `jax.devices()`, which JAX already returns in
    ICI-topology order — nearest-neighbor axes (model/seq) get the fastest
    links, matching the scaling-book layout recipe. With ``dcn_data`` > 1
    the device array instead comes from
    ``mesh_utils.create_hybrid_device_mesh`` so the data axis's leading
    dimension strides across DCN granules (slices, or processes under
    ``dcn_process_granule``) and every other axis stays granule-local.
    """
    mesh_config = mesh_config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    shape = mesh_config.resolve(len(devices))
    if mesh_config.dcn_data > 1:
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_hybrid_device_mesh(
            shape,
            (mesh_config.dcn_data, 1, 1, 1, 1),
            devices,
            process_is_granule=mesh_config.dcn_process_granule,
        )
    else:
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, MESH_AXES)


# Logical axis name -> mesh axis (or None = replicated), per strategy.
# Model code only knows logical names (bert.py); changing strategy never
# touches model code — this table is the entire parallelism configuration.
_BASE_RULES = [
    ("batch", ("data", "fsdp")),  # batch shards over data (and fsdp if used)
    ("seq_act", "seq"),  # activation sequence axis (context parallelism)
    ("pos", None),
    ("types", None),
    ("classes", None),
    ("layers", None),  # scan axis; an active 'pipe' axis overrides this
]

# The rule TEMPLATE: for each param logical axis, the mesh axis that
# controls it WHEN that axis is active in the mesh spec (size > 1), else
# the param replicates (None). Rules for any strategy product — dp×fsdp,
# dp×pipe, dp×fsdp×pipe×tp — derive from this one table instead of a
# fixed enumeration of named strategies; the legacy names below are
# aliases that lower onto specs with byte-identical rules (pinned by
# tests/test_one_mesh.py::test_legacy_alias_rules_byte_identical).
_RULE_TEMPLATE = [
    ("embed", AXIS_FSDP),  # ZeRO-style gather-on-use sharding
    ("embed_out", AXIS_MODEL),
    ("vocab", AXIS_MODEL),
    ("heads", AXIS_MODEL),
    ("kv", None),  # per-head dim: never sharded (heads already split)
    ("mlp", AXIS_MODEL),
]

# Legacy strategy aliases -> the mesh axes they activate. 'dp' activates
# only the (always-on) data axis; 'sp' activates seq, which shards
# activations via the base 'seq_act' rule but no params — hence its rule
# list equals dp's.
_STRATEGY_AXES = {
    "dp": (),
    "sp": (AXIS_SEQ,),
    "fsdp": (AXIS_FSDP,),
    "tp": (AXIS_MODEL,),
    "tp_fsdp": (AXIS_FSDP, AXIS_MODEL),
    "pp": (AXIS_PIPE,),
    "pp_tp": (AXIS_PIPE, AXIS_MODEL),
}


def derive_rules(active) -> list[tuple]:
    """Param-sharding rules for the set of ACTIVE mesh axes.

    An active 'pipe' prepends ``('layers', 'pipe')`` — each pipeline stage
    holds L/P contiguous layers; the pipeline engine runs 'pipe' manually
    (explicit ppermute) and leaves the other axes to the compiler. Every
    template rule then resolves to its controlling axis when active, else
    to None (replicated). Only param axes appear here; batch/seq_act
    sharding lives in ``_BASE_RULES`` (first-wins matching)."""
    active = frozenset(active)
    rules = []
    if AXIS_PIPE in active:
        rules.append(("layers", AXIS_PIPE))
    for name, axis in _RULE_TEMPLATE:
        rules.append((name, axis if axis is not None and axis in active
                      else None))
    return rules


# Derived per-alias tables, kept for introspection and the shardlint
# mirror (analysis/axes.py regenerates the same dict from the same two
# literal tables; tests/test_jaxlint.py pins them together by AST).
_STRATEGY_RULES = {
    name: derive_rules(axes) for name, axes in _STRATEGY_AXES.items()
}


class MeshSpecError(ValueError):
    """A mesh spec that cannot be realized, with the reason why."""


# Accepted spelling aliases for spec keys: strategy-flavored names map
# onto the canonical mesh axes.
_SPEC_KEY_ALIASES = {
    "dp": "data",
    "data": "data",
    "fsdp": "fsdp",
    "pipe": "pipe",
    "pp": "pipe",
    "seq": "seq",
    "sp": "seq",
    "ring": "seq",
    "model": "model",
    "tp": "model",
    "dcn": "dcn_data",
    "dcn_data": "dcn_data",
}


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative parallelism product: sizes of every mesh axis.

    The one-mesh configuration surface (``--mesh dp=4,fsdp=2,pipe=2``):
    device mesh, logical-axis rules, and collective wiring are all
    DERIVED from this — any axis product is expressible, and the combos
    that cannot work are rejected by :meth:`validate` with the reason.
    Legacy ``--parallel_strategy`` names lower onto specs via
    :meth:`from_strategy`. ``data == -1`` means 'all remaining devices'.
    """

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    seq: int = 1
    model: int = 1
    dcn_data: int = 1

    @staticmethod
    def parse(text: str) -> "MeshSpec":
        """Parse ``"dp=4,fsdp=2,pipe=2,seq=1"`` (keys accept the
        strategy-flavored aliases pp→pipe, sp/ring→seq, tp→model)."""
        sizes = {}
        for item in str(text).split(","):
            item = item.strip()
            if not item:
                continue
            key, sep, value = item.partition("=")
            key = key.strip().lower()
            if key not in _SPEC_KEY_ALIASES:
                raise MeshSpecError(
                    f"unknown mesh-spec key '{key}' in {text!r}; "
                    f"options: {sorted(set(_SPEC_KEY_ALIASES))}")
            canon = _SPEC_KEY_ALIASES[key]
            if not sep:
                raise MeshSpecError(
                    f"mesh-spec entry {item!r} wants KEY=SIZE")
            try:
                size = int(value)
            except ValueError:
                raise MeshSpecError(
                    f"mesh-spec size for '{key}' must be an integer, "
                    f"got {value!r}") from None
            if canon in sizes:
                raise MeshSpecError(
                    f"mesh-spec key '{canon}' given twice in {text!r}")
            sizes[canon] = size
        spec = MeshSpec(**sizes)
        spec.validate()
        return spec

    @staticmethod
    def from_strategy(strategy: str, *, data: int = -1, fsdp: int = 1,
                      pipe: int = 1, seq: int = 1, model: int = 1,
                      dcn_data: int = 1) -> "MeshSpec":
        """Lower a legacy ``--parallel_strategy`` name plus the legacy
        ``--mesh_*`` sizes onto a spec (rules stay byte-identical)."""
        if strategy not in _STRATEGY_AXES:
            raise MeshSpecError(
                f"unknown strategy '{strategy}'; "
                f"options: {sorted(_STRATEGY_AXES)}")
        return MeshSpec(data=data, fsdp=fsdp, pipe=pipe, seq=seq,
                        model=model, dcn_data=dcn_data)

    def canonical(self) -> str:
        """Round-trippable spec string; inactive axes are elided."""
        parts = [f"dp={self.data}"]
        for key in ("fsdp", "pipe", "seq", "model"):
            size = getattr(self, key)
            if size != 1:
                parts.append(f"{key}={size}")
        if self.dcn_data != 1:
            parts.append(f"dcn={self.dcn_data}")
        return ",".join(parts)

    def as_dict(self) -> dict:
        """Plain-int dict for the (stdlib-only) checkpoint manifest."""
        return {"data": self.data, "fsdp": self.fsdp, "pipe": self.pipe,
                "seq": self.seq, "model": self.model,
                "dcn_data": self.dcn_data}

    @staticmethod
    def from_dict(d: dict) -> "MeshSpec":
        known = {f.name for f in dataclasses.fields(MeshSpec)}
        return MeshSpec(**{k: int(v) for k, v in dict(d).items()
                           if k in known})

    def active_axes(self) -> frozenset:
        """Mesh axes with size > 1 (data counts when -1 = 'remaining')."""
        active = set()
        if self.data != 1:
            active.add(AXIS_DATA)
        for axis, size in ((AXIS_FSDP, self.fsdp), (AXIS_PIPE, self.pipe),
                           (AXIS_SEQ, self.seq), (AXIS_MODEL, self.model)):
            if size > 1:
                active.add(axis)
        return frozenset(active)

    def validate(self, *, n_devices: Optional[int] = None,
                 packed: bool = False) -> None:
        """Reject specs that cannot be realized, naming the reason.

        ``packed`` enables the sequence-packing compatibility check; pass
        ``n_devices`` to also enforce the axis-product divisibility."""
        for key in ("fsdp", "pipe", "seq", "model", "dcn_data"):
            size = getattr(self, key)
            if size < 1:
                raise MeshSpecError(
                    f"mesh-spec axis '{key}' must be >= 1, got {size}")
        if self.data < 1 and self.data != -1:
            raise MeshSpecError(
                f"mesh-spec axis 'data' must be >= 1 or -1 "
                f"(= all remaining devices), got {self.data}")
        if packed and self.seq > 1:
            raise MeshSpecError(
                "sequence packing composes with dp/fsdp/pipe/model but "
                "not with seq>1 (ring context parallelism): the packed "
                "block-diagonal attention mask ties together positions "
                "of one packed row, and the ring shards exactly that "
                "axis — segment boundaries cannot cross seq shards "
                "without a per-segment halo exchange")
        if n_devices is not None:
            try:
                self.mesh_config().resolve(n_devices)
            except MeshSpecError:
                raise
            except ValueError as e:
                # resolve() predates the spec layer; unify its divisibility
                # errors under the one spec-rejection type.
                raise MeshSpecError(str(e)) from None

    def mesh_config(self, *,
                    dcn_process_granule: bool = False) -> MeshConfig:
        return MeshConfig(data=self.data, fsdp=self.fsdp, pipe=self.pipe,
                          seq=self.seq, model=self.model,
                          dcn_data=self.dcn_data,
                          dcn_process_granule=dcn_process_granule)

    def rules(self) -> list[tuple]:
        """Full rule list for ``nn.logical_to_mesh_sharding``."""
        return derive_rules(self.active_axes()) + _BASE_RULES


def parse_mesh_spec(text: str) -> MeshSpec:
    """Module-level alias for :meth:`MeshSpec.parse`."""
    return MeshSpec.parse(text)


def logical_axis_rules(strategy="dp") -> list[tuple]:
    """Rule list for ``nn.logical_to_mesh_sharding``.

    Accepts a legacy strategy alias (str) or a :class:`MeshSpec`.
    Derived rules come first: matching is first-wins, and an active
    'pipe' axis overrides the base ``('layers', None)`` with
    ``('layers', 'pipe')``."""
    if isinstance(strategy, MeshSpec):
        return strategy.rules()
    if strategy not in _STRATEGY_AXES:
        raise ValueError(
            f"unknown strategy '{strategy}'; options: {sorted(_STRATEGY_AXES)}"
        )
    return derive_rules(_STRATEGY_AXES[strategy]) + _BASE_RULES


def current_mesh() -> Optional[Mesh]:
    """The active mesh, from either the new ``jax.set_mesh``/``use_mesh``
    context or the legacy ``with mesh:`` context used throughout this
    codebase; None if neither is set."""
    try:
        m = jax.sharding.get_mesh()
        if m is not None and getattr(m, "axis_names", ()):  # non-empty
            return m
    except Exception:
        pass
    try:
        from jax._src.mesh import thread_resources

        pm = thread_resources.env.physical_mesh
        if not pm.empty:
            return pm
    except Exception:
        pass
    return None
