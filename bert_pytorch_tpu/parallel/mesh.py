"""Device mesh construction and logical-axis rules.

Replaces the reference's process-group bootstrap (run_pretraining.py:183-185
``init_process_group('nccl')`` + torchrun rendezvous, sbatch:64-92). On TPU a
"process group" is a `jax.sharding.Mesh` over `jax.devices()`; multi-host
initialization is `jax.distributed.initialize` (see
bert_pytorch_tpu/parallel/launcher.py).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# The canonical mesh-axis names. Everything outside parallel/ must spell
# axes through these constants (enforced by jaxlint SD603, mirrored in
# analysis/axes.py): the one-mesh refactor then renames or splits an axis
# by editing this block, not by a repo-wide string hunt.
AXIS_DATA = "data"
AXIS_FSDP = "fsdp"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_MODEL = "model"

MESH_AXES = (AXIS_DATA, AXIS_FSDP, AXIS_PIPE, AXIS_SEQ, AXIS_MODEL)


@dataclasses.dataclass
class MeshConfig:
    """Sizes of each mesh axis; -1 on ``data`` means 'all remaining devices'.

    The product must equal the device count. The default is the reference's
    capability: pure data parallelism over every chip (§2.2). ``pipe`` is the
    pipeline-stage axis (parallel/pipeline.py).

    ``dcn_data`` > 1 builds a HYBRID mesh for multi-slice pods: that many
    data-parallel replicas span slices over DCN while every other axis
    (and the remaining data parallelism) stays within a slice on ICI —
    the standard multi-slice recipe (gradient all-reduce decomposes into
    a fast ICI phase and one small DCN phase per slice pair; XLA does the
    decomposition once the device order encodes slice adjacency).
    ``dcn_process_granule`` treats each PROCESS as the DCN granule instead
    of each TPU slice — the CPU multi-process test analog, where "slice"
    boundaries are process boundaries.
    """

    data: int = -1
    fsdp: int = 1
    pipe: int = 1
    seq: int = 1
    model: int = 1
    dcn_data: int = 1
    dcn_process_granule: bool = False

    def resolve(self, n_devices: int) -> tuple[int, int, int, int, int]:
        """Per-ICI-granule axis sizes (the full mesh's data axis is
        ``resolve()[0] * dcn_data``)."""
        fixed = self.fsdp * self.pipe * self.seq * self.model
        denom = fixed * self.dcn_data
        data = self.data
        if data == -1:
            if n_devices % denom != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by "
                    f"fsdp*pipe*seq*model*dcn_data={denom}"
                )
            data = n_devices // denom
        if data * denom != n_devices:
            raise ValueError(
                f"mesh {data}x{self.fsdp}x{self.pipe}x{self.seq}"
                f"x{self.model} (x{self.dcn_data} dcn)"
                f" != {n_devices} devices"
            )
        return (data, self.fsdp, self.pipe, self.seq, self.model)


def create_mesh(
    mesh_config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the ('data', 'fsdp', 'pipe', 'seq', 'model') mesh.

    Device order comes from `jax.devices()`, which JAX already returns in
    ICI-topology order — nearest-neighbor axes (model/seq) get the fastest
    links, matching the scaling-book layout recipe. With ``dcn_data`` > 1
    the device array instead comes from
    ``mesh_utils.create_hybrid_device_mesh`` so the data axis's leading
    dimension strides across DCN granules (slices, or processes under
    ``dcn_process_granule``) and every other axis stays granule-local.
    """
    mesh_config = mesh_config or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    shape = mesh_config.resolve(len(devices))
    if mesh_config.dcn_data > 1:
        from jax.experimental import mesh_utils

        device_array = mesh_utils.create_hybrid_device_mesh(
            shape,
            (mesh_config.dcn_data, 1, 1, 1, 1),
            devices,
            process_is_granule=mesh_config.dcn_process_granule,
        )
    else:
        device_array = np.asarray(devices).reshape(shape)
    return Mesh(device_array, MESH_AXES)


# Logical axis name -> mesh axis (or None = replicated), per strategy.
# Model code only knows logical names (bert.py); changing strategy never
# touches model code — this table is the entire parallelism configuration.
_BASE_RULES = [
    ("batch", ("data", "fsdp")),  # batch shards over data (and fsdp if used)
    ("seq_act", "seq"),  # activation sequence axis (context parallelism)
    ("pos", None),
    ("types", None),
    ("classes", None),
    ("layers", None),  # scan axis; the 'pp' strategy overrides this to 'pipe'
]

_STRATEGY_RULES = {
    # pipeline parallelism: the stacked-layer axis shards over 'pipe' (each
    # stage holds L/P contiguous layers); everything else replicates like dp.
    # The 'layers' base rule is overridden below (first match wins in
    # flax.linen.logical_to_mesh_sharding).
    "pp": [
        ("layers", "pipe"),
        ("embed", None),
        ("embed_out", None),
        ("vocab", None),
        ("heads", None),
        ("kv", None),
        ("mlp", None),
    ],
    # sequence/context parallelism: params replicated like dp; the activation
    # sequence axis ('seq_act', in _BASE_RULES) shards over the seq mesh axis.
    "sp": [
        ("embed", None),
        ("embed_out", None),
        ("vocab", None),
        ("heads", None),
        ("kv", None),
        ("mlp", None),
    ],
    "dp": [
        ("embed", None),
        ("embed_out", None),
        ("vocab", None),
        ("heads", None),
        ("kv", None),
        ("mlp", None),
    ],
    "fsdp": [
        ("embed", "fsdp"),
        ("embed_out", None),
        ("vocab", None),
        ("heads", None),
        ("kv", None),
        ("mlp", None),
    ],
    "tp": [
        ("embed", None),
        ("embed_out", "model"),
        ("vocab", "model"),
        ("heads", "model"),
        ("kv", None),
        ("mlp", "model"),
    ],
    # tp + fsdp composed: sharded params gather over fsdp, split over model.
    "tp_fsdp": [
        ("embed", "fsdp"),
        ("embed_out", "model"),
        ("vocab", "model"),
        ("heads", "model"),
        ("kv", None),
        ("mlp", "model"),
    ],
    # pipeline + tensor parallel composed: stage blocks over 'pipe', each
    # stage's matmuls split over 'model'. The pipeline engine runs 'pipe'
    # manually (explicit ppermute) and leaves 'model' to the compiler
    # (shard_map axis_names={'pipe'}), so these are the tp rules plus the
    # pipe-stacked layer axis.
    "pp_tp": [
        ("layers", "pipe"),
        ("embed", None),
        ("embed_out", "model"),
        ("vocab", "model"),
        ("heads", "model"),
        ("kv", None),
        ("mlp", "model"),
    ],
}


def logical_axis_rules(strategy: str = "dp") -> list[tuple]:
    """Rule list for ``nn.logical_to_mesh_sharding``.

    Strategy rules come first: matching is first-wins, and 'pp' overrides the
    base ``('layers', None)`` with ``('layers', 'pipe')``."""
    if strategy not in _STRATEGY_RULES:
        raise ValueError(
            f"unknown strategy '{strategy}'; options: {sorted(_STRATEGY_RULES)}"
        )
    return _STRATEGY_RULES[strategy] + _BASE_RULES


def current_mesh() -> Optional[Mesh]:
    """The active mesh, from either the new ``jax.set_mesh``/``use_mesh``
    context or the legacy ``with mesh:`` context used throughout this
    codebase; None if neither is set."""
    try:
        m = jax.sharding.get_mesh()
        if m is not None and getattr(m, "axis_names", ()):  # non-empty
            return m
    except Exception:
        pass
    try:
        from jax._src.mesh import thread_resources

        pm = thread_resources.env.physical_mesh
        if not pm.empty:
            return pm
    except Exception:
        pass
    return None
