"""Bucketed data-parallel gradient reduction, shaped for compute/comm
overlap.

Under plain jit the cross-replica gradient reduction is implicit: XLA
inserts the all-reduces wherever its SPMD partitioner likes, typically
fused into one tree-wide reduction that cannot start until the whole
backward has finished. The ZeRO/DDP lineage (PAPERS.md; Rajbhandari 2020)
overlaps instead: gradients for the layers that finish their backward
FIRST are reduced while the remaining backward still computes.

This module gives the graph that shape explicitly: gradients are grouped
into availability-ordered buckets — task heads (whose grads exit the
backward first), the encoder stack, embeddings (last) — and each bucket
gets its OWN ``lax.psum``. The psums depend only on their bucket's leaves,
so XLA's latency-hiding scheduler is free to run the heads' collective
under the encoder backward. It is used from inside a ``shard_map`` over
the batch axes where the per-shard backward produces LOCAL gradient sums
(pretrain.py ``overlap_grad_buckets``); numerically the bucketed psum of
local sums equals the implicit global reduction to fp32 roundoff (the
parity test pins 1e-6).
"""

from __future__ import annotations

import jax

# Availability order of the top-level parameter groups during the backward
# pass: head gradients materialize first, embeddings last. Bucket ids
# double as emission order — earlier buckets' collectives get the longest
# window of remaining backward compute to hide under.
_BUCKET_EMBEDDINGS = 2
_BUCKET_ENCODER = 1
_BUCKET_HEADS = 0
N_BUCKETS = 3


def _bucket_of(path) -> int:
    names = {str(getattr(entry, "key", entry)) for entry in path}
    if "embeddings" in names:
        return _BUCKET_EMBEDDINGS
    if "encoder" in names:
        return _BUCKET_ENCODER
    return _BUCKET_HEADS


def bucketed_psum(tree, axis_names):
    """``lax.psum(tree, axis_names)``, one collective per availability
    bucket instead of whatever single fusion XLA would pick. Exact: psum
    is psum; only the grouping (and therefore the schedulable order)
    changes."""
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
    leaves = [leaf for _, leaf in leaves_with_path]
    buckets = [[] for _ in range(N_BUCKETS)]
    for i, (path, _) in enumerate(leaves_with_path):
        buckets[_bucket_of(path)].append(i)
    for bucket in buckets:  # heads -> encoder -> embeddings
        if not bucket:
            continue
        reduced = jax.lax.psum([leaves[i] for i in bucket], axis_names)
        for i, leaf in zip(bucket, reduced):
            leaves[i] = leaf
    return jax.tree_util.tree_unflatten(treedef, leaves)
