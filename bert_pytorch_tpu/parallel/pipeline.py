"""Pipeline parallelism: a GPipe schedule over the mesh 'pipe' axis.

The reference's only model-training parallelism is data parallel (SURVEY.md
§2.2); this module is a beyond-parity strategy for models whose layer stack
does not fit (or does not scale) on one chip. TPU-native design: the encoder's
stacked layer parameters ([L, ...] from ``nn.scan``, bert.py) shard over a
'pipe' mesh axis — each stage holds L/P *contiguous* layers — and microbatch
activations rotate stage-to-stage with ``ppermute`` under ``shard_map``. The
communication pattern IS the algorithm here, so this is hand-written
collective code, like ops/ring.py and unlike everything under pjit.

Schedule: plain GPipe. M microbatches flow through P stages in M + P - 1
ticks; every stage applies its layer block each tick (bubble fraction
(P-1)/(M+P-1)). The backward pass is jax autodiff through the tick scan,
which reverses the rotation into the symmetric backward pipeline. Combine
with ``remat`` so each stage keeps only block boundaries alive.

Composition: only 'pipe' is MANUAL (``shard_map(axis_names={'pipe'})``) —
every other mesh axis stays automatic, so 'data'/'fsdp' batch sharding and
'model' tensor parallelism inside a stage compose for free: the stage's
matmuls see model-sharded weights (the 'pp_tp' rules) and GSPMD inserts the
tensor-parallel collectives, while the stage-to-stage rotation stays an
explicit ``ppermute``. 'seq' (ring attention) composes too, but not by
nesting (the nested partial-manual backward is rejected by Shardy's
lowering): pass ``seq_axis`` and the SAME shard_map goes manual over
{pipe, seq}, activations arrive sequence-sharded, and the stage body runs
the manual ring-attention collective (ops/attention.py
``backend='ring_manual'``) so K/V rotate over 'seq' inside this region —
pp x sp x tp in one step (tests/test_pipeline.py equivalence vs dp).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from bert_pytorch_tpu.parallel.mesh import AXIS_PIPE, AXIS_SEQ

try:  # jax >= 0.5: top-level shard_map with axis_names + lax.pcast typing
    from jax import shard_map as _shard_map

    def shard_map(f, *, mesh, axis_names, in_specs, out_specs):
        return _shard_map(f, mesh=mesh, axis_names=axis_names,
                          in_specs=in_specs, out_specs=out_specs)

    def _pcast_varying(x, axis_name):
        return jax.lax.pcast(x, axis_name, to="varying")

except ImportError:  # jax 0.4.x: experimental shard_map, auto= complement
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, axis_names, in_specs, out_specs):
        # The old API is manual over every mesh axis NOT listed in ``auto``;
        # the new axis_names= is its complement. check_rep=False because the
        # legacy replication checker predates (and rejects) the partial-auto
        # composition this engine relies on; the pcast/pvary annotations the
        # new typing needs don't exist here, so they no-op below.
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        # jit wrapper: the legacy eager path raises NotImplementedError for
        # partial-auto shard_maps; under the runner's outer jit this inlines.
        return jax.jit(_shard_map(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, auto=auto,
                                  check_rep=False))

    def _pcast_varying(x, axis_name):
        return x


def stage_layer_count(n_layers: int, n_stages: int) -> int:
    if n_layers % n_stages != 0:
        raise ValueError(
            f"num_hidden_layers={n_layers} must divide by pipeline stages "
            f"={n_stages} (contiguous equal blocks per stage)"
        )
    return n_layers // n_stages


def gpipe(
    stage_fn: Callable[..., jax.Array],
    stacked_params: Any,
    x: jax.Array,
    consts: Any,
    mesh: Mesh,
    replicated: Any = None,
    axis: str = AXIS_PIPE,
    seq_axis: str = None,
    x_seq_dim: int = 2,
    consts_seq_dims: Any = None,
) -> jax.Array:
    """Run ``x`` microbatches through the pipelined layer stack.

    Args:
      stage_fn: ``(local_params, x_mb, consts_mb, replicated, stage_id,
        mb_idx) -> y_mb``; applies one stage's L/P layers to one microbatch.
        ``mb_idx`` is the microbatch index (for PRNG folding); during bubble
        ticks it is clipped garbage and the result is discarded.
      stacked_params: pytree with leaves ``[L, ...]``, sharded over ``axis``
        on dim 0 (the 'pp'/'pp_tp' rules in parallel/mesh.py); any 'model'
        sharding on other dims flows through the automatic axes.
      x: ``[M, B, ...]`` microbatched activations, replicated over ``axis``;
        batch sharding over 'data'/'fsdp' flows through automatically.
      consts: pytree of per-microbatch side inputs (e.g. the attention bias),
        leaves ``[M, B, ...]``, sharded like ``x``.
      mesh: the device mesh; ``mesh.shape[axis]`` is the stage count.
      replicated: pytree passed to ``stage_fn`` verbatim on every stage
        (fully replicated — e.g. a PRNG key). Traced values must come in
        this way rather than by closure: ``shard_map`` rejects closed-over
        tracers.
      seq_axis: if set (the 'pp_sp' composition), that mesh axis joins the
        manual set and activations/consts are SHARDED over it — each device
        holds an S/n sequence slice and ``stage_fn`` must run the manual
        ring-attention body (attention ``backend='ring_manual'``) so K/V
        rotate over ``seq_axis`` inside this same region. One shard_map
        manual over {pipe, seq} sidesteps the nested-manual backward that
        Shardy rejects (the reason pp x sp was previously refused). Must
        be the mesh axis literally named 'seq': the ring_manual attention
        body and the stage dropout folding hardcode that axis name.
      x_seq_dim: dimension of ``x`` carrying the sequence (default 2:
        ``[M, B, S, ...]``).
      consts_seq_dims: pytree matching ``consts`` giving each leaf's
        sequence dimension (-1 = replicated over ``seq_axis``).

    Returns ``[M, B, ...]`` outputs, replicated over ``axis`` (every stage
    ends up with the full result — heads after the pipeline run replicated)
    and, when ``seq_axis`` is set, still sequence-sharded over it.
    """
    n_stages = mesh.shape[axis]
    n_mb = x.shape[0]
    if n_mb < n_stages:
        raise ValueError(
            f"need at least as many microbatches as pipeline stages: "
            f"{n_mb} < {n_stages} (the bubble would dominate anyway)"
        )
    if seq_axis is not None and seq_axis != AXIS_SEQ:
        # The ring_manual attention body (ops/attention.py) and the stage
        # dropout folding (pretrain.make_pp_train_step) hardcode the axis
        # name 'seq'; a differently-named axis would shard the activations
        # here but trace an unbound axis name deep inside the stage body.
        raise ValueError(
            f"gpipe seq_axis must be the mesh axis named 'seq' "
            f"(got {seq_axis!r})")
    if seq_axis is None and mesh.shape.get(AXIS_SEQ, 1) > 1:
        # Without the manual-ring composition, a seq>1 mesh would need ring
        # attention's own 'seq'-manual shard_map NESTED inside this region;
        # that type-checks, but Shardy's lowering verifier rejects the
        # backward pass today (propagation shards a residual dimension as
        # {pipe, seq} and "manual axes must come before free axes" within a
        # dim sharding). Callers compose pp with 'seq' by passing
        # ``seq_axis`` instead (pretrain.make_pp_train_step does).
        raise ValueError(
            "pipeline parallelism with a 'seq' mesh axis requires the "
            "manual ring composition: pass seq_axis='seq' (and a "
            "ring_manual stage_fn); see parallel/pipeline.py"
        )

    # 'pipe' (and 'seq' under pp_sp) are manual: specs mention only the
    # stacked-layer axis and the activation sequence axis, and every other
    # mesh axis (data/fsdp batch sharding, 'model' tensor parallelism)
    # keeps flowing through GSPMD automatically.
    manual = frozenset({axis}) if seq_axis is None else frozenset({axis, seq_axis})

    def param_spec(leaf):
        return P(axis, *(None,) * (leaf.ndim - 1))

    def rep_spec(leaf):
        return P(*(None,) * leaf.ndim)

    def seq_spec(leaf, seq_dim):
        if seq_axis is None or seq_dim < 0:
            return rep_spec(leaf)
        names = [None] * leaf.ndim
        names[seq_dim] = seq_axis
        return P(*names)

    # XLA's CPU AllReducePromotion pass crashes ("Invalid binary
    # instruction opcode copy") cloning bf16 all-reduces, and this region
    # implies two: the forward's last-stage psum and the transpose-inserted
    # psum for the cotangent of ``x`` (replicated over 'pipe' in its
    # in-spec). On the CPU test/dryrun path widen the boundary to f32 —
    # the TPU path keeps the half-width bf16 collectives over ICI.
    cpu_bf16 = x.dtype == jnp.bfloat16 and jax.default_backend() == "cpu"
    orig_dtype = x.dtype
    if cpu_bf16:
        x = x.astype(jnp.float32)

    x_spec = seq_spec(x, x_seq_dim if x_seq_dim is not None else -1)
    if consts_seq_dims is None:
        consts_specs = jax.tree_util.tree_map(rep_spec, consts)
    else:
        consts_specs = jax.tree_util.tree_map(seq_spec, consts, consts_seq_dims)

    in_specs = (
        jax.tree_util.tree_map(param_spec, stacked_params),
        x_spec,
        consts_specs,
        jax.tree_util.tree_map(rep_spec, replicated),
    )

    @partial(
        shard_map,
        mesh=mesh,
        axis_names=manual,
        in_specs=in_specs,
        out_specs=x_spec,
    )
    def run(local_params, x_local, consts_local, replicated_local):
        stage = jax.lax.axis_index(axis)
        ticks = n_mb + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        if seq_axis is not None:
            # Mark the (fp32 master) stage params varying over 'seq' HERE,
            # before any bf16 cast inside the stage body: the transpose of
            # this explicit pvary is the cross-shard cotangent psum, so it
            # runs on the fp32 cotangents (better gradient-reduction
            # numerics, and it sidesteps an XLA CPU AllReducePromotion
            # crash on the bf16 psums the auto-inserted invariance
            # conversions would otherwise create — Shardy leaks sharding
            # custom-calls into those reductions' to_apply computations).
            local_params = jax.tree_util.tree_map(
                lambda p: _pcast_varying(p, seq_axis),
                local_params)

        def tick(carry, t):
            outs, act = carry
            mb = jnp.clip(t - stage, 0, n_mb - 1)
            x_t = jax.lax.dynamic_index_in_dim(
                x_local, jnp.clip(t, 0, n_mb - 1), 0, keepdims=False
            )
            c_t = jax.tree_util.tree_map(
                lambda c: jax.lax.dynamic_index_in_dim(c, mb, 0, keepdims=False),
                consts_local,
            )
            inp = jnp.where(stage == 0, x_t, act)
            y = stage_fn(local_params, inp, c_t, replicated_local, stage, mb)
            out_idx = t - (n_stages - 1)
            idx = jnp.clip(out_idx, 0, n_mb - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, idx, 0, keepdims=False)
            keep = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(keep, y, cur), idx, 0
            )
            act_next = jax.lax.ppermute(y, axis, perm)
            return (outs, act_next), None

        # The carry is device-varying over 'pipe' after the first tick; mark
        # the zero initializers as varying so the scan carry type is stable
        # (shard_map's varying-manual-axes typing).
        outs0 = _pcast_varying(jnp.zeros_like(x_local), axis)
        act0 = _pcast_varying(jnp.zeros_like(x_local[0]), axis)
        (outs, _), _ = jax.lax.scan(
            tick, (outs0, act0), jnp.arange(ticks, dtype=jnp.int32)
        )
        # Only the last stage holds real outputs; give every stage the full
        # result so the (replicated) heads can run without a reshard.
        # (On the CPU path this psum — and the transpose-psum of x's
        # cotangent — run in f32 via the cpu_bf16 boundary cast above.)
        masked = jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(masked, axis)

    out = run(stacked_params, x, consts, replicated)
    return out.astype(orig_dtype) if cpu_bf16 else out
