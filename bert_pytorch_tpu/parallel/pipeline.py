"""Pipeline parallelism: a GPipe schedule over the mesh 'pipe' axis.

The reference's only model-training parallelism is data parallel (SURVEY.md
§2.2); this module is a beyond-parity strategy for models whose layer stack
does not fit (or does not scale) on one chip. TPU-native design: the encoder's
stacked layer parameters ([L, ...] from ``nn.scan``, bert.py) shard over a
'pipe' mesh axis — each stage holds L/P *contiguous* layers — and microbatch
activations rotate stage-to-stage with ``ppermute`` under ``shard_map``. The
communication pattern IS the algorithm here, so this is hand-written
collective code, like ops/ring.py and unlike everything under pjit.

Schedule: plain GPipe. M microbatches flow through P stages in M + P - 1
ticks; every stage applies its layer block each tick (bubble fraction
(P-1)/(M+P-1)). The backward pass is jax autodiff through the tick scan,
which reverses the rotation into the symmetric backward pipeline. Combine
with ``remat`` so each stage keeps only block boundaries alive.

Composition: only 'pipe' is MANUAL (``shard_map(axis_names={'pipe'})``) —
every other mesh axis stays automatic, so 'data'/'fsdp' batch sharding and
'model' tensor parallelism inside a stage compose for free: the stage's
matmuls see model-sharded weights (the 'pp_tp' rules) and GSPMD inserts the
tensor-parallel collectives, while the stage-to-stage rotation stays an
explicit ``ppermute``. 'seq' (ring attention) remains unsupported: the
nested partial-manual composition type-checks but Shardy's lowering
rejects the backward (see the guard below) — the engine raises rather
than fail deep inside compilation.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def stage_layer_count(n_layers: int, n_stages: int) -> int:
    if n_layers % n_stages != 0:
        raise ValueError(
            f"num_hidden_layers={n_layers} must divide by pipeline stages "
            f"={n_stages} (contiguous equal blocks per stage)"
        )
    return n_layers // n_stages


def gpipe(
    stage_fn: Callable[..., jax.Array],
    stacked_params: Any,
    x: jax.Array,
    consts: Any,
    mesh: Mesh,
    replicated: Any = None,
    axis: str = "pipe",
) -> jax.Array:
    """Run ``x`` microbatches through the pipelined layer stack.

    Args:
      stage_fn: ``(local_params, x_mb, consts_mb, replicated, stage_id,
        mb_idx) -> y_mb``; applies one stage's L/P layers to one microbatch.
        ``mb_idx`` is the microbatch index (for PRNG folding); during bubble
        ticks it is clipped garbage and the result is discarded.
      stacked_params: pytree with leaves ``[L, ...]``, sharded over ``axis``
        on dim 0 (the 'pp'/'pp_tp' rules in parallel/mesh.py); any 'model'
        sharding on other dims flows through the automatic axes.
      x: ``[M, B, ...]`` microbatched activations, replicated over ``axis``;
        batch sharding over 'data'/'fsdp' flows through automatically.
      consts: pytree of per-microbatch side inputs (e.g. the attention bias),
        leaves ``[M, B, ...]``, sharded like ``x``.
      mesh: the device mesh; ``mesh.shape[axis]`` is the stage count.
      replicated: pytree passed to ``stage_fn`` verbatim on every stage
        (fully replicated — e.g. a PRNG key). Traced values must come in
        this way rather than by closure: ``shard_map`` rejects closed-over
        tracers.

    Returns ``[M, B, ...]`` outputs, replicated over ``axis`` (every stage
    ends up with the full result — heads after the pipeline run replicated).
    """
    n_stages = mesh.shape[axis]
    n_mb = x.shape[0]
    if n_mb < n_stages:
        raise ValueError(
            f"need at least as many microbatches as pipeline stages: "
            f"{n_mb} < {n_stages} (the bubble would dominate anyway)"
        )
    if mesh.shape.get("seq", 1) > 1:
        # Nesting ring attention's 'seq'-manual shard_map inside this
        # region type-checks (disjoint manual axis sets, varying-axes
        # cotangents flow), but Shardy's lowering verifier rejects the
        # backward pass today: propagation shards a residual dimension as
        # {pipe, seq} and "manual axes must come before free axes" within
        # a dim sharding. Until the compiler lifts that, refuse rather
        # than fail deep inside lowering.
        raise ValueError(
            "pipeline parallelism does not compose with the 'seq' mesh "
            "axis (Shardy rejects the nested-manual backward; see "
            "parallel/pipeline.py)"
        )

    # Only 'pipe' is manual: specs mention nothing but the stacked-layer
    # axis, and every other mesh axis (data/fsdp batch sharding, 'model'
    # tensor parallelism) keeps flowing through GSPMD automatically.
    def param_spec(leaf):
        return P(axis, *(None,) * (leaf.ndim - 1))

    def rep_spec(leaf):
        return P(*(None,) * leaf.ndim)

    in_specs = (
        jax.tree_util.tree_map(param_spec, stacked_params),
        rep_spec(x),
        jax.tree_util.tree_map(rep_spec, consts),
        jax.tree_util.tree_map(rep_spec, replicated),
    )

    @partial(
        shard_map,
        mesh=mesh,
        axis_names=frozenset({axis}),
        in_specs=in_specs,
        out_specs=rep_spec(x),
    )
    def run(local_params, x_local, consts_local, replicated_local):
        stage = jax.lax.axis_index(axis)
        ticks = n_mb + n_stages - 1
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            outs, act = carry
            mb = jnp.clip(t - stage, 0, n_mb - 1)
            x_t = jax.lax.dynamic_index_in_dim(
                x_local, jnp.clip(t, 0, n_mb - 1), 0, keepdims=False
            )
            c_t = jax.tree_util.tree_map(
                lambda c: jax.lax.dynamic_index_in_dim(c, mb, 0, keepdims=False),
                consts_local,
            )
            inp = jnp.where(stage == 0, x_t, act)
            y = stage_fn(local_params, inp, c_t, replicated_local, stage, mb)
            out_idx = t - (n_stages - 1)
            idx = jnp.clip(out_idx, 0, n_mb - 1)
            cur = jax.lax.dynamic_index_in_dim(outs, idx, 0, keepdims=False)
            keep = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            outs = jax.lax.dynamic_update_index_in_dim(
                outs, jnp.where(keep, y, cur), idx, 0
            )
            act_next = jax.lax.ppermute(y, axis, perm)
            return (outs, act_next), None

        # The carry is device-varying over 'pipe' after the first tick; mark
        # the zero initializers as varying so the scan carry type is stable
        # (shard_map's varying-manual-axes typing).
        outs0 = jax.lax.pcast(jnp.zeros_like(x_local), axis, to="varying")
        act0 = jax.lax.pcast(jnp.zeros_like(x_local[0]), axis, to="varying")
        (outs, _), _ = jax.lax.scan(
            tick, (outs0, act0), jnp.arange(ticks, dtype=jnp.int32)
        )
        # Only the last stage holds real outputs; give every stage the full
        # result so the (replicated) heads can run without a reshard.
        return jax.lax.psum(
            jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs)), axis
        )

    return run(stacked_params, x, consts, replicated)
