"""Sharding helpers: logical-annotation → NamedSharding plumbing.

The glue between model code (logical axis names on params, bert.py) and the
mesh (mesh.py). This is where the reference's "DDP wraps the model"
(run_pretraining.py:270) becomes "every param/batch array gets a
NamedSharding and jit inserts the collectives".
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def mesh_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def batch_sharding(mesh: Mesh, seq_sharded: bool = False) -> NamedSharding:
    """Sharding for [B, S] / [B] host batches: batch over data(+fsdp) axes,
    sequence over seq axis when context parallelism is on."""
    if seq_sharded:
        return NamedSharding(mesh, P(("data", "fsdp"), "seq"))
    return NamedSharding(mesh, P(("data", "fsdp")))


def params_shardings(mesh: Mesh, abstract_variables: Any, rules) -> Any:
    """Map a tree of ``nn.Partitioned``-boxed abstract params (from
    ``jax.eval_shape(model.init, ...)``) to a tree of NamedShardings."""
    logical_specs = nn.get_partition_spec(abstract_variables)
    return nn.logical_to_mesh_sharding(logical_specs, mesh, rules)


def shard_params(params: Any, shardings: Any) -> Any:
    """Device-put a host param tree onto the mesh per the sharding tree."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), params, shardings
    )


def unbox(tree: Any) -> Any:
    """Strip ``nn.Partitioned`` metadata boxes, returning raw arrays."""
    return nn.unbox(tree)
