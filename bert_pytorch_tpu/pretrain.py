"""Pretraining engine: sharded train state + the single jitted train step.

The XLA analog of the reference's hot loop (SURVEY.md §3.1,
run_pretraining.py:405-460): where the reference does
fwd -> bwd -> DDP bucket allreduce -> FusedLAMB per microbatch sequence,
here ONE jitted function scans over the accumulation microbatches
(``lax.scan``), accumulates gradients locally, and applies the optimizer —
XLA inserts the cross-device gradient reduction implied by the shardings
(params replicated/sharded per strategy, batch sharded over data axes), so
no collective is ever written by hand. ``no_sync()`` (run_pretraining.py:
448-453) has no analog: communication happens once per step by construction.

bf16 activations / fp32 params+moments replace torch.cuda.amp + GradScaler
(run_pretraining.py:314-318,424-434) — bf16 needs no loss scaling.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bert_pytorch_tpu.models.losses import mlm_accuracy, pretraining_loss
from bert_pytorch_tpu.ops.grad_utils import global_norm
from bert_pytorch_tpu.optim.transforms import (LossScaleState, OptState,
                                               opt_step_count)
from bert_pytorch_tpu.parallel.mesh import (AXIS_DATA, AXIS_FSDP, AXIS_PIPE,
                                            AXIS_SEQ)
from bert_pytorch_tpu.parallel.sharding import params_shardings


@flax.struct.dataclass
class TrainState:
    params: Any
    opt_state: OptState
    rng: jax.Array


def _replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def state_shardings(mesh: Mesh, model, rules, sample_inputs,
                    loss_scaled: bool = False) -> TrainState:
    """Shardings for every leaf of TrainState, derived from the model's
    logical axis annotations (no per-param code — the point of the design).
    ``loss_scaled`` matches an fp16 optimizer wrapped in
    ``optim.dynamic_loss_scale`` (two extra replicated scalars)."""
    abstract = jax.eval_shape(
        lambda r: model.init(r, *sample_inputs), jax.random.PRNGKey(0)
    )
    p_shardings = params_shardings(mesh, abstract, rules)["params"]
    repl = _replicated(mesh)
    opt = OptState(count=repl, mu=p_shardings, nu=p_shardings)
    if loss_scaled:
        opt = LossScaleState(scale=repl, growth_count=repl, inner=opt)
    return TrainState(params=p_shardings, opt_state=opt, rng=repl)


def batch_shardings(mesh: Mesh, batch_spec: dict, seq_sharded: bool = False) -> dict:
    """Shardings for the [A, B, ...] stacked microbatch dict: accumulation
    axis replicated (scanned), batch axis sharded over data(+fsdp), and —
    under context parallelism (``seq_sharded``) — the sequence axis of
    [A, B, S] entries sharded over the mesh 'seq' axis."""
    out = {}
    for key, ndim in batch_spec.items():
        spec = [None, ("data", "fsdp")] + [None] * (ndim - 2)
        if seq_sharded and ndim == 3:
            spec[2] = "seq"
        out[key] = NamedSharding(mesh, P(*spec))
    return out


def make_init_fn(model, tx, sample_inputs, shardings: TrainState):
    """Jitted initializer producing an already-sharded TrainState."""

    def init_fn(rng):
        init_rng, state_rng = jax.random.split(rng)
        variables = nn.unbox(model.init(init_rng, *sample_inputs))
        params = variables["params"]
        return TrainState(
            params=params, opt_state=tx.init(params), rng=state_rng
        )

    return jax.jit(init_fn, out_shardings=shardings)


def _mlm_positions(labels, max_pred_per_seq):
    """Extract [B, P] masked positions + gathered labels when P < S (top_k on
    the label mask — stable, so the first max_pred masked positions win)."""
    if max_pred_per_seq is None or max_pred_per_seq >= labels.shape[-1]:
        return labels, None
    is_masked = (labels != -1).astype(jnp.int32)
    _, masked_positions = jax.lax.top_k(is_masked, max_pred_per_seq)
    labels = jnp.take_along_axis(labels, masked_positions, axis=1)
    return labels, masked_positions


def _apply_model(model, variables, mb, rng, max_pred_per_seq,
                 mutable=False):
    """Shared masked-position extraction + model apply: returns
    ``((mlm_logits, nsp_logits), labels, mutated)`` where ``labels`` are
    the (possibly position-gathered) MLM labels the loss must score
    against. Factored out of :func:`_apply_pretraining_loss` so the
    bucketed-overlap path (same apply, sum-form loss) cannot drift."""
    labels, masked_positions = _mlm_positions(
        mb["masked_lm_labels"], max_pred_per_seq
    )
    out = model.apply(
        variables,
        mb["input_ids"],
        mb["segment_ids"],
        mb["input_mask"],
        False,  # deterministic
        masked_positions,
        # Packed batches (data/packing.py) carry the extra arrays; absent
        # keys select the unpacked model path unchanged.
        mb.get("sequence_ids"),
        mb.get("cls_positions"),
        rngs={"dropout": rng},
        **({"mutable": mutable} if mutable else {}),
    )
    if mutable:
        logits, mutated = out
    else:
        logits, mutated = out, None
    return logits, labels, mutated


def _apply_pretraining_loss(model, variables, mb, rng, next_sentence,
                            max_pred_per_seq, mutable=False):
    """The one shared apply+loss(+accuracy) sequence behind every
    pretraining loss path — the plain train-step loss, the fused-capture
    tapped loss, and the K-FAC stats pass. One definition, so a loss or
    signature change cannot silently diverge between them.

    Returns (loss, acc, mutated); ``mutated`` is None unless ``mutable``
    names collections. ``acc`` is always computed — XLA dead-code
    eliminates it in consumers that drop it.
    """
    (mlm_logits, nsp_logits), labels, mutated = _apply_model(
        model, variables, mb, rng, max_pred_per_seq, mutable=mutable)
    loss = pretraining_loss(
        mlm_logits,
        nsp_logits if next_sentence else None,
        labels,
        mb["next_sentence_labels"] if next_sentence else None,
    )
    acc = mlm_accuracy(mlm_logits, labels)
    return loss, acc, mutated


def make_kfac_fns(
    model_tapped,
    next_sentence: bool = True,
    max_pred_per_seq: Optional[int] = None,
):
    """(apply_loss, tap_shape_fn) for :class:`bert_pytorch_tpu.optim.KFAC`,
    sharing the pretraining loss with the train step.

    ``model_tapped`` must be the same architecture built with
    ``kfac_tap=True``. Remat guidance depends on where the taps fire:
    the decoupled stats pass runs a small batch where ``remat='none'``
    suffices, while the fused in-train capture
    (``make_train_step(kfac_capture_model=...)``) should keep the main
    model's remat so microbatch 0's tapped backward fits the same memory
    budget (taps compose with ``nn.remat``).
    """

    def apply_loss(params, taps, mb, rng):
        loss, _, mutated = _apply_pretraining_loss(
            model_tapped, {"params": params, "kfac_taps": taps}, mb, rng,
            next_sentence, max_pred_per_seq, mutable=["kfac_a"]
        )
        return loss, mutated["kfac_a"]

    def tap_shape_fn(params, mb, rng):
        def f(p, mb_):
            _, _, mutated = _apply_pretraining_loss(
                model_tapped, {"params": p}, mb_, rng,
                next_sentence, max_pred_per_seq,
                mutable=["kfac_taps", "kfac_a"]
            )
            return mutated["kfac_taps"], mutated["kfac_a"]

        return jax.eval_shape(f, params, mb)

    return apply_loss, tap_shape_fn


def _jit_train_step(step_fn, shardings, batch_shardings_, kfac,
                    kfac_shardings, fused_kfac=False):
    """Shared jit dispatch for the train-step builders: donated state,
    declared shardings, and the optional kfac_state third argument.
    ``fused_kfac`` marks the in-train factor-capture step, which returns
    (and therefore donates) the kfac_state as a third output."""
    donate = (0, 2) if fused_kfac else (0,)
    if shardings is None:
        return jax.jit(step_fn, donate_argnums=donate)
    in_shardings = (shardings, batch_shardings_)
    if kfac is not None:
        in_shardings = in_shardings + (kfac_shardings,)
    out_shardings = (
        (shardings, None, kfac_shardings) if fused_kfac
        else (shardings, None))
    return jax.jit(
        step_fn,
        donate_argnums=donate,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
    )


def _make_overlap_step_fn(model, tx, mesh, schedule, next_sentence,
                          max_pred_per_seq, stats_every, stats_phase):
    """Train step whose data-parallel gradient reduction is EXPLICIT and
    bucketed for compute/communication overlap (parallel/overlap.py).

    The microbatch backward runs per shard inside a ``shard_map`` over the
    batch axes, producing LOCAL gradient sums; each availability bucket
    (heads -> encoder -> embeddings) then gets its own ``lax.psum``, so
    XLA's latency-hiding scheduler can run early buckets' collectives
    under the remaining backward compute — the ZeRO/DDP overlap shape the
    implicit one-shot reduction of plain jit cannot express.

    Numerics: each microbatch's local SUM loss is divided by the GLOBAL
    valid-token count (a psum of label counts — no gradient flows through
    it) before the backward, so per-shard grads psum to exactly the
    global-mean gradient; bucketed == unbucketed to fp32 roundoff (the
    parity test pins 1e-6). Dropout draws fold in the shard index — valid
    streams, but not bit-identical to the unbucketed path's (the same
    caveat as --rng_impl rbg).
    """
    from jax.sharding import PartitionSpec as P  # noqa: F811 (local alias)

    from bert_pytorch_tpu.models.losses import pretraining_loss_sums
    from bert_pytorch_tpu.parallel.overlap import bucketed_psum
    from bert_pytorch_tpu.parallel.pipeline import shard_map

    axes = ("data", "fsdp")

    def local_grads(params, batch, step_rng):
        # Runs PER SHARD: ``batch`` is the local [A, b_local, ...] slice.
        # Dropout decorrelates over BOTH batch axes — the batch shards
        # over ('data','fsdp') even under dp rules (params replicated),
        # so folding in only 'data' would hand every fsdp shard sharing a
        # data index identical masks for different examples.
        shard = (jax.lax.axis_index(AXIS_DATA) * mesh.shape[AXIS_FSDP]
                 + jax.lax.axis_index(AXIS_FSDP))
        rng0 = jax.random.fold_in(step_rng, shard)
        zero_grads = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params)

        def body(carry, mb):
            grads_acc, rng = carry
            rng, sub = jax.random.split(rng)
            # Global per-microbatch normalizers, from labels alone (the
            # position gather caps masked counts per row, so count AFTER
            # it — exactly what the mean-form loss divides by).
            gathered = _mlm_positions(
                mb["masked_lm_labels"], max_pred_per_seq)[0]
            c_mlm = jnp.maximum(
                jax.lax.psum(jnp.sum(gathered != -1), axes), 1
            ).astype(jnp.float32)
            c_nsp = jnp.maximum(
                jax.lax.psum(
                    jnp.sum(mb["next_sentence_labels"] != -1), axes), 1
            ).astype(jnp.float32) if next_sentence else jnp.float32(1)

            def local_loss(p):
                (mlm_logits, nsp_logits), labels, _ = _apply_model(
                    model, {"params": p}, mb, sub, max_pred_per_seq)
                mlm_sum, _, nsp_sum, _, correct = pretraining_loss_sums(
                    mlm_logits, nsp_logits if next_sentence else None,
                    labels,
                    mb["next_sentence_labels"] if next_sentence else None)
                loss = mlm_sum / c_mlm
                if next_sentence:
                    loss = loss + nsp_sum / c_nsp
                return loss, (mlm_sum, nsp_sum, correct)

            (_, aux), grads = jax.value_and_grad(
                local_loss, has_aux=True)(params)
            grads_acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(a.dtype), grads_acc, grads)
            mlm_sum, nsp_sum, correct = aux
            return (grads_acc, rng), (mlm_sum, nsp_sum, correct,
                                      c_mlm, c_nsp)

        (grads_acc, _), (mlm_sums, nsp_sums, corrects, c_mlms, c_nsps) = (
            jax.lax.scan(body, (zero_grads, rng0), batch))
        # Metric sums are scalars-per-microbatch: one cheap psum for all.
        g_mlm, g_nsp, g_correct = jax.lax.psum(
            (mlm_sums, nsp_sums, corrects.astype(jnp.float32)), axes)
        losses = g_mlm / c_mlms
        if next_sentence:
            losses = losses + g_nsp / c_nsps
        accs = g_correct / c_mlms
        # The overlap surface: availability-ordered per-bucket collectives.
        grads = bucketed_psum(grads_acc, axes)
        return grads, losses, accs

    def step_fn(state: TrainState, batch: dict):
        accum_steps = batch["input_ids"].shape[0]
        step_rng, new_rng = jax.random.split(state.rng)
        batch_specs = {
            k: P(*([None, axes] + [None] * (v.ndim - 2)))
            for k, v in batch.items()}
        grads, losses, accs = shard_map(
            local_grads, mesh=mesh, axis_names={AXIS_DATA, AXIS_FSDP},
            in_specs=(P(), batch_specs, P()),
            out_specs=(P(), P(), P()))(state.params, batch, step_rng)
        grads = jax.tree_util.tree_map(lambda g: g / accum_steps, grads)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = global_norm(grads)
        metrics = {
            "loss": jnp.mean(losses),
            "mlm_accuracy": jnp.mean(accs),
            "grad_norm": gnorm,
            # Same sentinel/padding contracts as make_train_step.
            "finite": (jnp.isfinite(jnp.sum(losses))
                       & jnp.isfinite(gnorm)).astype(jnp.float32),
            "real_tokens": jnp.sum(batch["input_mask"]).astype(jnp.float32),
        }
        if schedule is not None:
            metrics["learning_rate"] = schedule(
                opt_step_count(state.opt_state))
        if stats_every:
            from bert_pytorch_tpu.telemetry import model_stats

            metrics["grad_health"] = model_stats.gated_grad_health(
                state.params, grads, updates,
                opt_step_count(state.opt_state), stats_every,
                phase=stats_phase)
        return TrainState(
            params=params, opt_state=opt_state, rng=new_rng), metrics

    return step_fn


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    schedule: Optional[Callable] = None,
    next_sentence: bool = True,
    shardings: Optional[TrainState] = None,
    batch_shardings_: Optional[dict] = None,
    max_pred_per_seq: Optional[int] = None,
    kfac=None,
    kfac_shardings=None,
    kfac_capture_model=None,
    kfac_factor_interval: int = 1,
    kfac_inv_interval: int = 0,
    kfac_capture_microbatches: str = "first",
    loss_scale: bool = False,
    stats_every: int = 0,
    stats_phase: int = 0,
    mesh=None,
    overlap_grad_buckets: bool = False,
):
    """Build the jitted train step.

    ``batch`` is a dict of arrays with a leading accumulation axis:
    input_ids/segment_ids/input_mask/masked_lm_labels [A, B, S],
    next_sentence_labels [A, B]. Returns (new_state, metrics).

    When ``max_pred_per_seq`` is set, the masked positions are extracted
    inside the jitted step and the 30k-vocab decoder runs only on those
    [B, P] positions instead of all [B, S]: same loss, ~S/P less decoder
    compute.

    When ``kfac`` (a :class:`bert_pytorch_tpu.optim.KFAC`) is given, the
    step takes a third ``kfac_state`` argument and preconditions the
    accumulated gradients before the optimizer update (the
    ``preconditioner.step()`` slot in the reference's
    ``take_optimizer_step``, run_pretraining.py:405-417). Requires
    ``schedule`` for the kl_clip learning-rate term.

    ``kfac_capture_model`` switches K-FAC to FUSED in-train factor
    capture: pass the tapped twin of ``model`` (``kfac_tap=True``, same
    dtype/remat/backend) and the step harvests Kronecker factors from
    microbatch 0's own backward pass — the reference's free hook capture
    (run_pretraining.py:320-355) — instead of the runner paying a
    separate stats forward/backward per factor update. The step then
    RETURNS the updated kfac_state: ``(state, metrics, kfac_state)``.
    Factor EMA fires when ``opt_step_count % kfac_factor_interval == 0``
    (a ``lax.cond`` — skipped steps pay no capture FLOPs). With
    ``kfac_inv_interval > 0`` the inverse recompute ALSO runs in-jit
    under a cond on due steps, ordered factors → inverses →
    precondition exactly like kfac_pytorch's ``optimizer.step()``
    (hooks during backward, due inverses, then the preconditioned
    update); with 0 the caller drives ``kfac.update_inverses`` on the
    host and preconditioning sees inverses one factor-update stale.
    ``kfac_capture_microbatches`` picks the capture source on due
    steps: ``'first'`` (default) taps microbatch 0 only — capture cost
    amortizes over the accumulation; ``'all'`` accumulates statistics
    over EVERY microbatch's backward, kfac_pytorch's exact accumulation
    semantics (its hooks fire on each micro-backward), at capture cost
    proportional to accum_steps.

    ``loss_scale=True`` is the fp16 parity mode (reference GradScaler,
    run_pretraining.py:314-318): ``tx`` must be wrapped in
    ``optim.dynamic_loss_scale``; the step multiplies the loss by the
    state's current scale before differentiating and the wrapper
    unscales, finite-checks, and skips/backs off.

    ``overlap_grad_buckets=True`` (requires ``mesh``; data-parallel
    first-order path only) replaces the implicit tree-wide gradient
    reduction with explicit availability-ordered per-bucket psums so the
    early buckets' collectives overlap the remaining backward
    (:func:`_make_overlap_step_fn`; parallel/overlap.py). Exact to fp32
    roundoff against this function's default path.

    ``stats_every > 0`` splices the in-jit grad-health block
    (telemetry/model_stats.py: per-layer-group grad/param norms and
    update:weight ratios) into ``metrics["grad_health"]``, lax.cond-gated
    on the optimizer step counter so off-cadence steps pay nothing.
    ``stats_phase`` is the optimizer count at run start (resumed runs),
    aligning the due gate with the host's run-local sync cadence.
    TrainTelemetry.step_done pops and emits it.
    """
    if kfac is not None and schedule is None:
        raise ValueError("kfac preconditioning requires a schedule")
    if kfac is not None and loss_scale:
        raise ValueError(
            "loss_scale composes with first-order optimizers only; K-FAC "
            "runs in bf16/f32 where no scaler is needed")
    if kfac_capture_model is not None and kfac is None:
        raise ValueError("kfac_capture_model requires kfac")
    fused_kfac = kfac is not None and kfac_capture_model is not None
    if fused_kfac and kfac_factor_interval < 1:
        raise ValueError(
            f"kfac_factor_interval must be >= 1, got {kfac_factor_interval}")
    if kfac_inv_interval and not fused_kfac:
        raise ValueError(
            "kfac_inv_interval (in-jit inverse updates) requires the fused "
            "capture path (kfac_capture_model); host-driven flows call "
            "kfac.update_inverses themselves")
    if kfac_capture_microbatches not in ("first", "all"):
        raise ValueError(
            f"kfac_capture_microbatches must be first|all, got "
            f"{kfac_capture_microbatches!r}")
    if overlap_grad_buckets:
        if kfac is not None or loss_scale:
            raise ValueError(
                "overlap_grad_buckets composes with the plain first-order "
                "dp path only (no K-FAC, no fp16 loss scaling)")
        if mesh is None or shardings is None or batch_shardings_ is None:
            raise ValueError(
                "overlap_grad_buckets requires mesh + shardings (the "
                "explicit per-bucket collectives are defined over the "
                "mesh batch axes)")
        return _jit_train_step(
            _make_overlap_step_fn(
                model, tx, mesh, schedule, next_sentence, max_pred_per_seq,
                stats_every, stats_phase),
            shardings, batch_shardings_, None, None)

    def loss_fn(params, mb, rng):
        loss, acc, _ = _apply_pretraining_loss(
            model, {"params": params}, mb, rng,
            next_sentence, max_pred_per_seq)
        return loss, acc

    def tapped_loss_fn(params, taps, mb, rng):
        # Same math as loss_fn, through the tapped twin: identical logits
        # (taps are identity in the forward), plus the mutated kfac_a
        # collection and — under grad w.r.t. taps — the per-layer G
        # factors from the _g_factor_probe backward.
        loss, acc, mutated = _apply_pretraining_loss(
            kfac_capture_model, {"params": params, "kfac_taps": taps},
            mb, rng, next_sentence, max_pred_per_seq, mutable=["kfac_a"])
        return loss, (acc, mutated["kfac_a"])

    def step_fn(state: TrainState, batch: dict, kfac_state=None):
        accum_steps = batch["input_ids"].shape[0]
        step_rng, new_rng = jax.random.split(state.rng)
        scale = state.opt_state.scale if loss_scale else None

        def scaled_loss_fn(params, mb, rng):
            loss, acc = loss_fn(params, mb, rng)
            return loss * scale, (loss, acc)

        def body(carry, mb):
            grads_acc, rng = carry
            rng, sub = jax.random.split(rng)
            if loss_scale:
                (_, (loss, acc)), grads = jax.value_and_grad(
                    scaled_loss_fn, has_aux=True)(state.params, mb, sub)
            else:
                (loss, acc), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(state.params, mb, sub)
            grads_acc = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(a.dtype), grads_acc, grads
            )
            return (grads_acc, rng), (loss, acc)

        zero_grads = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state.params
        )
        if fused_kfac and kfac_capture_microbatches == "all":
            # kfac_pytorch accumulation semantics: every microbatch's
            # backward contributes statistics (its hooks fire per
            # micro-backward); the scan carries factor-stat accumulators
            # alongside the gradient accumulator.
            rows = (accum_steps * batch["input_ids"].shape[1]
                    * batch["input_ids"].shape[2])
            mb_scale = kfac.grad_scale(
                jax.tree_util.tree_map(lambda v: v[0], batch))

            def tapped_body(carry, mb):
                grads_acc, gtap_acc, astat_acc, rng = carry
                rng, sub = jax.random.split(rng)
                (loss, (acc, astats)), (grads, gtaps) = jax.value_and_grad(
                    tapped_loss_fn, argnums=(0, 1), has_aux=True
                )(state.params, kfac.zero_taps(), mb, sub)
                grads_acc = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(a.dtype), grads_acc, grads)
                gtap_acc = jax.tree_util.tree_map(
                    jnp.add, gtap_acc, gtaps)
                astat_acc = jax.tree_util.tree_map(
                    jnp.add, astat_acc, astats)
                return (grads_acc, gtap_acc, astat_acc, rng), (loss, acc)

            def all_capture(ks):
                (grads, gtap_sum, astat_sum, _), (losses, accs) = (
                    jax.lax.scan(
                        tapped_body,
                        (zero_grads, kfac.zero_taps(), kfac.zero_astats(),
                         step_rng),
                        batch))
                ks = kfac.ema_factors(ks, astat_sum, gtap_sum, rows, mb_scale)
                return losses, accs, grads, ks

            def all_plain(ks):
                (grads, _), (losses, accs) = jax.lax.scan(
                    body, (zero_grads, step_rng), batch)
                return losses, accs, grads, ks

            if kfac_factor_interval == 1:
                losses, accs, grads, kfac_state = all_capture(kfac_state)
            else:
                due = (opt_step_count(state.opt_state)
                       % kfac_factor_interval) == 0
                losses, accs, grads, kfac_state = jax.lax.cond(
                    due, all_capture, all_plain, kfac_state)
        elif fused_kfac:
            # 'first': microbatch 0 unrolls out of the scan so its
            # backward can be the tapped one; the rng split chain matches
            # body's exactly, so microbatch i sees the same dropout rng
            # either way.
            mb0 = jax.tree_util.tree_map(lambda v: v[0], batch)
            rng_rest, sub0 = jax.random.split(step_rng)
            rows = mb0["input_ids"].shape[0] * mb0["input_ids"].shape[1]

            def mb0_capture(ks):
                (loss0, (acc0, astats)), (g0, gtaps) = jax.value_and_grad(
                    tapped_loss_fn, argnums=(0, 1), has_aux=True
                )(state.params, kfac.zero_taps(), mb0, sub0)
                ks = kfac.ema_factors(
                    ks, astats, gtaps, rows, kfac.grad_scale(mb0))
                return loss0, acc0, g0, ks

            def mb0_plain(ks):
                (loss0, acc0), g0 = jax.value_and_grad(
                    loss_fn, has_aux=True)(state.params, mb0, sub0)
                return loss0, acc0, g0, ks

            if kfac_factor_interval == 1:
                loss0, acc0, grads0, kfac_state = mb0_capture(kfac_state)
            else:
                due = (opt_step_count(state.opt_state)
                       % kfac_factor_interval) == 0
                loss0, acc0, grads0, kfac_state = jax.lax.cond(
                    due, mb0_capture, mb0_plain, kfac_state)
            grads0 = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads0)
            if accum_steps > 1:
                rest = jax.tree_util.tree_map(lambda v: v[1:], batch)
                (grads, _), (losses_r, accs_r) = jax.lax.scan(
                    body, (grads0, rng_rest), rest
                )
                losses = jnp.concatenate([loss0[None], losses_r])
                accs = jnp.concatenate([acc0[None], accs_r])
            else:
                grads = grads0
                losses = loss0[None]
                accs = acc0[None]
        else:
            (grads, _), (losses, accs) = jax.lax.scan(
                body, (zero_grads, step_rng), batch
            )
        if fused_kfac and kfac_inv_interval:
            # Reference ordering: inverse-due steps rebuild the inverses
            # from the factors THIS step just captured, before
            # preconditioning.
            inv_due = (opt_step_count(state.opt_state)
                       % kfac_inv_interval) == 0
            kfac_state = jax.lax.cond(
                inv_due, kfac.inverse_factors, lambda s: s, kfac_state)
        grads = jax.tree_util.tree_map(lambda g: g / accum_steps, grads)

        if kfac is not None:
            grads = kfac.precondition(
                kfac_state, grads, schedule(opt_step_count(state.opt_state))
            )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        # grads carry the loss scale in fp16 mode; report the true norm
        gnorm = (global_norm(grads) / scale if loss_scale
                 else global_norm(grads))
        metrics = {
            "loss": jnp.mean(losses),
            "mlm_accuracy": jnp.mean(accs),
            "grad_norm": gnorm,
            # Failure sentinel (telemetry/sentinels.py): one scalar the host
            # can fetch for free alongside the loss. isfinite(sum) catches a
            # non-finite loss in ANY microbatch, not just the mean.
            "finite": (jnp.isfinite(jnp.sum(losses))
                       & jnp.isfinite(gnorm)).astype(jnp.float32),
            # Padding-aware throughput accounting (docs/telemetry.md): the
            # non-pad token count this step actually trained on. Telemetry
            # pops it on the sync cadence (never an extra device fetch) and
            # reports padding_efficiency / real-token throughput; with
            # sequence packing this approaches the full batch token budget.
            "real_tokens": jnp.sum(batch["input_mask"]).astype(jnp.float32),
        }
        if loss_scale:
            metrics["loss_scale"] = scale
        if schedule is not None:
            metrics["learning_rate"] = schedule(opt_step_count(state.opt_state))
        if stats_every:
            from bert_pytorch_tpu.telemetry import model_stats

            # fp16: skipped overflow steps do NOT advance the inner
            # optimizer count (optim/transforms.py dynamic_loss_scale),
            # so a count-based gate would drift off the host's
            # step-index sync cadence after the first skip and the
            # records would silently stop. Compute every step instead —
            # the O(params) reduction is noise next to the step's
            # O(params x tokens) — and let the sync cadence sample.
            metrics["grad_health"] = model_stats.gated_grad_health(
                state.params, grads, updates,
                opt_step_count(state.opt_state),
                1 if loss_scale else stats_every,
                grad_scale=scale if loss_scale else None,
                phase=stats_phase)
        new_state = TrainState(params=params, opt_state=opt_state, rng=new_rng)
        if fused_kfac:
            return new_state, metrics, kfac_state
        return new_state, metrics

    return _jit_train_step(
        step_fn, shardings, batch_shardings_, kfac, kfac_shardings,
        fused_kfac=fused_kfac)


def make_pp_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh,
    schedule: Optional[Callable] = None,
    next_sentence: bool = True,
    shardings: Optional[TrainState] = None,
    batch_shardings_: Optional[dict] = None,
    max_pred_per_seq: Optional[int] = None,
    kfac=None,
    kfac_shardings=None,
    stats_every: int = 0,
    stats_phase: int = 0,
):
    """Train step with the encoder executed as a GPipe pipeline over the
    mesh 'pipe' axis (parallel/pipeline.py).

    When ``kfac`` is given the step takes a third ``kfac_state`` argument
    and preconditions the pipeline-accumulated gradients before the
    optimizer update, exactly as in :func:`make_train_step` — the
    preconditioner is a pure per-layer solve over the stacked factors, so
    it composes with the pipe-sharded gradient layout (XLA reshards). The
    factor/inverse cadence runs OUTSIDE this step on the tapped non-pp
    model (the runner's pattern), which under automatic sharding reads the
    pipe-sharded params directly.

    The accumulation microbatches ([A, B, ...] stacked batch) ARE the
    pipeline microbatches: instead of ``lax.scan``-ing them sequentially
    (make_train_step), all A flow through the P pipeline stages concurrently
    and autodiff reverses the schedule for the backward — gradient
    accumulation falls out of the sum over microbatch losses. Embeddings and
    heads (<5% of BERT-large FLOPs) run replicated across stages on the
    flattened [A*B, ...] batch rather than being placed on the first/last
    stage.

    The forward reassembles ``BertForPreTraining.__call__`` (models/bert.py)
    from its submodules functionally, because the encoder's stacked layer
    params must be driven per stage-block; the module definitions and the
    parameter tree are shared with the non-pp path, so checkpoints are
    interchangeable between strategies.
    """
    from bert_pytorch_tpu.models.bert import (
        BertEmbeddings,
        BertLayer,
        BertLMPredictionHead,
        BertPooler,
        bert_normal_init,
    )
    from bert_pytorch_tpu.ops.attention import make_attention_bias
    from bert_pytorch_tpu.parallel.pipeline import gpipe, stage_layer_count

    cfg = model.config
    n_stages = mesh.shape[AXIS_PIPE]
    stage_layer_count(cfg.num_hidden_layers, n_stages)  # validate divisibility

    # pp x sp: with a 'seq' mesh axis the pipeline's shard_map goes manual
    # over {pipe, seq} and the layers run the manual ring-attention body
    # (ops/attention.py backend='ring_manual') — K/V rotate over 'seq'
    # inside the SAME manual region, sidestepping the nested-manual
    # backward Shardy rejects (parallel/pipeline.py docstring).
    seq_manual = mesh.shape.get(AXIS_SEQ, 1) > 1
    layer_backend = "ring_manual" if seq_manual else model.attention_backend

    emb_mod = BertEmbeddings(cfg, dtype=model.dtype)
    layer_mod = BertLayer(
        cfg, dtype=model.dtype, attention_backend=layer_backend
    )
    head_mod = BertLMPredictionHead(cfg, dtype=model.dtype)
    pooler_mod = BertPooler(cfg, dtype=model.dtype) if next_sentence else None
    nsp_mod = (
        nn.Dense(
            2,
            dtype=model.dtype,
            param_dtype=jnp.float32,
            kernel_init=bert_normal_init(cfg.initializer_range),
        )
        if next_sentence
        else None
    )

    remat_policy = None
    if model.remat == "dots":
        remat_policy = jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    elif model.remat == "full":
        remat_policy = jax.checkpoint_policies.nothing_saveable

    def loss_fn(params, batch, rng):
        n_mb, b, seq = batch["input_ids"].shape
        # Packed rows (data/packing.py) carry the extra arrays; their
        # block-diagonal attention bias replaces the [.., 1, S] padding
        # bias and already encodes the no-cross-contamination mask, so the
        # stages need no extra plumbing. packed x seq-sharding is rejected
        # at spec validation (parallel/mesh.py MeshSpec.validate).
        packed = "sequence_ids" in batch
        if seq_manual and seq % mesh.shape[AXIS_SEQ] != 0:
            raise ValueError(
                f"pp x sp: sequence length {seq} is not divisible by the "
                f"mesh 'seq' axis ({mesh.shape[AXIS_SEQ]})")
        if seq_manual and packed:
            raise ValueError(
                "packed batches cannot shard the sequence axis "
                "(MeshSpec.validate(packed=True) rejects seq>1)")
        # Two streams: embeddings dropout + the per-(layer, microbatch)
        # folding inside the pipeline. The heads are dropout-free.
        emb_rng, pipe_rng = jax.random.split(rng)

        flat = lambda a: a.reshape((n_mb * b,) + a.shape[2:])
        seq_ids = flat(batch["sequence_ids"]) if packed else None
        hidden = emb_mod.apply(
            {"params": params["bert"]["embeddings"]},
            flat(batch["input_ids"]),
            flat(batch["segment_ids"]),
            False,  # deterministic
            seq_ids,
            rngs={"dropout": emb_rng},
        )
        hidden = hidden.reshape(n_mb, b, seq, -1)
        bias = make_attention_bias(flat(batch["input_mask"]), dtype=jnp.float32,
                                   sequence_ids=seq_ids)
        # Unpacked: [A*B, 1, 1, S] -> [A, B, 1, 1, S]; packed
        # block-diagonal: [A*B, 1, S, S] -> [A, B, 1, S, S].
        bias = bias.reshape((n_mb, b) + bias.shape[1:])

        def apply_one(carry, lp, key, bias_mb):
            out, _ = layer_mod.apply(
                {"params": lp}, carry, bias_mb, False, rngs={"dropout": key}
            )
            return out

        if remat_policy is not None:
            apply_one = jax.checkpoint(
                apply_one, policy=remat_policy, prevent_cse=False
            )

        def stage_fn(local_params, h, bias_mb, rng_rep, stage, mb):
            n_local = jax.tree_util.tree_leaves(local_params)[0].shape[0]
            if seq_manual:
                # Decorrelate the hidden-state dropouts across sequence
                # shards: with a replicated key each shard would draw the
                # IDENTICAL mask for its local block of tokens. (The
                # attention-probability dropout decorrelates itself —
                # _ring_shard folds in the seq index too.)
                rng_rep = jax.random.fold_in(
                    rng_rep, jax.lax.axis_index(AXIS_SEQ))

            def body(carry, xs):
                lp, j = xs
                key = jax.random.fold_in(
                    jax.random.fold_in(rng_rep, stage * n_local + j), mb
                )
                return apply_one(carry, lp, key, bias_mb), None

            h, _ = jax.lax.scan(
                body, h, (local_params, jnp.arange(n_local, dtype=jnp.int32))
            )
            return h

        hidden = gpipe(
            stage_fn,
            params["bert"]["encoder"]["layers"],
            hidden,
            bias,
            mesh,
            replicated=pipe_rng,
            seq_axis=AXIS_SEQ if seq_manual else None,
            x_seq_dim=2,
            consts_seq_dims=4 if seq_manual else None,
        )

        seq_out = hidden.reshape(n_mb * b, seq, -1)
        labels, masked_positions = _mlm_positions(
            flat(batch["masked_lm_labels"]), max_pred_per_seq
        )
        if masked_positions is not None:
            onehot = jax.nn.one_hot(masked_positions, seq, dtype=model.dtype)
            seq_out = jnp.einsum("bps,bsh->bph", onehot, seq_out)
        word_embedding = params["bert"]["embeddings"]["word_embeddings"][
            "embedding"
        ]
        mlm_logits = head_mod.apply(
            {"params": params["predictions"]}, seq_out, word_embedding
        )
        nsp_logits = None
        nsp_labels = None
        if next_sentence:
            # Packed rows pool at each packed sequence's own [CLS] offset
            # ([A*B, K, hidden]); empty pack slots are neutralized by
            # their -1 NSP label (same contract as the non-pp path).
            pooled = pooler_mod.apply(
                {"params": params["bert"]["pooler"]},
                hidden.reshape(n_mb * b, seq, -1),
                flat(batch["cls_positions"]) if packed else None,
            )
            nsp_logits = nsp_mod.apply(
                {"params": params["seq_relationship"]}, pooled
            )
            nsp_labels = batch["next_sentence_labels"]
        # Per-MICROBATCH loss, then mean — the accumulation semantics of
        # make_train_step (and the reference's loss/accumulation_steps,
        # run_pretraining.py:445): each microbatch's masked-token mean gets
        # equal weight regardless of how many positions were masked in it.
        unflat = lambda a: a.reshape((n_mb, b) + a.shape[1:])
        losses = jax.vmap(pretraining_loss)(
            unflat(mlm_logits),
            unflat(nsp_logits) if next_sentence else None,
            unflat(labels),
            nsp_labels if next_sentence else None,
        )
        accs = jax.vmap(mlm_accuracy)(unflat(mlm_logits), unflat(labels))
        return jnp.mean(losses), jnp.mean(accs)

    if kfac is not None and schedule is None:
        raise ValueError("kfac preconditioning requires a schedule")

    def step_fn(state: TrainState, batch: dict, kfac_state=None):
        step_rng, new_rng = jax.random.split(state.rng)
        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch, step_rng
        )
        if kfac is not None:
            grads = kfac.precondition(
                kfac_state, grads, schedule(opt_step_count(state.opt_state))
            )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = global_norm(grads)
        metrics = {
            "loss": loss,
            "mlm_accuracy": acc,
            "grad_norm": gnorm,
            # Failure sentinel (telemetry/sentinels.py), same contract as
            # make_train_step: a NaN in any microbatch propagates into the
            # mean loss, so isfinite(loss) covers them all.
            "finite": (jnp.isfinite(loss)
                       & jnp.isfinite(gnorm)).astype(jnp.float32),
            # Padding-aware accounting, same contract as make_train_step.
            "real_tokens": jnp.sum(batch["input_mask"]).astype(jnp.float32),
        }
        if schedule is not None:
            metrics["learning_rate"] = schedule(opt_step_count(state.opt_state))
        if stats_every:
            # Same grad-health block as make_train_step; the norms are
            # pure per-leaf reductions, so XLA reshards them over the
            # pipe-sharded gradient layout for free.
            from bert_pytorch_tpu.telemetry import model_stats

            metrics["grad_health"] = model_stats.gated_grad_health(
                state.params, grads, updates,
                opt_step_count(state.opt_state), stats_every,
                phase=stats_phase)
        return TrainState(params=params, opt_state=opt_state, rng=new_rng), metrics

    return _jit_train_step(
        step_fn, shardings, batch_shardings_, kfac, kfac_shardings)


def make_eval_step(model, next_sentence: bool = True):
    """Deterministic forward + loss for held-out evaluation. Handles
    packed validation batches the same way the train step does (the extra
    keys select the block-diagonal path)."""

    def eval_fn(params, batch):
        mlm_logits, nsp_logits = model.apply(
            {"params": params},
            batch["input_ids"],
            batch["segment_ids"],
            batch["input_mask"],
            True,  # deterministic
            None,  # masked_positions
            batch.get("sequence_ids"),
            batch.get("cls_positions"),
        )
        loss = pretraining_loss(
            mlm_logits,
            nsp_logits if next_sentence else None,
            batch["masked_lm_labels"],
            batch["next_sentence_labels"] if next_sentence else None,
        )
        return loss, mlm_accuracy(mlm_logits, batch["masked_lm_labels"])

    return jax.jit(eval_fn)


def check_batch_process_locality(mesh: Mesh) -> None:
    """Raise if any batch shard's replica set spans processes.

    The multi-host input path feeds each process ITS OWN loader slice
    (per-rank DataLoaders + ``make_array_from_process_local_data``). That
    is only correct when every (data, fsdp) batch shard — including its
    replicas over the pipe/seq/model axes — lives within one process;
    otherwise two processes would supply DIFFERENT host data for the same
    global rows and training silently diverges across ranks. The default
    id-ordered mesh satisfies this whenever pipe*seq*model divides the
    per-host device count (model parallelism inside the host, data across
    hosts — the layout you want on ICI anyway); reordered meshes that
    stripe pipe/model across hosts need a replicated input feed instead.
    """
    if jax.process_count() == 1:
        return
    devs = mesh.devices  # [data, fsdp, pipe, seq, model]
    d, f = devs.shape[0], devs.shape[1]
    for di in range(d):
        for fi in range(f):
            procs = {dev.process_index for dev in devs[di, fi].flat}
            if len(procs) > 1:
                raise ValueError(
                    f"batch shard (data={di}, fsdp={fi}) is replicated "
                    f"across processes {sorted(procs)} via the "
                    "pipe/seq/model axes; the per-process input pipeline "
                    "would feed it conflicting data. Keep pipe*seq*model "
                    "within one host (the default device order does this "
                    "when it divides the per-host chip count), or feed "
                    "every replica host identical batches."
                )


def put_batch(batch: dict, shardings: dict) -> dict:
    """Host numpy batch -> global sharded device arrays.

    Single-process: a device_put per array. Multi-host: each process passes
    its local slice of the global batch and
    ``make_array_from_process_local_data`` assembles the global array — the
    analog of per-rank DataLoaders feeding DDP (SURVEY §3.1).
    """
    if jax.process_count() == 1:
        # One device_put for the whole dict: a single dispatch (one tunnel
        # round-trip on remote-attached TPUs) instead of one per array.
        return jax.device_put(batch, {k: shardings[k] for k in batch})
    return {
        k: jax.make_array_from_process_local_data(shardings[k], v)
        for k, v in batch.items()
    }


def device_prefetch(loader, accum_steps: int, shardings: dict,
                    depth: int = 2):
    """Device-resident stacked batches, staged ``depth`` ahead.

    A :class:`~bert_pytorch_tpu.data.device_prefetch.DevicePrefetcher`
    over the loader: a background thread stacks the microbatches and
    dispatches ``device_put`` with the step's input shardings, so the H2D
    transfer (and the per-call dispatch latency) hides behind device
    compute — the role the reference's 4 pinned-memory DataLoader workers
    + non_blocking copies play on GPU (run_pretraining.py:394-395,539).
    With this in place the real input pipeline matches the
    synthetic-resident-batch bench (~400 seq/s, BERT-large phase 1 batch
    56 on one v5e), the loop's ``data_wait`` measures only true producer
    stalls, and the staging share reports as telemetry's ``h2d_wait``
    sub-phase (attach the returned prefetcher to TrainTelemetry).
    ``depth <= 0`` stages inline on the loop thread.
    """
    from bert_pytorch_tpu.data.device_prefetch import DevicePrefetcher

    return DevicePrefetcher(
        iter(loader),
        stage=lambda host: put_batch(
            stack_microbatches(host, accum_steps), shardings),
        depth=depth)


def stack_microbatches(batch: dict, accum_steps: int) -> dict:
    """[A*B, ...] host batch -> [A, B, ...] for the scan."""
    out = {}
    for k, v in batch.items():
        if v.shape[0] % accum_steps != 0:
            raise ValueError(
                f"batch dim {v.shape[0]} not divisible by accumulation steps "
                f"{accum_steps}"
            )
        out[k] = v.reshape((accum_steps, v.shape[0] // accum_steps) + v.shape[1:])
    return out
