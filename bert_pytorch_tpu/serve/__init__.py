"""Online inference subsystem (docs/serving.md).

The first subsystem that exercises the model library outside a training
loop: a bucket-compiled :class:`InferenceEngine` (params-only checkpoint
load, AOT warmup per (task, length-bucket), optional request packing via
``data/packing.py``), a dynamically micro-batching :class:`Batcher`
(flush on size or deadline), per-task pre/post-processing
(:mod:`~bert_pytorch_tpu.serve.tasks`), a stdlib JSON-over-HTTP front end
(:mod:`~bert_pytorch_tpu.serve.http`), the ``serve`` telemetry record
family (:class:`ServeTelemetry`) flowing through the schema-v1 JSONL
machinery, and request-level tracing + the Prometheus /metricsz export
plane (:class:`TraceCollector`, :mod:`~bert_pytorch_tpu.serve.tracing`).
"""

from bert_pytorch_tpu.serve.batcher import Batcher, BatcherFull, Request
from bert_pytorch_tpu.serve.engine import (BatchPlan, InferenceEngine,
                                           StagedBatch, TaskSpec)
from bert_pytorch_tpu.serve.http import make_server
from bert_pytorch_tpu.serve.router import (Router, RouterShed,
                                           make_router_server)
from bert_pytorch_tpu.serve.service import ServiceDraining, ServingService
from bert_pytorch_tpu.serve.stats import ServeTelemetry
from bert_pytorch_tpu.serve.supervisor import ReplicaSpec, Supervisor
from bert_pytorch_tpu.serve.tasks import TASK_NAMES, build_handlers
from bert_pytorch_tpu.serve.tracing import TraceCollector

__all__ = [
    "Batcher",
    "BatcherFull",
    "BatchPlan",
    "InferenceEngine",
    "ReplicaSpec",
    "Request",
    "Router",
    "RouterShed",
    "ServeTelemetry",
    "ServiceDraining",
    "ServingService",
    "StagedBatch",
    "Supervisor",
    "TaskSpec",
    "TraceCollector",
    "TASK_NAMES",
    "build_handlers",
    "make_router_server",
    "make_server",
]
