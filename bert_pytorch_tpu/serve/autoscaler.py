"""Signal-driven autoscaler: elastic fleet capacity with hysteresis,
surge protection, and graceful scale-down (docs/serving.md "Elastic
fleet", docs/fault_tolerance.md "Scale-down drain contract").

PR 11 made the fleet *resilient* (supervisor restarts, router failover)
and the continuous-batching work made it *fast*, but capacity stayed
fixed at launch: a traffic surge ended in brownout sheds, and a quiet
fleet burned replicas it did not need. The missing piece is a CONTROL
LOOP over the signals the fleet already exports — the replicas'
queue-wait share and error-budget burn (serve/tracing.py), the
``bert_serve_unfinished`` load gauge the router scrapes, and the
router's own shed/error counters — deciding ``scale_up`` /
``scale_down`` / ``hold`` each tick (Verma et al. 2015, Borg; Dean &
Barroso 2013 for why the shared AOT compile cache — a new replica warm
in seconds with ``compiles_cold == 0`` — is the precondition that makes
REACTIVE scaling viable at all).

Three disciplines keep the loop from thrashing the fleet it manages:

* **window evidence, not instantaneous readings** — a decision needs
  ``red_windows_to_scale_up`` consecutive overloaded windows (or
  ``green_windows_to_scale_down`` consecutive idle ones), the same
  consecutive-green discipline the rollout controller uses
  (serve/rollout.py). A red window additionally needs
  ``min_window_requests`` of traffic behind it (or an actual shed) —
  one noisy sample over a thin window is not a surge;
* **separate up/down cooldowns** — after ANY scaling action, another
  ``scale_up`` must wait ``up_cooldown_s`` and another ``scale_down``
  must wait ``down_cooldown_s`` (down is the slower, more cautious
  direction). A direction FLIP inside the cooldown window is therefore
  structurally impossible — which is exactly why the telemetry-report
  gate "autoscaler thrash" is zero-tolerance: the controller counts
  what cannot happen so the claim is falsifiable, the torn-serves
  pattern;
* **hard scale-down holds** — never shrink while any replica is in
  crash backoff or restarting (a SIGKILLed replica's owed respawn is
  not spare capacity), never while a previous drain is still in flight,
  never below ``min_replicas`` healthy, and never while a canary
  traffic split is active (serve/rollout.py owns the fleet's shape
  mid-rollout). Each hold names itself in the emitted record's
  ``reason``.

Scale-up goes through ``Supervisor.add_replica`` (fresh port + output
dir + never-reused index from a :class:`ReplicaTemplate`) and
``Router.add_target`` (the new target enters unhealthy until its first
clean scrape). Scale-down drains through the existing SIGTERM → rc-75
preemption contract (``Supervisor.drain_replica``: reap WITHOUT
respawn) and removes the router target only after the supervisor
confirms the drain — zero stranded requests, proven end to end by
``tools/chaos_serve.py --surge``.

Every tick's verdict is a schema-v1 ``scale_event`` record carrying the
decision, the triggering signal values, the replica count before/after,
and the cooldown/hold reason; the cross-record lint
(telemetry/schema.py) reconstructs fleet membership from the event
stream alone, so a decision the artifact cannot explain is a lint
failure, not a mystery.

This module is **stdlib-only and dual-loadable** like supervisor/router:
imported normally it is part of the serve package; loaded by FILE PATH
(tools/_bootstrap.py) it pulls its dependencies the same way, so the
jax-free chaos parent never executes the package ``__init__`` chain.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, List, Optional


def _load_pkg_module(subpkg: str, modname: str):
    """Import a stdlib-only package sibling both ways: through the
    package when this module was imported normally, by file path when
    this module was itself loaded by path (the package ``__init__``
    chain imports jax — the property tools/chaos_serve.py needs)."""
    if __package__:
        import importlib

        return importlib.import_module(
            f"bert_pytorch_tpu.{subpkg}.{modname}")
    import importlib.util

    alias = f"_fleet_{subpkg}_{modname}"
    module = sys.modules.get(alias)
    if module is not None:
        return module
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), subpkg, f"{modname}.py")
    spec = importlib.util.spec_from_file_location(alias, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[alias] = module
    spec.loader.exec_module(module)
    return module


_schema = _load_pkg_module("telemetry", "schema")
_supervisor = _load_pkg_module("serve", "supervisor")

# The decision vocabulary is pinned in telemetry/schema.py (the
# registry.py pattern): runtime and offline lint cannot drift.
SCALE_UP, SCALE_DOWN, HOLD = _schema.SCALE_DECISIONS

# Replica lifecycle states (serve/supervisor.py) the capacity/hold
# classification reads from status() rows.
_STARTING = _supervisor.STARTING
_RUNNING = _supervisor.RUNNING
_BACKOFF = _supervisor.BACKOFF
_FAILED = _supervisor.FAILED
_STOPPED = _supervisor.STOPPED


class AutoscalerError(ValueError):
    """Bad autoscaler configuration or an illegal call."""


def _is_active(st: dict) -> bool:
    """Whether a ``Supervisor.status()`` row counts as fleet capacity:
    not decommissioned (draining) and not given up on. BACKOFF/STARTING
    still count — a SIGKILLed replica's owed respawn is the SAME
    capacity, not new capacity (double-counting it is exactly the drift
    the membership chain lint forbids)."""
    return (not st.get("draining")
            and st.get("state") not in (_STOPPED, _FAILED))


class ElasticFleet:
    """Binds a live ``Supervisor`` + ``Router`` + ``ReplicaTemplate``
    into the actuation surface :class:`AutoscalerController` drives.

    Thread-safety rides the bound objects' own locks; the adapter's
    only state of its own is the pending-drain list (a drain is
    two-phase: SIGTERM now, router-target removal only after the
    supervisor confirms the exit), guarded by ``_lock``
    (concurrency registry, analysis/concurrency.py).
    """

    def __init__(self, supervisor, router, template,
                 alloc_port: Optional[Callable[[], int]] = None):
        self._supervisor = supervisor
        self._router = router
        self._template = template
        self._alloc_port = alloc_port
        self._lock = threading.Lock()
        self._pending_drains: List[dict] = []  # [{"replica", "url"}]

    # -- observation ------------------------------------------------------

    def status(self) -> List[dict]:
        return self._supervisor.status()

    def split_active(self) -> bool:
        return self._router.split_active()

    def draining(self) -> bool:
        """A drain is in flight until the router target is removed."""
        with self._lock:
            if self._pending_drains:
                return True
        return any(st.get("draining") and st.get("state") != _STOPPED
                   for st in self._supervisor.status())

    # -- actuation --------------------------------------------------------

    def scale_up(self) -> dict:
        """Grow by one: supervisor spawns from the template (fresh
        port/dir/index), then the router learns the target — which
        enters unhealthy until its first clean scrape, so the warming
        replica takes no traffic."""
        spec = self._supervisor.add_replica(
            self._template,
            port=self._alloc_port() if self._alloc_port else None)
        self._router.add_target(spec.url)
        return {"replica": spec.index, "url": spec.url,
                "port": spec.port}

    def begin_drain(self) -> Optional[dict]:
        """Pick the scale-down victim — the HIGHEST-index active
        replica, so elastically added capacity leaves first and the
        seed fleet stays — and start its SIGTERM drain. The router
        keeps routing to it until the supervisor confirms the exit
        (:meth:`reap_drained`); the replica's own draining gauge flips
        its router health on the next scrape, so new traffic stops
        while in-flight work finishes."""
        candidates = [st for st in self._supervisor.status()
                      if _is_active(st)]
        if not candidates:
            return None
        victim = max(candidates, key=lambda st: st["replica"])
        self._supervisor.drain_replica(victim["replica"])
        item = {"replica": victim["replica"], "url": victim["url"]}
        with self._lock:
            self._pending_drains.append(item)
        return dict(item)

    def reap_drained(self) -> List[dict]:
        """Remove the router target of every drain the supervisor has
        confirmed (state STOPPED). Called at the top of each controller
        tick — removal strictly AFTER the replica answered its last
        in-flight request."""
        states = {st["replica"]: st for st in self._supervisor.status()}
        with self._lock:
            pending = list(self._pending_drains)
        done = []
        for item in pending:
            st = states.get(item["replica"])
            if st is not None and st.get("state") == _STOPPED:
                self._router.remove_target(item["url"])
                with self._lock:
                    if item in self._pending_drains:
                        self._pending_drains.remove(item)
                done.append(item)
        return done


class RouterSignals:
    """Per-tick signal windows from a live :class:`Router` (and,
    optionally, the replicas' own ``/statsz``).

    Each call returns ONE observation window: deltas of the router's
    run-level outcome counters (requests/errors/sheds) since the
    previous call, the summed ``bert_serve_unfinished`` load gauge from
    the router's scrape table, and — when ``probe`` is wired — the
    worst per-replica ``queue_wait_share`` / ``slo_budget_burn`` from
    the tracing rollup (serve/tracing.py via ``/statsz`` ``phases``).
    Max over replicas on purpose: one overloaded replica is the surge
    signal; averaging it away is how brownouts sneak up.

    Single-caller by design (the controller's tick loop): the
    delta baseline is the only mutable state.
    """

    def __init__(self, router,
                 probe: Optional[Callable[[str], Optional[dict]]] = None):
        self._router = router
        self._probe = probe
        self._last = {"requests": 0, "errors": 0, "sheds": 0}

    def __call__(self) -> dict:
        snap = self._router.snapshot()
        reps = snap.get("replica_states") or []
        sig = {
            "window_requests": int(snap.get("requests", 0)
                                   - self._last["requests"]),
            "window_errors": int(snap.get("errors", 0)
                                 - self._last["errors"]),
            "window_sheds": int(snap.get("sheds", 0)
                                - self._last["sheds"]),
            "unfinished": sum(int(r.get("unfinished") or 0)
                              for r in reps),
        }
        self._last = {key: int(snap.get(key, 0))
                      for key in ("requests", "errors", "sheds")}
        if self._probe is not None:
            shares, burns = [], []
            for r in reps:
                try:
                    stats = self._probe(r["url"]) or {}
                except Exception:
                    continue
                phases = stats.get("phases") or {}
                if phases.get("queue_wait_share") is not None:
                    shares.append(float(phases["queue_wait_share"]))
                if phases.get("slo_budget_burn") is not None:
                    burns.append(float(phases["slo_budget_burn"]))
            if shares:
                sig["queue_wait_share"] = max(shares)
            if burns:
                sig["budget_burn"] = max(burns)
        return sig


class AutoscalerController:
    """The control loop: classify each signal window red (overloaded) /
    green (idle) / neutral, accumulate consecutive-window evidence, and
    scale within the ``[min_replicas, max_replicas]`` band under the
    cooldowns and hard holds documented in the module docstring.

    Collaborators are injectable for deterministic tests: ``fleet`` is
    anything with the :class:`ElasticFleet` surface (status /
    split_active / draining / scale_up / begin_drain / reap_drained),
    ``signals`` is a zero-arg callable returning one window's signal
    dict, ``clock`` a monotonic float. :meth:`tick` is public — the
    fake-clock tests drive passes themselves; :meth:`start` runs the
    production loop thread.

    All decision state lives under ``_lock`` (concurrency registry,
    analysis/concurrency.py): the loop thread mutates it while
    status() readers snapshot it.
    """

    def __init__(
        self,
        fleet,
        signals: Callable[[], dict],
        min_replicas: int = 1,
        max_replicas: int = 4,
        red_windows_to_scale_up: int = 2,
        green_windows_to_scale_down: int = 4,
        up_cooldown_s: float = 5.0,
        down_cooldown_s: float = 20.0,
        min_window_requests: int = 8,
        queue_wait_share_high: float = 0.25,
        queue_wait_share_low: float = 0.05,
        budget_burn_high: float = 1.0,
        budget_burn_low: float = 0.25,
        unfinished_high_per_replica: float = 8.0,
        unfinished_low_per_replica: float = 1.0,
        emit: Optional[Callable[[dict], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if not 1 <= int(min_replicas) <= int(max_replicas):
            raise AutoscalerError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"[{min_replicas}, {max_replicas}]")
        if int(red_windows_to_scale_up) < 1 \
                or int(green_windows_to_scale_down) < 1:
            raise AutoscalerError("evidence windows must be >= 1")
        if float(up_cooldown_s) < 0 or float(down_cooldown_s) < 0:
            raise AutoscalerError("cooldowns must be >= 0")
        for low, high, what in (
                (queue_wait_share_low, queue_wait_share_high,
                 "queue_wait_share"),
                (budget_burn_low, budget_burn_high, "budget_burn"),
                (unfinished_low_per_replica, unfinished_high_per_replica,
                 "unfinished_per_replica")):
            if not 0 <= float(low) < float(high):
                raise AutoscalerError(
                    f"need 0 <= {what}_low < {what}_high, got "
                    f"[{low}, {high}]")
        self.fleet = fleet
        self.signals = signals
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.red_windows_to_scale_up = int(red_windows_to_scale_up)
        self.green_windows_to_scale_down = int(green_windows_to_scale_down)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.min_window_requests = int(min_window_requests)
        self.queue_wait_share_high = float(queue_wait_share_high)
        self.queue_wait_share_low = float(queue_wait_share_low)
        self.budget_burn_high = float(budget_burn_high)
        self.budget_burn_low = float(budget_burn_low)
        self.unfinished_high_per_replica = float(
            unfinished_high_per_replica)
        self.unfinished_low_per_replica = float(unfinished_low_per_replica)
        self._emit_fn = emit
        self._clock = clock
        self._sleep = sleep
        # Decision state: consecutive-window evidence, cooldown
        # bookkeeping, the membership chain tail, and the impossibility
        # counter — all under _lock (the loop thread mutates while
        # status() readers snapshot).
        self._lock = threading.Lock()
        self._reds = 0
        self._greens = 0
        self._ticks = 0
        self._scale_ups = 0
        self._scale_downs = 0
        self._last_scale_at: Optional[float] = None
        self._last_direction: Optional[str] = None
        self._last_after: Optional[int] = None
        self._last_emitted: Optional[tuple] = None
        # Structurally impossible under the cooldown rule — counted
        # precisely so the zero-tolerance "autoscaler thrash" claim is
        # falsifiable (the torn-serves pattern).
        self._thrash = 0
        self._last_error: Optional[str] = None
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- window classification -------------------------------------------

    def _classify(self, sig: dict, capacity: int):
        """(red, green, why): red = overload evidence, green = idle
        evidence, neither = neutral (resets both streaks)."""
        reqs = int(sig.get("window_requests", 0) or 0)
        sheds = int(sig.get("window_sheds", 0) or 0)
        share = sig.get("queue_wait_share")
        burn = sig.get("budget_burn")
        unfinished = sig.get("unfinished")
        per_rep = (float(unfinished) / max(1, capacity)
                   if unfinished is not None else None)
        why = []
        if sheds > 0:
            why.append(f"sheds={sheds}")
        if share is not None \
                and float(share) >= self.queue_wait_share_high:
            why.append(f"queue_wait_share={float(share):.3f}")
        if burn is not None and float(burn) >= self.budget_burn_high:
            why.append(f"budget_burn={float(burn):.3f}")
        if per_rep is not None \
                and per_rep >= self.unfinished_high_per_replica:
            why.append(f"unfinished_per_replica={per_rep:.1f}")
        # Evidence floor: a hot reading over a thin window is noise —
        # unless the fleet actually SHED, which is its own evidence.
        red = bool(why) and (sheds > 0
                             or reqs >= self.min_window_requests)
        green = (not why and sheds == 0
                 and (share is None
                      or float(share) <= self.queue_wait_share_low)
                 and (burn is None
                      or float(burn) <= self.budget_burn_low)
                 and (per_rep is None
                      or per_rep <= self.unfinished_low_per_replica))
        return red, green, why

    def _cooldown_remaining(self, now: float, direction: str) -> float:
        if self._last_scale_at is None:
            return 0.0
        cool = (self.up_cooldown_s if direction == SCALE_UP
                else self.down_cooldown_s)
        return max(0.0, cool - (now - self._last_scale_at))

    # -- the control pass (public for fake-clock tests) -------------------

    def tick(self) -> dict:
        """One control pass: finish confirmed drains, read one signal
        window, classify, decide, actuate, emit. Returns the
        scale_event record (also emitted, deduplicated for holds)."""
        self.fleet.reap_drained()
        now = self._clock()
        sig = dict(self.signals() or {})
        status = self.fleet.status()
        active = [st for st in status if _is_active(st)]
        capacity = len(active)
        healthy = sum(1 for st in active if st.get("state") == _RUNNING)
        restarting = sum(1 for st in active
                         if st.get("state") in (_BACKOFF, _STARTING))
        draining = self.fleet.draining()
        split = self.fleet.split_active()
        red, green, why = self._classify(sig, capacity)

        with self._lock:
            self._ticks += 1
            if red:
                self._reds += 1
                self._greens = 0
            elif green:
                self._greens += 1
                self._reds = 0
            else:
                self._reds = 0
                self._greens = 0
            decision, reason = HOLD, "hold:evidence"
            if self._reds >= self.red_windows_to_scale_up:
                if capacity >= self.max_replicas:
                    reason = "hold:band_max"
                elif self._cooldown_remaining(now, SCALE_UP) > 0:
                    reason = "hold:up_cooldown"
                else:
                    decision = SCALE_UP
                    reason = "red_windows:" + ",".join(why)
            elif self._greens >= self.green_windows_to_scale_down:
                # Hard holds, in a fixed order: the reason names the
                # FIRST thing blocking the shrink.
                if capacity <= self.min_replicas:
                    reason = "hold:band_min"
                elif split:
                    reason = "hold:canary_split"
                elif draining:
                    reason = "hold:draining"
                elif restarting:
                    reason = "hold:restarting"
                elif healthy - 1 < self.min_replicas:
                    reason = "hold:min_healthy"
                elif self._cooldown_remaining(now, SCALE_DOWN) > 0:
                    reason = "hold:down_cooldown"
                else:
                    decision, reason = SCALE_DOWN, "green_windows"
            reds, greens = self._reds, self._greens
            since = (None if self._last_scale_at is None
                     else now - self._last_scale_at)

        # Actuate OUTSIDE the lock: spawning/draining does real I/O.
        detail: dict = {}
        if decision == SCALE_UP:
            try:
                detail = self.fleet.scale_up() or {}
            except Exception as exc:
                decision = HOLD
                reason = f"hold:scale_up_failed:{type(exc).__name__}"
                with self._lock:
                    self._last_error = f"{type(exc).__name__}: {exc}"
        elif decision == SCALE_DOWN:
            try:
                detail = self.fleet.begin_drain() or {}
            except Exception as exc:
                detail = {}
                with self._lock:
                    self._last_error = f"{type(exc).__name__}: {exc}"
            if not detail:
                decision, reason = HOLD, "hold:no_candidate"

        with self._lock:
            delta = {SCALE_UP: 1, SCALE_DOWN: -1}.get(decision, 0)
            before = capacity
            after = capacity + delta
            # Exogenous membership drift since the last EMITTED event
            # (a replica gave up, an operator intervened): stamped so
            # the offline lint can still reconstruct the chain.
            exogenous = (0 if self._last_after is None
                         else before - self._last_after)
            if decision == SCALE_UP:
                self._scale_ups += 1
                self._reds = 0
            elif decision == SCALE_DOWN:
                self._scale_downs += 1
                self._greens = 0
            if decision in (SCALE_UP, SCALE_DOWN):
                if (self._last_direction is not None
                        and decision != self._last_direction
                        and since is not None
                        and since < self._cooldown_for(decision)):
                    self._thrash += 1
                self._last_scale_at = now
                self._last_direction = decision
            record = {
                "kind": "scale_event", "tag": "autoscale",
                "decision": decision, "reason": reason,
                "replicas_before": before, "replicas_after": after,
                "exogenous": exogenous,
                "healthy": healthy,
                "reds": reds, "greens": greens,
                "window_requests": int(sig.get("window_requests", 0) or 0),
                "window_errors": int(sig.get("window_errors", 0) or 0),
                "window_sheds": int(sig.get("window_sheds", 0) or 0),
                "cooldown_s": self._cooldown_for(decision),
            }
            for key in ("queue_wait_share", "budget_burn", "unfinished"):
                if sig.get(key) is not None:
                    record[key] = sig[key]
            if since is not None:
                record["since_last_scale_s"] = round(since, 3)
            if detail.get("replica") is not None:
                record["replica"] = int(detail["replica"])
            # Hold-spam control: a hold repeating the previous hold's
            # reason with no membership movement adds nothing — emit
            # scaling actions always, holds only when something changed.
            dedup_key = (decision, reason, before, after, exogenous)
            emit_it = (decision != HOLD
                       or dedup_key != self._last_emitted)
            if emit_it:
                self._last_emitted = dedup_key
                self._last_after = after
        if emit_it:
            self._emit(record)
        return record

    def _cooldown_for(self, decision: str) -> float:
        """The cooldown the record is accountable to: a scale_down (or
        a hold) answers to the stricter down cooldown, a scale_up to
        the up cooldown — the offline thrash lint compares
        ``since_last_scale_s`` against exactly this number."""
        return (self.up_cooldown_s if decision == SCALE_UP
                else self.down_cooldown_s)

    def _emit(self, record: dict) -> None:
        if self._emit_fn is None:
            return
        try:
            self._emit_fn(record)
        except Exception:
            pass  # observability must never take the control loop down

    # -- lifecycle --------------------------------------------------------

    def start(self, interval_s: float = 1.0) -> None:
        """Run the control loop on its own daemon thread."""
        if self._thread is not None:
            raise AutoscalerError("controller already started")
        self._stop_event.clear()
        self._thread = threading.Thread(
            target=self._loop, args=(float(interval_s),),
            name="fleet-autoscaler", daemon=True)
        self._thread.start()

    def _loop(self, interval_s: float) -> None:
        while not self._stop_event.is_set():
            try:
                self.tick()
            except Exception as exc:
                # The loop survives a transient actuation/scrape error;
                # the error is surfaced in status() for the harness.
                with self._lock:
                    self._last_error = f"{type(exc).__name__}: {exc}"
            self._sleep(interval_s)

    def stop(self) -> None:
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def status(self) -> dict:
        """Controller snapshot under the lock — what the chaos harness
        and tests assert on."""
        with self._lock:
            return {
                "ticks": self._ticks,
                "reds": self._reds,
                "greens": self._greens,
                "scale_ups": self._scale_ups,
                "scale_downs": self._scale_downs,
                "thrash": self._thrash,
                "replicas": self._last_after,
                "last_error": self._last_error,
            }
