"""Dynamic micro-batching for online inference (docs/serving.md).

A :class:`Batcher` owns a thread-safe FIFO of :class:`Request` objects fed
by the HTTP worker threads and drained by the single dispatch thread
(serve/service.py). Batches form per task head (one jitted forward serves
one head) and flush on whichever comes first:

* **size** — the head-of-queue task has accumulated a full batch
  (``max_batch_size`` requests, or ``max_batch_size * max_requests_per_pack``
  when packing — packed rows hold several requests each);
* **deadline** — the OLDEST pending request has waited ``max_wait_ms``
  (tail latency is bounded by the oldest request, not the newest).

The flush policy is deliberately separated from the blocking machinery:
:meth:`poll` is a non-blocking pure function of (queue state, clock) so
tests drive it deterministically with an injected fake clock, while
:meth:`next_batch` adds the condition-variable wait the dispatch thread
uses in production.

Length-aware grouping happens downstream: the batcher keeps arrival order
(FIFO fairness bounds worst-case wait), and the engine's batch planner
(serve/engine.py ``plan_batch``) picks the smallest length bucket — and,
when packing, the row assignment — for the flushed group, returning any
requests that did not fit to :meth:`requeue_front`.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, List, Optional


class BatcherFull(RuntimeError):
    """Raised by :meth:`Batcher.submit` when the pending queue is at its
    ``max_pending`` cap — the load-shedding signal the HTTP layer turns
    into a 503 instead of letting memory (and client-visible latency)
    grow without bound under sustained overload."""


class Request:
    """One in-flight inference request.

    ``features`` is the task's prepared input (serve/tasks.py): a dict with
    unpadded ``input_ids``/``segment_ids`` plus task-specific decode
    context. ``length`` (tokens incl. specials) drives bucket selection and
    packing. The dispatch thread fulfils the request via :meth:`set_result`
    / :meth:`set_error`; the submitting thread blocks in :meth:`wait`.
    A submitter that gives up marks the request ``abandoned`` so the
    dispatch thread skips it instead of spending device time on a result
    nobody is waiting for.
    """

    _ids = itertools.count()

    def __init__(self, task: str, features: dict, payload: dict,
                 enqueued_at: Optional[float] = None):
        self.id = next(Request._ids)
        self.task = task
        self.features = features
        self.payload = payload
        self.length = len(features["input_ids"])
        # None until Batcher.submit stamps it (or process_batch, for
        # directly-constructed requests that never enter the queue) —
        # a sentinel, not 0.0, so an injected clock legitimately reading
        # 0.0 is never mistaken for "unstamped".
        self.enqueued_at = enqueued_at
        # Stamped by the batcher when the dispatch thread pops the
        # request (re-stamped after a plan-leftover requeue, so the
        # trace's queue span covers the whole time spent waiting).
        self.dequeued_at = enqueued_at
        # Host prepare() time measured by the submitting thread
        # (serve/service.py) — pre-queue, so trace context, not a span.
        self.prepare_s: float = 0.0
        self.completed_at: Optional[float] = None
        self.result: Optional[dict] = None
        self.error: Optional[str] = None
        self.abandoned = False
        # Filled by the dispatch thread for telemetry: seconds of jitted
        # forward (incl. the device sync) the request's batch cost.
        self.device_s: Optional[float] = None
        self._done = threading.Event()

    def set_result(self, result: dict, completed_at: float) -> None:
        self.result = result
        self.completed_at = completed_at
        self._done.set()

    def set_error(self, error: str, completed_at: float) -> None:
        self.error = error
        self.completed_at = completed_at
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


class Batcher:
    def __init__(
        self,
        max_batch_size: int = 8,
        max_wait_ms: float = 5.0,
        max_requests_per_pack: int = 1,
        max_pending: int = 1024,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_batch_size < 1:
            raise ValueError(
                f"max_batch_size must be >= 1, got {max_batch_size}")
        if max_requests_per_pack < 1:
            raise ValueError(
                "max_requests_per_pack must be >= 1, got "
                f"{max_requests_per_pack}")
        self.max_batch_size = int(max_batch_size)
        self.max_wait_ms = float(max_wait_ms)
        self.max_requests_per_pack = int(max_requests_per_pack)
        self.max_pending = int(max_pending)
        self._clock = clock
        self._pending: List[Request] = []
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        # Requests popped by the dispatch thread but not yet finished
        # (fulfilled, errored, or requeued as plan leftovers). Drain
        # (service.stop) must wait on pending + in-flight, not pending
        # alone: depth() reads 0 the instant a batch is popped, and a
        # stop() racing that window used to close the batcher while the
        # dispatch thread still held requests whose plan leftovers it
        # was about to requeue — stranding them with blocked waiters
        # (the requeue-during-drain ordering bug, PR 11).
        self._inflight = 0
        # Gauges for the serve telemetry window (serve/stats.py).
        self.depth_max = 0
        self.submitted = 0

    # -- producer side ---------------------------------------------------

    def submit(self, request: Request) -> None:
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._pending) >= self.max_pending:
                raise BatcherFull(
                    f"pending queue at max_pending={self.max_pending}; "
                    "shedding load")
            request.enqueued_at = self._clock()
            self._pending.append(request)
            self.submitted += 1
            self.depth_max = max(self.depth_max, len(self._pending))
            self._cond.notify()

    def requeue_front(self, requests: List[Request]) -> None:
        """Return requests a partial dispatch could not fit to the FRONT of
        the queue (they are the oldest; FIFO order is preserved). They
        move from in-flight back to pending, so :meth:`unfinished` never
        dips while a leftover is in transit — the drain loop's evidence.
        """
        if not requests:
            return
        with self._cond:
            self._pending[:0] = requests
            # max(0, ...): tests/offline callers may requeue requests
            # they never popped; the counter must not go negative.
            self._inflight = max(0, self._inflight - len(requests))
            self.depth_max = max(self.depth_max, len(self._pending))
            self._cond.notify()

    def done(self, n: int) -> None:
        """The dispatch thread finished ``n`` popped requests (result,
        error, or abandoned-and-skipped — anything except a requeue,
        which re-counts itself)."""
        if n <= 0:
            return
        with self._cond:
            self._inflight = max(0, self._inflight - n)
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- consumer side ---------------------------------------------------

    def _flush_size(self) -> int:
        """Requests of the head task that justify a size flush."""
        return self.max_batch_size * self.max_requests_per_pack

    def _take_head_task_locked(self) -> List[Request]:
        """Pop up to a full batch of the HEAD request's task, preserving
        both the taken group's and the remainder's arrival order."""
        head_task = self._pending[0].task
        take, keep = [], []
        limit = self._flush_size()
        for req in self._pending:
            if req.task == head_task and len(take) < limit:
                take.append(req)
            else:
                keep.append(req)
        self._pending = keep
        self._inflight += len(take)
        now = self._clock()
        for req in take:
            # Trace queue span: enqueued_at -> this pop (serve/tracing.py).
            req.dequeued_at = now
        return take

    def poll(self) -> Optional[List[Request]]:
        """Non-blocking: the next batch if one is DUE (size or deadline),
        else None. The deadline check uses the injected clock, so tests
        advance a fake clock instead of sleeping."""
        with self._cond:
            if not self._pending:
                return None
            head_task = self._pending[0].task
            n_head = sum(1 for r in self._pending if r.task == head_task)
            oldest_wait_ms = (self._clock()
                              - self._pending[0].enqueued_at) * 1000.0
            if (n_head >= self._flush_size()
                    or oldest_wait_ms >= self.max_wait_ms):
                return self._take_head_task_locked()
            return None

    def next_batch(self, timeout: Optional[float] = None
                   ) -> Optional[List[Request]]:
        """Blocking: wait until a batch is due (or the batcher closes /
        ``timeout`` elapses) and return it. The wait granularity is the
        time to the oldest request's deadline, so a lone request is
        dispatched ~``max_wait_ms`` after arrival without polling."""
        deadline = None if timeout is None else self._clock() + timeout
        while True:
            batch = self.poll()
            if batch is not None:
                return batch
            with self._cond:
                if self._closed and not self._pending:
                    return None
                if deadline is not None and self._clock() >= deadline:
                    return None
                waits = []
                if self._pending:
                    waits.append(max(
                        0.0,
                        self._pending[0].enqueued_at
                        + self.max_wait_ms / 1000.0 - self._clock()))
                if deadline is not None:
                    waits.append(max(0.0, deadline - self._clock()))
                self._cond.wait(timeout=min(waits) if waits else None)

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def unfinished(self) -> int:
        """Pending + in-flight: the requests the service still OWES an
        answer. This — not :meth:`depth` — is what a graceful drain
        waits on (depth alone reads 0 while a popped batch is being
        processed, and its plan leftovers may be about to requeue)."""
        with self._lock:
            return len(self._pending) + self._inflight

    def drain_remaining(self) -> List[Request]:
        """Pop and return every still-pending request (drain-deadline
        path, serve/service.py stop): the caller fails them
        deterministically instead of leaving their submitters blocked
        until the client-side timeout."""
        with self._cond:
            remaining, self._pending = self._pending, []
            self._cond.notify_all()
        return remaining
