"""Dynamic micro-batching for online inference (docs/serving.md).

A :class:`Batcher` owns a thread-safe FIFO of :class:`Request` objects fed
by the HTTP worker threads and drained by the single dispatch thread
(serve/service.py). Batches form per task head (one jitted forward serves
one head) and flush on whichever comes first:

* **size** — the head-of-queue task has accumulated a full batch
  (``max_batch_size`` requests, or ``max_batch_size * max_requests_per_pack``
  when packing — packed rows hold several requests each);
* **deadline** — the OLDEST pending request has waited ``max_wait_ms``
  (tail latency is bounded by the oldest request, not the newest).

The flush policy is deliberately separated from the blocking machinery:
:meth:`poll` is a non-blocking pure function of (queue state, clock) so
tests drive it deterministically with an injected fake clock, while
:meth:`next_batch` adds the condition-variable wait the dispatch thread
uses in production. The pipelined dispatch plane (docs/serving.md
"Continuous batching") grows a third path out of the same flush
machinery: :meth:`admit_into_forming` lets the assembler stage admit
requests that arrive WHILE a previous batch executes into the batch it
is still forming — continuous batching's iteration-level admission (Yu
et al., OSDI 2022) adapted to the one-shot encoder workload — instead
of parking them for the next flush.

Length-aware grouping happens downstream: the batcher keeps arrival order
(FIFO fairness bounds worst-case wait), and the engine's batch planner
(serve/engine.py ``plan_batch``) picks the smallest length bucket — and,
when packing, the row assignment — for the flushed group, returning any
requests that did not fit to :meth:`requeue_front`.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, List, Optional


class BatcherFull(RuntimeError):
    """Raised by :meth:`Batcher.submit` when the pending queue is at its
    ``max_pending`` cap — the load-shedding signal the HTTP layer turns
    into a 503 instead of letting memory (and client-visible latency)
    grow without bound under sustained overload."""


class Request:
    """One in-flight inference request.

    ``features`` is the task's prepared input (serve/tasks.py): a dict with
    unpadded ``input_ids``/``segment_ids`` plus task-specific decode
    context. ``length`` (tokens incl. specials) drives bucket selection and
    packing. The dispatch thread fulfils the request via :meth:`set_result`
    / :meth:`set_error`; the submitting thread blocks in :meth:`wait`.
    A submitter that gives up marks the request ``abandoned`` so the
    dispatch thread skips it instead of spending device time on a result
    nobody is waiting for.
    """

    _ids = itertools.count()

    def __init__(self, task: str, features: dict, payload: dict,
                 enqueued_at: Optional[float] = None):
        self.id = next(Request._ids)
        self.task = task
        self.features = features
        self.payload = payload
        self.length = len(features["input_ids"])
        # None until Batcher.submit stamps it (or process_batch, for
        # directly-constructed requests that never enter the queue) —
        # a sentinel, not 0.0, so an injected clock legitimately reading
        # 0.0 is never mistaken for "unstamped".
        self.enqueued_at = enqueued_at
        # Stamped by the batcher when the dispatch thread pops the
        # request (re-stamped after a plan-leftover requeue, so the
        # trace's queue span covers the whole time spent waiting).
        self.dequeued_at = enqueued_at
        # Host prepare() time measured by the submitting thread
        # (serve/service.py) — pre-queue, so trace context, not a span.
        self.prepare_s: float = 0.0
        # Inbound router trace context (serve/tracing.py
        # parse_trace_header), stamped by ServingService.submit like
        # prepare_s; the dispatch thread forwards it to the tracer so
        # the emitted serve_trace chains to the router's span tree.
        self.trace_ctx: Optional[dict] = None
        self.completed_at: Optional[float] = None
        self.result: Optional[dict] = None
        self.error: Optional[str] = None
        self.abandoned = False
        # True when the request joined a FORMING batch through the
        # admission window (Batcher.admit_into_forming) instead of a
        # normal flush — the continuous-batching win the serve_trace
        # `admitted_late` field and the admitted-late counters report.
        self.admitted_late = False
        # Filled by the dispatch thread for telemetry: seconds of jitted
        # forward (incl. the device sync) the request's batch cost.
        self.device_s: Optional[float] = None
        self._done = threading.Event()

    def set_result(self, result: dict, completed_at: float) -> None:
        self.result = result
        self.completed_at = completed_at
        self._done.set()

    def set_error(self, error: str, completed_at: float) -> None:
        self.error = error
        self.completed_at = completed_at
        self._done.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


class Batcher:
    def __init__(
        self,
        max_batch_size: int = 8,
        max_wait_ms: float = 5.0,
        max_requests_per_pack: int = 1,
        max_pending: int = 1024,
        clock: Callable[[], float] = time.monotonic,
    ):
        if max_batch_size < 1:
            raise ValueError(
                f"max_batch_size must be >= 1, got {max_batch_size}")
        if max_requests_per_pack < 1:
            raise ValueError(
                "max_requests_per_pack must be >= 1, got "
                f"{max_requests_per_pack}")
        self.max_batch_size = int(max_batch_size)
        self.max_wait_ms = float(max_wait_ms)
        self.max_requests_per_pack = int(max_requests_per_pack)
        self.max_pending = int(max_pending)
        self._clock = clock
        self._pending: List[Request] = []
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        # Requests popped by the dispatch thread but not yet finished
        # (fulfilled, errored, or requeued as plan leftovers). Drain
        # (service.stop) must wait on pending + in-flight, not pending
        # alone: depth() reads 0 the instant a batch is popped, and a
        # stop() racing that window used to close the batcher while the
        # dispatch thread still held requests whose plan leftovers it
        # was about to requeue — stranding them with blocked waiters
        # (the requeue-during-drain ordering bug, PR 11).
        self._inflight = 0
        # Gauges for the serve telemetry window (serve/stats.py).
        self.depth_max = 0
        self.submitted = 0

    # -- producer side ---------------------------------------------------

    def submit(self, request: Request) -> None:
        with self._cond:
            if self._closed:
                raise RuntimeError("batcher is closed")
            if len(self._pending) >= self.max_pending:
                raise BatcherFull(
                    f"pending queue at max_pending={self.max_pending}; "
                    "shedding load")
            request.enqueued_at = self._clock()
            self._pending.append(request)
            self.submitted += 1
            self.depth_max = max(self.depth_max, len(self._pending))
            self._cond.notify()

    def requeue_front(self, requests: List[Request]) -> None:
        """Return requests a partial dispatch could not fit to the FRONT of
        the queue (they are the oldest; FIFO order is preserved). They
        move from in-flight back to pending, so :meth:`unfinished` never
        dips while a leftover is in transit — the drain loop's evidence.
        A requeued request is no longer late-admitted, whatever path
        popped it: it will ride a future flush like any pending request,
        and the admitted_late marker must describe the batch that
        actually serves it.
        """
        if not requests:
            return
        with self._cond:
            for req in requests:
                req.admitted_late = False
            self._pending[:0] = requests
            # max(0, ...): tests/offline callers may requeue requests
            # they never popped; the counter must not go negative.
            self._inflight = max(0, self._inflight - len(requests))
            self.depth_max = max(self.depth_max, len(self._pending))
            self._cond.notify()

    def done(self, n: int) -> None:
        """The dispatch thread finished ``n`` popped requests (result,
        error, or abandoned-and-skipped — anything except a requeue,
        which re-counts itself)."""
        if n <= 0:
            return
        with self._cond:
            self._inflight = max(0, self._inflight - n)
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    # -- consumer side ---------------------------------------------------

    def flush_size(self) -> int:
        """Requests of the head task that justify a size flush — also
        the budget an admission window may grow a forming batch to
        (:meth:`admit_into_forming`)."""
        return self.max_batch_size * self.max_requests_per_pack

    def _take_head_task_locked(self) -> List[Request]:
        """Pop up to a full batch of the HEAD request's task, preserving
        both the taken group's and the remainder's arrival order."""
        head_task = self._pending[0].task
        take, keep = [], []
        limit = self.flush_size()
        for req in self._pending:
            if req.task == head_task and len(take) < limit:
                take.append(req)
            else:
                keep.append(req)
        self._pending = keep
        self._inflight += len(take)
        now = self._clock()
        for req in take:
            # Trace queue span: enqueued_at -> this pop (serve/tracing.py).
            req.dequeued_at = now
        return take

    def poll(self) -> Optional[List[Request]]:
        """Non-blocking: the next batch if one is DUE (size or deadline),
        else None. The deadline check uses the injected clock, so tests
        advance a fake clock instead of sleeping."""
        with self._cond:
            if not self._pending:
                return None
            head_task = self._pending[0].task
            n_head = sum(1 for r in self._pending if r.task == head_task)
            oldest_wait_ms = (self._clock()
                              - self._pending[0].enqueued_at) * 1000.0
            if (n_head >= self.flush_size()
                    or oldest_wait_ms >= self.max_wait_ms):
                return self._take_head_task_locked()
            return None

    def next_batch(self, timeout: Optional[float] = None
                   ) -> Optional[List[Request]]:
        """Blocking: wait until a batch is due (or the batcher closes /
        ``timeout`` elapses) and return it. The wait granularity is the
        time to the oldest request's deadline, so a lone request is
        dispatched ~``max_wait_ms`` after arrival without polling."""
        deadline = None if timeout is None else self._clock() + timeout
        while True:
            batch = self.poll()
            if batch is not None:
                return batch
            with self._cond:
                if self._closed and not self._pending:
                    return None
                if deadline is not None and self._clock() >= deadline:
                    return None
                waits = []
                if self._pending:
                    waits.append(max(
                        0.0,
                        self._pending[0].enqueued_at
                        + self.max_wait_ms / 1000.0 - self._clock()))
                if deadline is not None:
                    waits.append(max(0.0, deadline - self._clock()))
                self._cond.wait(timeout=min(waits) if waits else None)

    def admit_into_forming(self, task: str, limit: int) -> List[Request]:
        """Admission-window path (pipelined dispatch, docs/serving.md
        "Continuous batching"): pop up to ``limit`` pending requests of
        ``task`` so the assembler can fold them into the batch it is
        still FORMING while the executor runs the previous one — they
        ride the next device step instead of waiting for their own
        flush. Popped requests are stamped ``dequeued_at`` (their queue
        span ends at admission) and marked ``admitted_late``; they move
        to in-flight like any flush, so :meth:`unfinished` never dips.
        Non-blocking; returns [] when nothing matches (or the batcher
        is closed — a drain must not grow forming batches)."""
        if limit <= 0:
            return []
        with self._cond:
            if self._closed or not self._pending:
                return []
            take, keep = [], []
            for req in self._pending:
                if req.task == task and len(take) < limit:
                    take.append(req)
                else:
                    keep.append(req)
            if not take:
                return []
            self._pending = keep
            self._inflight += len(take)
            now = self._clock()
            for req in take:
                req.dequeued_at = now
                req.admitted_late = True
            return take

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def inflight(self) -> int:
        """Requests popped (flushed or late-admitted) but not yet
        finished — the in-flight half of :meth:`unfinished`, spanning
        every pipeline stage (forming, staged, executing, completing)."""
        with self._lock:
            return self._inflight

    def unfinished(self) -> int:
        """Pending + in-flight: the requests the service still OWES an
        answer. This — not :meth:`depth` — is what a graceful drain
        waits on (depth alone reads 0 while a popped batch is being
        processed, and its plan leftovers may be about to requeue), and
        what the router's least-loaded score balances on
        (``bert_serve_unfinished`` — a mid-batch replica must not
        scrape as idle). In pipelined dispatch the in-flight half spans
        every stage: requests in the forming batch, the staged handoff,
        the executing batch, and the completion stage all count until
        :meth:`done` retires them."""
        with self._lock:
            return len(self._pending) + self._inflight

    def drain_remaining(self) -> List[Request]:
        """Pop and return every still-pending request (drain-deadline
        path, serve/service.py stop): the caller fails them
        deterministically instead of leaving their submitters blocked
        until the client-side timeout."""
        with self._cond:
            remaining, self._pending = self._pending, []
            self._cond.notify_all()
        return remaining
