"""Shared CLI surface for the inference fast path (docs/serving.md).

One flag helper next to the engine options so every entry point that
builds an :class:`~bert_pytorch_tpu.serve.engine.InferenceEngine` —
``run_server.py`` online, ``tools/batch_infer.py`` offline, bench legs —
exposes the SAME quantization/kernel knobs with the same spellings, and
``/statsz`` reports the mode a replica is actually serving (the router
work reads it to tell a cheap int8 replica from an fp32 one).
"""

from __future__ import annotations

import argparse

QUANTIZE_CHOICES = ("none", "bf16", "int8")
ATTENTION_BACKENDS = ("xla", "pallas", "pallas_infer", "pallas_infer_int8",
                      "auto")
DISPATCH_MODES = ("pipelined", "serial")
AUTOTUNE_MODES = ("off", "load", "measure")


def add_dispatch_args(parser: argparse.ArgumentParser) -> None:
    """The dispatch-plane knob (serve/service.py, docs/serving.md
    "Continuous batching"), shared by run_server.py and the BENCH_SERVE
    legs so the A/B comparison uses one spelling."""
    parser.add_argument(
        "--dispatch_mode", type=str, default="pipelined",
        choices=DISPATCH_MODES,
        help="pipelined (default) runs the three-stage continuous-"
             "batching plane: an assembler admits late arrivals into "
             "the forming batch while the executor keeps the device "
             "hot and a completion stage decodes off the device "
             "thread; serial is the flush-then-wait loop, kept for "
             "A/B measurement")


def add_fast_path_args(parser: argparse.ArgumentParser) -> None:
    """The inference-fast-path engine options (ops/quant.py,
    ops/pallas/attention.py ``flash_attention_infer``)."""
    parser.add_argument(
        "--quantize", type=str, default="none", choices=QUANTIZE_CHOICES,
        help="inference weight format: bf16 halves weight bytes, int8 "
             "quarters the matmul weights and serves int8 GEMMs "
             "(per-tensor symmetric scales applied while the checkpoint "
             "streams in; embeddings/LayerNorm stay fp32). Parity bounds "
             "per level: docs/serving.md")
    parser.add_argument(
        "--attention_backend", type=str, default="xla",
        choices=ATTENTION_BACKENDS,
        help="encoder attention kernel for the serve forwards; "
             "pallas_infer is the forward-only fused kernel (TPU; "
             "interpret-mode on CPU) and pallas_infer_int8 its "
             "int8-QK^T variant (per-head symmetric scales; "
             "docs/serving.md 'Raw-speed kernels' for parity bounds)")
    parser.add_argument(
        "--fuse_epilogues", action="store_true",
        help="fold each head's output extraction into the forward's "
             "epilogue (fill_mask gathers its [MASK] slots before the "
             "vocab projection, squad stacks start/end into one "
             "output) — same results, fewer device->host bytes "
             "(docs/serving.md 'Raw-speed kernels')")
    parser.add_argument(
        "--epilogue_slots", type=int, default=8,
        help="per-row gather quota for fused epilogues; a batch whose "
             "rows carry more positions of interest falls back to the "
             "unfused forward")
    parser.add_argument(
        "--autotune", type=str, default="off", choices=AUTOTUNE_MODES,
        help="measured Pallas block-geometry pass for the "
             "pallas_infer* backends (ops/pallas/autotune.py): 'load' "
             "reads persisted winners from --autotune_cache, 'measure' "
             "additionally times candidates for unseen shapes at "
             "startup and persists the winners")
    parser.add_argument(
        "--autotune_cache", type=str, default="",
        help="autotune winners JSON, kept next to the persisted AOT "
             "compile cache with the same keying discipline (a warm "
             "restart that loads the same winners compiles the same "
             "programs under the same names — compiles_cold stays 0)")
# The engine itself normalizes the "none" spelling to None
# (InferenceEngine.__init__) — entry points pass args.quantize verbatim.


def add_tracing_args(parser: argparse.ArgumentParser) -> None:
    """The request-tracing / metrics-plane knobs (serve/tracing.py),
    shared by run_server.py, tools/batch_infer.py (its engine flags flow
    through run_server.parse_arguments), and the BENCH_SERVE legs."""
    parser.add_argument(
        "--trace_sample_rate", type=float, default=0.01,
        help="fraction of requests exported as serve_trace span trees "
             "(deterministic head sampling on the request id; requests "
             "over the SLO are ALWAYS traced). 0 disables trace export "
             "while the serve_phase aggregates and /metricsz keep "
             "working")
    parser.add_argument(
        "--slo_p99_ms", type=float, default=500.0,
        help="per-request latency SLO target (ms): drives the "
             "always-sample-slow rule, the over-SLO counters on "
             "/metricsz, and telemetry-report's SLO verdict. 0 disables "
             "SLO accounting")
    parser.add_argument(
        "--slo_error_budget", type=float, default=0.01,
        help="fraction of requests allowed over the SLO target before "
             "the error budget is burned (telemetry-report's "
             "budget-burn verdict)")


def build_tracer(args, emit=None, window: int = 64):
    """One TraceCollector from the add_tracing_args flags (the single
    construction point run_server/bench share)."""
    from bert_pytorch_tpu.serve.tracing import TraceCollector

    return TraceCollector(
        emit=emit,
        sample_rate=args.trace_sample_rate,
        slo_p99_ms=args.slo_p99_ms or None,
        error_budget=args.slo_error_budget,
        window=window)
