"""Bucket-compiled inference engine for the BERT task heads
(docs/serving.md).

The :class:`InferenceEngine` owns the device side of serving:

* **params-only checkpoint load** — each task head restores just the model
  subtree via :func:`bert_pytorch_tpu.utils.checkpoint.load_params_only`
  (a K-FAC pretraining checkpoint's preconditioner/optimizer pytrees never
  touch serving host memory); a missing checkpoint falls back to seeded
  random init (demo/smoke mode, loudly noted by run_server.py);
* **AOT bucket compilation** — one jitted forward per (task head,
  length-bucket, packedness), each with a STABLE function name per
  (task, bucket, packed, quant) so the persistent compile cache
  (whose key covers the fn-name-derived HLO module name) makes a
  restarted replica's warmup pure cache hits — cold start in seconds,
  ``startup["compiles_cold"] == 0``, proven by the cache counter
  events rather than wall clock. Compiles are attributed by the shared
  :class:`~bert_pytorch_tpu.telemetry.compile_events.CompileMonitor`,
  so the serve telemetry can assert "zero compiles after warmup"
  instead of hoping;
* **inference weight quantization** (``quantize="bf16"|"int8"``,
  ops/quant.py) — applied tensor-by-tensor inside the streaming
  params-only checkpoint decode; int8 serves ~4x smaller matmul
  weights through int8 GEMMs. ``attention_backend="pallas_infer"``
  selects the forward-only fused attention kernel
  (ops/pallas/attention.py);
* **batch planning** — :meth:`plan_batch` picks the SMALLEST bucket whose
  budget fits the flushed group (and, with packing on, the first-fit-
  decreasing row assignment over ``data/packing.py``'s packer), returning
  requests that do not fit for the batcher to requeue;
* **execution + demultiplexing** — split into three composable steps so
  the pipelined dispatch plane (serve/service.py, docs/serving.md
  "Continuous batching") can run them on different stages:
  :meth:`stage` pads/packs the group into the fixed
  (max_batch_size, bucket) compile shape (host-only — the assembler
  stage), :meth:`execute_staged` runs the jitted forward (the ONLY
  device call — the executor stage), and :meth:`demux` slices each
  request's own output back out (row, or (row, segment-span) /
  (row, pack-slot) when packed; host conversion — the completion
  stage). :meth:`execute` composes the three for the serial dispatch
  mode, offline scoring, and tests.

Batch shapes are FIXED at (max_batch_size, bucket): a partially full
group pads with all-zero rows (attention mask 0 — rows are independent
under the padding/block-diagonal mask, so parity with a direct
single-request forward holds to fp32 exactness; tests/test_serve.py).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bert_pytorch_tpu.config import BertConfig
from bert_pytorch_tpu.data.packing import first_fit_decreasing
from bert_pytorch_tpu.serve import tasks as tasks_lib
from bert_pytorch_tpu.serve.batcher import Request
from bert_pytorch_tpu.telemetry.compile_events import CompileMonitor
from bert_pytorch_tpu.testing import faults
from bert_pytorch_tpu.utils import checkpoint as ckpt_util


class SwapBusy(RuntimeError):
    """A second hot-swap was requested while one is already in flight
    (loads cannot overlap — serve/http.py maps this to HTTP 409)."""


class TaskSpec:
    """One served head: its flax model, restored (possibly quantized)
    params, handler, and the jitted (instrumented) forwards — ONE per
    (bucket, packedness, fused-epilogue), each with a stable per-spec
    function name (see :meth:`InferenceEngine._build_forwards`)."""

    def __init__(self, name: str, model, params, handler):
        self.name = name
        self.model = model
        self.params = params
        self.handler = handler
        self.forwards: Dict[Tuple[int, bool, bool], Callable] = {}


class BatchPlan:
    """Output of :meth:`InferenceEngine.plan_batch`."""

    def __init__(self, bucket: int, rows: List[List[Request]],
                 leftover: List[Request], packed: bool):
        self.bucket = bucket
        self.rows = rows          # per dispatched row, its member requests
        self.leftover = leftover  # did not fit; requeue at queue front
        self.packed = packed

    @property
    def requests(self) -> List[Request]:
        return [r for row in self.rows for r in row]


class StagedBatch:
    """A plan staged into its fixed compile-shape arrays, ready for the
    device (output of :meth:`InferenceEngine.stage`).

    ``args`` is the positional argument tuple the plan's jitted forward
    takes (after params); ``offsets`` maps request id -> (row, token
    offset, pack slot) for :meth:`InferenceEngine.demux`; ``pack_s`` is
    the host seconds spent filling the arrays — the engine's share of
    the trace's ``assembly`` span. ``staged_at`` is stamped by the
    dispatch plane (the assembler) when staging completes, so the
    executor's pickup delay (``staged_wait``) is attributable.

    ``fused`` selects the fused-epilogue forward variant (docs/
    serving.md "Raw-speed kernels"); for a ``"gather"`` epilogue,
    ``gather_slots`` maps request id -> (row, first slot, slot count)
    into the [B, epilogue_slots, V] gathered output."""

    def __init__(self, task: str, plan: BatchPlan, args: tuple,
                 offsets: Dict[int, Tuple[int, int, int]], pack_s: float,
                 fused: bool = False,
                 gather_slots: Optional[Dict[int, Tuple[int, int, int]]]
                 = None):
        self.task = task
        self.plan = plan
        self.args = args
        self.offsets = offsets
        self.pack_s = pack_s
        self.fused = fused
        self.gather_slots = gather_slots or {}
        self.staged_at: Optional[float] = None


class InferenceEngine:
    def __init__(
        self,
        config: BertConfig,
        tokenizer,
        tasks: Dict[str, dict],
        buckets: Sequence[int] = (64, 128),
        max_batch_size: int = 8,
        max_requests_per_pack: int = 1,
        dtype=None,
        seed: int = 0,
        monitor: Optional[CompileMonitor] = None,
        clock: Callable[[], float] = time.perf_counter,
        quantize: Optional[str] = None,
        attention_backend: str = "xla",
        fuse_epilogues: bool = False,
        epilogue_slots: int = 8,
        autotune: str = "off",
        autotune_cache: Optional[str] = None,
        version: str = "v0",
    ):
        """``quantize`` selects the inference weight format
        (ops/quant.py): None serves the checkpoint's fp32 params,
        ``"bf16"`` halves weight bytes, ``"int8"`` quarters the matmul
        weights and runs int8 GEMMs (per-token dynamic activation
        scales). ``attention_backend`` routes the encoder's attention
        (ops/attention.py); ``"pallas_infer"`` is the forward-only fused
        kernel for serving on TPU (interpret-mode on CPU) and
        ``"pallas_infer_int8"`` its int8-QK^T variant (per-head
        symmetric scales).

        ``fuse_epilogues`` folds each head's output extraction into the
        forward's epilogue (docs/serving.md "Raw-speed kernels"):
        fill_mask gathers its [MASK] slots before the vocab projection
        ([B, epilogue_slots, V] out instead of [B, S, V]); squad stacks
        start/end into one output. ``epilogue_slots`` is the per-row
        gather quota; a batch whose rows need more falls back to that
        spec's unfused forward (both are AOT-warmed).

        ``autotune`` drives the measured Pallas block-geometry pass
        (ops/pallas/autotune.py) for the ``pallas_infer*`` backends:
        ``"load"`` reads persisted winners from ``autotune_cache``,
        ``"measure"`` additionally times candidates for any
        (bucket, batch*heads) shape without one and persists the
        result. Runs in ``__init__`` — BEFORE the forwards are built —
        because geometry is read at trace time and the winner digest is
        folded into the stable forward names (a warm restart that loads
        the same winners file compiles the same programs under the same
        names, keeping ``compiles_cold == 0``)."""
        import jax.numpy as jnp

        from bert_pytorch_tpu.ops import quant as quant_ops

        self.quantize = quant_ops.check_mode(
            None if quantize in (None, "none") else quantize)
        self.attention_backend = attention_backend
        self.fuse_epilogues = bool(fuse_epilogues)
        self.epilogue_slots = int(epilogue_slots)
        if self.fuse_epilogues and self.epilogue_slots < 1:
            raise ValueError(
                f"epilogue_slots must be >= 1, got {epilogue_slots}")
        if autotune not in ("off", "load", "measure"):
            raise ValueError(
                f"autotune must be off|load|measure, got {autotune!r}")
        if autotune != "off" and not autotune_cache:
            # Silently degrading to the heuristic would defeat the one
            # guarantee the flag exists for (winners persisted -> warm
            # restart compiles nothing new); a forgotten --autotune_cache
            # must fail at construction, not at the next restart.
            raise ValueError(
                f"autotune={autotune!r} requires autotune_cache (the "
                "winners JSON path next to the AOT compile cache)")
        if autotune != "off" and attention_backend not in (
                "pallas_infer", "pallas_infer_int8"):
            # Same fail-loud policy for the backend pairing: only the
            # Pallas inference kernels have geometry to tune — silently
            # no-opping under xla would let an operator believe measured
            # autotune is active when nothing was tuned.
            raise ValueError(
                f"autotune={autotune!r} tunes the Pallas inference "
                f"kernels; attention_backend={attention_backend!r} has "
                "no geometry to tune (use pallas_infer or "
                "pallas_infer_int8)")
        self.autotune = autotune
        self.autotune_cache = autotune_cache
        self.startup: Optional[dict] = None
        self.config = config
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not self.buckets or self.buckets[0] < 8:
            raise ValueError(f"buckets must be >= 8, got {buckets}")
        if max(self.buckets) > config.max_position_embeddings:
            raise ValueError(
                f"largest bucket {max(self.buckets)} exceeds "
                f"max_position_embeddings {config.max_position_embeddings}")
        self.max_batch_size = int(max_batch_size)
        self.max_requests_per_pack = max(1, int(max_requests_per_pack))
        self.pack = self.max_requests_per_pack > 1
        self.dtype = dtype if dtype is not None else jnp.float32
        self._clock = clock
        self.monitor = monitor or CompileMonitor(emit=lambda rec: None)
        self._setup_autotune()
        # Hot-swap state (docs/serving.md "Model registry & canary
        # rollouts"): _swap_lock makes (spec.params, serving_version,
        # _swap_epoch) flip as ONE atomic unit — the executor captures
        # all three in a single acquisition, so an in-flight batch
        # always runs against exactly one consistent version, and the
        # epoch check counts any params change that bypassed the flip
        # into _torn_serves (the zero-tolerance report gate).
        self._swap_lock = threading.Lock()
        self.serving_version = str(version)
        self._swap_epoch = 0
        self._swaps = 0
        self._torn_serves = 0
        self._swap_inflight = False
        handlers = tasks_lib.build_handlers(tokenizer, tasks)
        self.tasks: Dict[str, TaskSpec] = {}
        # Per-task (options, seed) as __init__ built them: swap_params
        # re-creates the SAME fp32 init template (the streaming-decode
        # load target) for the incoming checkpoint.
        self._task_build: Dict[str, Tuple[dict, int]] = {}
        for name, options in tasks.items():
            options = options or {}
            task_seed = seed + len(self.tasks)
            model, params = self._build_task(name, options, seed=task_seed)
            spec = TaskSpec(name, model, params, handlers[name])
            self._build_forwards(spec)
            self.tasks[name] = spec
            self._task_build[name] = (dict(options), task_seed)
        self.warmed = False

    # -- construction ----------------------------------------------------

    def _autotune_kernel(self) -> Optional[str]:
        """The autotune registry kernel this engine's forwards trace, or
        None when the backend has no Pallas geometry to tune."""
        return {"pallas_infer": "infer",
                "pallas_infer_int8": "infer_int8"}.get(
                    self.attention_backend)

    def _setup_autotune(self) -> None:
        """Load (and, in ``"measure"`` mode, fill) the Pallas geometry
        winners BEFORE any forward is built: geometry is read at trace
        time, and the winner digest rides the stable forward names.
        One ``kind="autotune"`` record per (bucket, bh) says where that
        shape's geometry came from — measured now, loaded from the
        cache, or the heuristic fallback."""
        if self.autotune == "off":
            return
        from bert_pytorch_tpu.ops.pallas import autotune as autotune_lib

        kernel = self._autotune_kernel()
        if kernel is None:
            return  # xla/pallas backends have no infer geometry to tune
        autotune_lib.load_winners(self.autotune_cache)
        bh = self.max_batch_size * self.config.num_attention_heads
        measured = 0
        for bucket in self.buckets:
            geom = autotune_lib.lookup(kernel, bucket, bh)
            record = {"kind": "autotune", "tag": "telemetry",
                      "kernel": kernel, "seq": bucket, "bh": bh}
            if geom is not None:
                record["source"] = "cached"
                record["winner"] = {"block_q": geom[0], "block_k": geom[1],
                                    "bh_block": geom[2]}
            elif self.autotune == "measure":
                t0 = self._clock()
                result = autotune_lib.measure(
                    kernel, bucket, bh, self.config.head_dim,
                    dtype=self.dtype)
                measured += 1
                record.update(source="measured", winner=result["winner"],
                              candidates=result["candidates"],
                              measure_s=round(self._clock() - t0, 3))
            else:
                record["source"] = "heuristic"
            self.monitor.note(record)
        if measured:
            autotune_lib.save_winners(self.autotune_cache)

    def _build_task(self, name: str, options: dict, seed: int):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        from bert_pytorch_tpu import models
        from bert_pytorch_tpu.ops import quant as quant_ops

        cfg = self.config

        def build(quant):
            kwargs = dict(dtype=self.dtype, quant=quant,
                          attention_backend=self.attention_backend)
            if name == "fill_mask":
                return models.BertForMaskedLM(cfg, **kwargs)
            if name == "classify":
                labels = options.get("labels") or ["0", "1"]
                return models.BertForSequenceClassification(
                    cfg, num_labels=len(labels), **kwargs)
            if name == "squad":
                return models.BertForQuestionAnswering(cfg, **kwargs)
            if name == "ner":
                labels = options.get("labels") or ["O"]
                # +1: label ids start at 1, id 0 is reserved (run_ner.py).
                return models.BertForTokenClassification(
                    cfg, num_labels=len(labels) + 1, **kwargs)
            raise ValueError(f"unknown serve task {name!r}")

        # The fp32-layout model is always built: its init provides the
        # load TARGET (and demo-mode weights); the quant model reuses the
        # module tree with quantized param storage for apply().
        model = build(None)
        sample = (jnp.zeros((1, self.buckets[0]), jnp.int32),) * 3
        params = nn.unbox(
            model.init(jax.random.PRNGKey(seed), *sample))["params"]
        checkpoint = options.get("checkpoint")
        if checkpoint:
            # Quantization happens INSIDE the streaming decode — each
            # tensor converts as its bytes arrive; the fp32 tree never
            # materializes on the serving host (utils/checkpoint.py).
            params = ckpt_util.load_params_only(
                checkpoint, params, quantize=self.quantize)
        elif self.quantize:
            params = quant_ops.quantize_params(params, self.quantize)
        if self.quantize:
            model = build(self.quantize)
        return model, params

    def _name_suffix(self, bucket: int) -> str:
        """The autotune-winner digest suffix for this bucket's forward
        names (``_g<digest>``), or "" when no winner is cached.

        The persistent compile cache keys on the fn-name-derived HLO
        module name, so WITHOUT the suffix a new measured geometry
        would compile a different program under the SAME name —
        aliasing two executables to one cache identity, where a stale
        entry for the old geometry could be served against the new
        one's name. With it, a geometry change invalidates exactly its
        own entry; no winner means the deterministic heuristic, whose
        program the plain name already identifies."""
        kernel = self._autotune_kernel()
        if kernel is None:
            return ""
        from bert_pytorch_tpu.ops.pallas import autotune as autotune_lib

        digest = autotune_lib.name_digest(
            kernel, bucket,
            self.max_batch_size * self.config.num_attention_heads)
        return f"_g{digest}" if digest else ""

    def _build_forwards(self, spec: TaskSpec) -> None:
        """One jitted forward per (bucket, packedness, fused-epilogue),
        each named ``serve_<task>_b<bucket>[_packed][_fused]_<quant>``
        (+ the autotune-winner digest, :meth:`_name_suffix`).

        The name is load-bearing twice over: the persistent compile
        cache keys on the HLO module name, which jax derives from the
        Python function name — the old closures were ALL literally named
        ``forward``, so a restarted replica's cache keys depended on
        nothing but shapes (collision-prone across specs) and every
        CompileMonitor event attributed to one ambiguous ``fn``. Stable
        per-spec names make the warm-start cache hit deterministic
        across process restarts (the cold-start acceptance:
        second start => zero cold compiles) and compile telemetry
        attributable per (task, bucket, packed, quant, epilogue,
        geometry).

        Fused-epilogue engines (docs/serving.md "Raw-speed kernels"):
        a ``"gather"`` head (fill_mask) gets BOTH variants per
        (bucket, packed) — the fused forward takes a [B, epilogue_slots]
        positions argument and emits the gathered [B, P, V] logits; the
        unfused twin stays as the slot-overflow fallback. A
        ``"stack_span"`` head (squad) gets only the fused variant (the
        stack always applies). Heads with nothing to fuse compile the
        exact same program (and name) as an unfused engine, so they
        share its persistent-cache entries.
        """
        import jax

        model = spec.model
        pooled = spec.handler.output_kind == "pooled"
        epilogue = spec.handler.epilogue if self.fuse_epilogues else None
        qtag = self.quantize or "fp32"
        for bucket in self.buckets:
            for packed in ((False, True) if self.pack else (False,)):
                variants = []  # (fused, closure)
                if not packed:
                    def base(params, input_ids, segment_ids, input_mask):
                        return model.apply(
                            {"params": params}, input_ids, segment_ids,
                            input_mask)
                elif pooled:
                    def base(params, input_ids, segment_ids, input_mask,
                             sequence_ids, cls_positions):
                        return model.apply(
                            {"params": params}, input_ids, segment_ids,
                            input_mask, True, sequence_ids, cls_positions)
                else:
                    def base(params, input_ids, segment_ids, input_mask,
                             sequence_ids):
                        return model.apply(
                            {"params": params}, input_ids, segment_ids,
                            input_mask, True, sequence_ids)
                if epilogue == "gather":
                    if not packed:
                        def fused(params, input_ids, segment_ids,
                                  input_mask, positions):
                            return model.apply(
                                {"params": params}, input_ids,
                                segment_ids, input_mask, True, None,
                                positions)
                    else:
                        def fused(params, input_ids, segment_ids,
                                  input_mask, sequence_ids, positions):
                            return model.apply(
                                {"params": params}, input_ids,
                                segment_ids, input_mask, True,
                                sequence_ids, positions)
                    variants = [(False, base), (True, fused)]
                elif epilogue == "stack_span":
                    import jax.numpy as jnp

                    def fused(*args, _base=base):
                        start, end = _base(*args)
                        # One [B, 2, S] output: a single D2H transfer
                        # (and one host conversion in demux) instead of
                        # two — XLA fuses the stack into the epilogue.
                        return jnp.stack([start, end], axis=1)
                    variants = [(True, fused)]
                else:
                    variants = [(False, base)]
                for is_fused, fwd in variants:
                    name = (f"serve_{spec.name}_b{bucket}"
                            f"{'_packed' if packed else ''}"
                            f"{'_fused' if is_fused else ''}_{qtag}"
                            f"{self._name_suffix(bucket)}")
                    fwd.__name__ = name
                    fwd.__qualname__ = name
                    spec.forwards[(bucket, packed, is_fused)] = \
                        self.monitor.instrument(jax.jit(fwd), name)

    def warmup(self) -> int:
        """AOT-compile every (task, bucket[, packed]) forward the serving
        loop can dispatch; returns the number of compile events observed.
        After this, steady-state traffic never compiles — the acceptance
        the smoke test asserts via the CompileMonitor.

        Also records :attr:`startup` — ``cold_start_s`` plus compile
        counts split warm/cold from the persistent-cache COUNTER events
        (``cache`` = hit vs miss/uncached; the authority per
        telemetry/compile_events.py — wall clock proves nothing), so a
        restarted replica can assert it recompiled nothing.
        """
        import jax

        from bert_pytorch_tpu.ops import quant as quant_ops

        t0 = self._clock()
        before = len(self.monitor.events)
        zeros = {}
        pos_zeros = np.zeros((self.max_batch_size, self.epilogue_slots),
                             np.int32)
        for bucket in self.buckets:
            B, S, K = (self.max_batch_size, bucket,
                       self.max_requests_per_pack)
            zeros[bucket] = (
                np.zeros((B, S), np.int32), np.zeros((B, S), np.int32),
                np.zeros((B, S), np.int32), np.zeros((B, S), np.int32),
                np.zeros((B, K), np.int32))
        for spec in self.tasks.values():
            pooled = spec.handler.output_kind == "pooled"
            gathered = spec.handler.epilogue == "gather"
            for (bucket, packed, fused), fwd in spec.forwards.items():
                ids, seg, mask, sids, cpos = zeros[bucket]
                if fused and gathered:
                    args = ((ids, seg, mask, pos_zeros) if not packed
                            else (ids, seg, mask, sids, pos_zeros))
                elif not packed:
                    args = (ids, seg, mask)
                elif pooled:
                    args = (ids, seg, mask, sids, cpos)
                else:
                    args = (ids, seg, mask, sids)
                out = fwd(spec.params, *args)
                jax.block_until_ready(out)
        compile_events = [e for e in self.monitor.events[before:]
                          if e.get("kind") == "compile"]
        self.startup = {
            "cold_start_s": round(self._clock() - t0, 3),
            "compiles": len(compile_events),
            "compiles_cold": sum(1 for e in compile_events
                                 if e.get("cache") in ("miss", "uncached")),
            "compiles_warm": sum(1 for e in compile_events
                                 if e.get("cache") == "hit"),
            "quantize": self.quantize or "none",
            "attention_backend": self.attention_backend,
            "fuse_epilogues": self.fuse_epilogues,
            "autotune": self.autotune,
            "weight_bytes": sum(quant_ops.weight_bytes(s.params)
                                for s in self.tasks.values()),
        }
        self.warmed = True
        return len(self.monitor.events) - before

    # -- hot swap (docs/serving.md "Model registry & canary rollouts") ---

    def version(self) -> str:
        """The serving model version (stamped atomically with the params
        flip — what /healthz, /statsz, and /metricsz report)."""
        with self._swap_lock:
            return self.serving_version

    def swap_stats(self) -> dict:
        """Swap counters for /statsz: the serving version, completed
        swaps, and torn serves (forwards whose params reference changed
        without the epoch-bumping flip — structurally 0; the
        zero-tolerance "rollout torn-model serves" gate reads it)."""
        with self._swap_lock:
            return {"version": self.serving_version,
                    "swaps": self._swaps,
                    "torn_serves": self._torn_serves}

    def swap_params(self, task: str, checkpoint: str, version: str,
                    emit: Optional[Callable[[dict], None]] = None) -> dict:
        """Hot-swap one task's params to ``checkpoint``, stamping the
        engine as serving ``version``. Raises :class:`SwapBusy` when a
        swap is already in flight (serve/http.py maps it to 409).

        The load runs OFF the dispatch path: the new params stream
        through the same quantize-at-decode path as startup (the fp32
        tree never materializes), built against a fresh init template
        from the task's original (options, seed) — so geometry, dtype,
        and quant layout match the forwards exactly. Because the jitted
        forwards key the persistent compile cache on their STABLE names
        and the staged shapes are unchanged, a same-geometry swap hits
        the already-compiled executables: zero compiles, cold or warm
        (the info dict proves it from the CompileMonitor's counter
        events, never wall clock).

        The flip itself is one lock acquisition that replaces the params
        reference, the version stamp, and the swap epoch together; an
        in-flight batch that captured the old reference keeps executing
        the old version to completion — there is no intermediate state
        to serve from."""
        spec = self.tasks.get(task)
        if spec is None:
            raise ValueError(
                f"unknown task {task!r} (serving: {sorted(self.tasks)})")
        if not checkpoint or not os.path.isfile(checkpoint):
            raise FileNotFoundError(f"swap checkpoint missing: "
                                    f"{checkpoint!r}")
        with self._swap_lock:
            if self._swap_inflight:
                raise SwapBusy(
                    "a hot-swap is already in flight; retry after it "
                    "completes")
            self._swap_inflight = True
            swap_attempt = self._swaps + 1
        try:
            options, seed = self._task_build[task]
            compiles_before = len(self.monitor.events)
            t0 = self._clock()
            _, new_params = self._build_task(
                task, dict(options, checkpoint=checkpoint), seed=seed)
            load_s = self._clock() - t0
            # Chaos hook: hold the swap window open between load and
            # flip (testing/faults.py swap_hold) — a SIGKILL landing
            # here proves in-flight batches only ever saw the OLD
            # consistent version.
            faults.get_plan().serve_swap_check(swap_attempt, emit=emit)
            with self._swap_lock:
                from_version = self.serving_version
                spec.params = new_params
                self.serving_version = str(version)
                self._swap_epoch += 1
                self._swaps += 1
        finally:
            with self._swap_lock:
                self._swap_inflight = False
        compile_events = [e for e in self.monitor.events[compiles_before:]
                          if e.get("kind") == "compile"]
        return {
            "task": task,
            "version": str(version),
            "from_version": from_version,
            "checkpoint": checkpoint,
            "load_s": round(load_s, 3),
            "compiles": len(compile_events),
            "compiles_cold": sum(1 for e in compile_events
                                 if e.get("cache") in ("miss", "uncached")),
            "compiles_warm": sum(1 for e in compile_events
                                 if e.get("cache") == "hit"),
        }

    # -- planning --------------------------------------------------------

    def select_bucket(self, length: int) -> int:
        """Smallest bucket that fits ``length``; the largest bucket for
        over-long requests (prepare() already truncated to it)."""
        for bucket in self.buckets:
            if length <= bucket:
                return bucket
        return self.buckets[-1]

    def max_len(self) -> int:
        return self.buckets[-1]

    def plan_batch(self, requests: List[Request],
                   packed: Optional[bool] = None) -> BatchPlan:
        """Assign a flushed request group to rows of the smallest workable
        bucket. Unpacked: one request per row, first ``max_batch_size``
        requests, bucket = smallest fitting the longest. Packed: the
        smallest bucket whose FFD packing needs <= ``max_batch_size``
        rows; requests falling outside the first ``max_batch_size`` rows
        are leftover for the batcher to requeue."""
        if packed is None:
            packed = self.pack
        if not requests:
            raise ValueError("plan_batch needs at least one request")
        if not packed:
            take = requests[: self.max_batch_size]
            leftover = requests[self.max_batch_size:]
            bucket = self.select_bucket(max(r.length for r in take))
            return BatchPlan(bucket, [[r] for r in take], leftover, False)

        lengths = [r.length for r in requests]
        # Budget-greedy bucket choice: every dispatch costs a FULL
        # (max_batch_size x bucket) token budget regardless of fill, so
        # the right bucket minimizes total dispatched budget INCLUDING
        # the extra dispatches a smaller bucket forces (ties -> smaller
        # bucket, which also means lower per-dispatch latency). A
        # smallest-that-fits-one-dispatch rule would pick a half-empty
        # large bucket over two dense small ones.
        chosen_bucket, chosen_packs, best_budget = None, None, None
        for bucket in self.buckets:
            if max(lengths) > bucket:
                continue
            packs = first_fit_decreasing(
                lengths, bucket, self.max_requests_per_pack)
            dispatches = -(-len(packs) // self.max_batch_size)
            budget = dispatches * self.max_batch_size * bucket
            if best_budget is None or budget < best_budget:
                chosen_bucket, chosen_packs, best_budget = (
                    bucket, packs, budget)
        if chosen_packs is None:  # nothing fits: largest bucket, truncate
            chosen_bucket = self.buckets[-1]
            chosen_packs = first_fit_decreasing(
                lengths, chosen_bucket, self.max_requests_per_pack)
        rows = [[requests[i] for i in pack]
                for pack in chosen_packs[: self.max_batch_size]]
        leftover_idx = sorted(
            i for pack in chosen_packs[self.max_batch_size:] for i in pack)
        return BatchPlan(chosen_bucket, rows,
                         [requests[i] for i in leftover_idx], True)

    # -- execution -------------------------------------------------------

    def stage(self, task: str, plan: BatchPlan) -> StagedBatch:
        """Pack/pad one planned batch into its fixed compile-shape
        arrays. HOST-ONLY — never touches the device, so the pipelined
        dispatch plane's assembler stage can run it concurrently with
        the executor's jitted forward (the one-device-thread
        invariant).

        Fused-epilogue engines additionally stage the per-row gather
        positions for ``"gather"`` heads ([B, epilogue_slots] absolute
        row positions, zero-padded — slot 0 gathers position 0
        harmlessly for unused slots); a batch whose rows overflow the
        slot quota stages for the unfused fallback forward instead."""
        spec = self.tasks[task]
        t_host0 = self._clock()
        B, S = self.max_batch_size, plan.bucket
        ids = np.zeros((B, S), np.int32)
        seg = np.zeros((B, S), np.int32)
        mask = np.zeros((B, S), np.int32)
        offsets: Dict[int, Tuple[int, int, int]] = {}  # req id -> (row, off, slot)
        epilogue = spec.handler.epilogue if self.fuse_epilogues else None
        fused = epilogue == "stack_span"
        gather_slots: Dict[int, Tuple[int, int, int]] = {}
        row_positions: List[List[int]] = []
        if epilogue == "gather":
            # First pass (features only): do the rows fit the quota?
            fused = True
            for row in plan.rows:
                positions: List[int] = []
                offset = 0
                for req in row:
                    pts = spec.handler.gather_positions(req.features)
                    gather_slots[req.id] = (len(row_positions),
                                            len(positions), len(pts))
                    positions.extend(offset + p for p in pts)
                    offset += req.length if plan.packed else 0
                if len(positions) > self.epilogue_slots:
                    fused = False
                    gather_slots = {}
                    break
                row_positions.append(positions)
        if plan.packed:
            K = self.max_requests_per_pack
            sids = np.zeros((B, S), np.int32)
            cpos = np.zeros((B, K), np.int32)
            for r, row in enumerate(plan.rows):
                offset = 0
                for k, req in enumerate(row):
                    n = req.length
                    ids[r, offset:offset + n] = req.features["input_ids"]
                    seg[r, offset:offset + n] = req.features["segment_ids"]
                    mask[r, offset:offset + n] = 1
                    sids[r, offset:offset + n] = k + 1
                    cpos[r, k] = offset
                    offsets[req.id] = (r, offset, k)
                    offset += n
            if spec.handler.output_kind == "pooled":
                args = (ids, seg, mask, sids, cpos)
            else:
                args = (ids, seg, mask, sids)
        else:
            for r, row in enumerate(plan.rows):
                (req,) = row
                n = req.length
                ids[r, :n] = req.features["input_ids"]
                seg[r, :n] = req.features["segment_ids"]
                mask[r, :n] = 1
                offsets[req.id] = (r, 0, 0)
            args = (ids, seg, mask)
        if fused and epilogue == "gather":
            pos = np.zeros((B, self.epilogue_slots), np.int32)
            for r, positions in enumerate(row_positions):
                pos[r, :len(positions)] = positions
            args = args + (pos,)
        return StagedBatch(task, plan, args, offsets,
                           pack_s=self._clock() - t_host0,
                           fused=fused, gather_slots=gather_slots)

    def execute_staged(self, staged: StagedBatch
                       ) -> Tuple[object, dict]:
        """Run one staged batch's jitted forward (incl. the device
        sync); returns (device output, info dict). The ONLY method on
        the serving path that touches the device — in pipelined
        dispatch, only the executor stage calls it."""
        import jax

        spec = self.tasks[staged.task]
        plan = staged.plan
        compiles_before = len(self.monitor.events)
        t0 = self._clock()
        fwd = spec.forwards[(plan.bucket, plan.packed, staged.fused)]
        # Capture the params reference, its swap epoch, and the version
        # stamp in ONE lock acquisition: the whole forward runs against
        # this single consistent tree no matter when a hot-swap flips
        # the spec (docs/serving.md "Model registry & canary rollouts").
        with self._swap_lock:
            params = spec.params
            epoch = self._swap_epoch
            version = self.serving_version
        out = fwd(params, *staged.args)
        out = jax.block_until_ready(out)
        # Flip-atomicity audit: the params reference may only change
        # through the epoch-bumping swap. A changed reference at an
        # UNCHANGED epoch means something mutated params outside the
        # flip while this batch ran — counted as a torn serve (the
        # zero-tolerance "rollout torn-model serves" gate).
        with self._swap_lock:
            if spec.params is not params and self._swap_epoch == epoch:
                self._torn_serves += 1
        device_s = self._clock() - t0
        compiles = sum(
            1 for e in self.monitor.events[compiles_before:]
            if e.get("kind") == "compile")
        info = {
            "bucket": plan.bucket,
            "rows": self.max_batch_size,
            "real_tokens": sum(r.length for r in plan.requests),
            "device_s": device_s,
            "pack_s": staged.pack_s,
            "compiles": compiles,
            "packed": plan.packed,
            "fused": staged.fused,
            "version": version,
        }
        return out, info

    def demux(self, staged: StagedBatch, out) -> List[object]:
        """Slice each request's own output back out of the batch output
        (host conversion + per-request views, in ``plan.requests``
        order). Host-only — the completion stage runs it, so client
        decode never blocks the next device step.

        Fused-epilogue batches consume the ALREADY-EXTRACTED outputs
        (docs/serving.md "Raw-speed kernels"): a ``"gather"`` head's
        [B, epilogue_slots, V] plane slices to each request's own slot
        run (handed to postprocess as a
        :class:`~bert_pytorch_tpu.serve.tasks.GatheredTokens`), and a
        ``"stack_span"`` head's single [B, 2, S] output re-splits into
        the usual (start, end) tuple — one host conversion instead of
        two."""
        spec = self.tasks[staged.task]
        plan = staged.plan
        kind = spec.handler.output_kind
        if kind == "span":
            if staged.fused:
                both = np.asarray(out, np.float32)  # [B, 2, S]
                start, end = both[:, 0], both[:, 1]
            else:
                start = np.asarray(out[0], np.float32)
                end = np.asarray(out[1], np.float32)
        else:
            host = np.asarray(out, np.float32)
        gathered = staged.fused and spec.handler.epilogue == "gather"
        results: List[object] = []
        for req in plan.requests:
            r, off, slot = staged.offsets[req.id]
            n = req.length
            if kind == "pooled":
                results.append(host[r, slot] if plan.packed else host[r])
            elif kind == "span":
                results.append((start[r, off:off + n], end[r, off:off + n]))
            elif gathered:
                gr, s0, count = staged.gather_slots[req.id]
                results.append(
                    tasks_lib.GatheredTokens(host[gr, s0:s0 + count]))
            else:
                results.append(host[r, off:off + n])
        return results

    def execute(self, task: str, plan: BatchPlan
                ) -> Tuple[List[object], dict]:
        """Run one planned batch end to end (stage -> execute_staged ->
        demux on the calling thread); returns (per-request output slices
        in ``plan.requests`` order, info dict with bucket/rows/
        real_tokens/device_s/compiles, plus ``pack_s`` — the host time
        spent packing the group into the fixed compile shape, the
        engine's share of the trace's ``assembly`` span). The serial
        dispatch mode, offline scoring, and parity tests use this
        composition; pipelined dispatch calls the three steps from
        their own stages."""
        staged = self.stage(task, plan)
        out, info = self.execute_staged(staged)
        return self.demux(staged, out), info

    def run_direct(self, task: str, payload: dict) -> dict:
        """One request end to end through the SAME batched path (a batch
        of one) — the offline/batch-scoring and parity-test entry point."""
        spec = self.tasks[task]
        features = spec.handler.prepare(payload, self.max_len())
        req = Request(task, features, payload)
        plan = self.plan_batch([req], packed=False)
        outputs, _ = self.execute(task, plan)
        return spec.handler.postprocess(features, outputs[0], payload)
