"""Stdlib JSON-over-HTTP front end for the serving engine
(docs/serving.md).

``ThreadingHTTPServer`` gives one OS thread per in-flight connection —
each worker blocks in :meth:`ServingService.submit` while the single
dispatch thread batches across all of them, which is exactly the
concurrency shape dynamic micro-batching wants. No framework, no new
dependency: the repo's hard constraint is stdlib-only for the server.

Routes:

* ``POST /v1/<task>``  — task in {fill_mask, classify, squad, ner}
  (whichever the engine was configured with); JSON body is the task
  payload (serve/tasks.py docstrings); 200 with the result JSON,
  400 on bad payloads, 404 on unknown tasks, 503 on
  timeout/overload/draining;
* ``GET  /healthz``    — DISPATCH-THREAD liveness + drain state
  (docs/fault_tolerance.md): 200 only while the thread that actually
  serves results is alive and accepting; 503 when draining for
  shutdown or when dispatch died (an HTTP thread answering proves
  nothing about the serving path) — load balancers stop routing on
  the first failed probe;
* ``GET  /statsz``     — the live ServeTelemetry rollup (requests,
  latency percentiles, batch occupancy, compile count; with tracing
  enabled, the ``phases`` sub-object carries the run-level queue-wait
  share and per-phase p95s; with a capture controller attached, the
  ``profile`` sub-object carries the live capture phase / last window);
* ``POST /profilez``   — arm an on-demand profiling capture
  (docs/observability.md "Profiling plane"): the dispatch plane starts
  a bounded host-thread-sampler + ``jax.profiler`` window at the next
  boundary and emits a ``profile_window`` record when it expires. JSON
  body (all optional): ``duration_s``, ``sample_interval_s``,
  ``max_samples``, ``top_k``, ``trigger``. 200 with the armed
  parameters, 409 while a capture is already armed or active (traces
  cannot nest), 404 when the service was built without a controller;
* ``GET  /metricsz``   — Prometheus text exposition (serve/tracing.py):
  per-task request/error/over-SLO counters, per-(task, phase) latency
  histograms, queue depth / occupancy / cold-start gauges — the scrape
  surface the router and standard collectors consume. 404 when the
  service was built without a tracer;
* ``POST /swapz``      — hot-swap one task's params to a new model
  version (docs/serving.md "Model registry & canary rollouts"). JSON
  body: ``task``, ``checkpoint`` (path the replica can read),
  ``version`` (the registry version name). The load runs on this
  control thread off the dispatch path; the flip is atomic, so
  in-flight batches finish on the old version. 200 with the swap info
  (load_s + the compile split proving a same-geometry swap recompiled
  nothing), 409 while another swap is in flight (loads cannot
  overlap), 404 on an unknown task, 400 on a missing checkpoint.
"""

from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from bert_pytorch_tpu.serve.batcher import BatcherFull
from bert_pytorch_tpu.serve.engine import SwapBusy
from bert_pytorch_tpu.serve.service import ServiceDraining, ServingService
from bert_pytorch_tpu.serve.tracing import (TRACE_HEADER,
                                            TRACE_ID_RESPONSE_HEADER,
                                            parse_trace_header)

MAX_BODY_BYTES = 1 << 20  # 1 MiB: plenty for text payloads, bounds abuse


class ServeHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    # socketserver's default listen backlog is 5 — a concurrent connect
    # burst (the router fanning out, a probe storm) overflows it and the
    # kernel RSTs the excess mid-handshake, surfacing as client-side
    # ConnectionResetError before the service ever sees the request.
    request_queue_size = 128
    # The service rides on the server object so handler instances (one per
    # request) can reach it without globals.
    service: ServingService = None
    request_timeout_s: float = 30.0


def _make_handler():
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet; telemetry is the log
            pass

        def _reply(self, code: int, payload: dict,
                   headers: dict = None) -> None:
            self._reply_text(code, json.dumps(payload), "application/json",
                             headers)

        def _reply_text(self, code: int, text: str, content_type: str,
                        headers: dict = None) -> None:
            body = text.encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for name, value in (headers or {}).items():
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            service = self.server.service
            if self.path == "/healthz":
                health = service.health()
                health.update({
                    "tasks": sorted(service.engine.tasks),
                    "buckets": list(service.engine.buckets),
                    "warmed": service.engine.warmed,
                })
                self._reply(200 if health["status"] == "ok" else 503,
                            health)
            elif self.path == "/statsz":
                snap = service.telemetry.snapshot()
                if service.capture is not None:
                    snap["profile"] = service.capture.status()
                swap_stats = getattr(service.engine, "swap_stats", None)
                if callable(swap_stats):
                    # serving version + swap/torn counters (the rollout
                    # controller and chaos harness scrape these).
                    snap.update(swap_stats())
                self._reply(200, snap)
            elif self.path == "/metricsz":
                text = service.metrics_text()
                if text is None:
                    self._reply(404, {
                        "error": "metrics export disabled: the service "
                                 "has no tracer (--trace_sample_rate / "
                                 "serve/tracing.py)"})
                else:
                    # The Prometheus text-exposition content type
                    # (version 0.0.4 — the format every scraper speaks).
                    self._reply_text(
                        200, text,
                        "text/plain; version=0.0.4; charset=utf-8")
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            service = self.server.service
            # Inbound router trace context (docs/observability.md "Trace
            # propagation"): adopted by the tracer so fleet-wide sampling
            # is consistent, and ECHOED on every response — sampled or
            # not — so clients correlate without relying on sampling.
            ctx = parse_trace_header(self.headers.get(TRACE_HEADER))
            echo = ({TRACE_ID_RESPONSE_HEADER: ctx["trace_id"]}
                    if ctx else None)
            if self.path.rstrip("/") == "/profilez":
                self._profilez(service, echo)
                return
            if self.path.rstrip("/") == "/swapz":
                self._swapz(service, echo)
                return
            if not self.path.startswith("/v1/"):
                self._reply(404, {"error": f"no route {self.path}"}, echo)
                return
            task = self.path[len("/v1/"):].strip("/")
            try:
                length = int(self.headers.get("Content-Length", 0))
                if length > MAX_BODY_BYTES:
                    self._reply(413, {"error": "payload too large"}, echo)
                    return
                payload = json.loads(
                    self.rfile.read(length).decode("utf-8") or "{}")
                if not isinstance(payload, dict):
                    raise ValueError("payload must be a JSON object")
            except ValueError as exc:
                self._reply(400, {"error": f"bad JSON payload: {exc}"},
                            echo)
                return
            try:
                result = service.submit(
                    task, payload, timeout=self.server.request_timeout_s,
                    trace_ctx=ctx)
            except ValueError as exc:
                code = 404 if "unknown task" in str(exc) else 400
                self._reply(code, {"error": str(exc)}, echo)
            except KeyError as exc:
                self._reply(400, {"error": f"missing payload field {exc}"},
                            echo)
            except (TimeoutError, BatcherFull, ServiceDraining) as exc:
                self._reply(503, {"error": str(exc)}, echo)
            except Exception as exc:
                self._reply(500, {"error": f"{type(exc).__name__}: {exc}"},
                            echo)
            else:
                self._reply(200, result, echo)

        def _swapz(self, service, echo) -> None:
            """Hot-swap control endpoint. The checkpoint load runs on
            THIS thread (one per request — the dispatch plane never
            blocks on it); 409 while another swap is in flight, the
            same no-overlap discipline as /profilez."""
            try:
                length = int(self.headers.get("Content-Length", 0))
                if length > MAX_BODY_BYTES:
                    self._reply(413, {"error": "payload too large"}, echo)
                    return
                body = json.loads(
                    self.rfile.read(length).decode("utf-8") or "{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
                missing = [k for k in ("task", "checkpoint", "version")
                           if not body.get(k)]
                if missing:
                    raise ValueError(f"missing fields {missing}")
            except ValueError as exc:
                self._reply(400, {"error": f"bad swap request: {exc}"},
                            echo)
                return
            try:
                info = service.swap(str(body["task"]),
                                    str(body["checkpoint"]),
                                    str(body["version"]))
            except SwapBusy as exc:
                self._reply(409, {"error": str(exc)}, echo)
            except ValueError as exc:
                code = 404 if "unknown task" in str(exc) else 400
                self._reply(code, {"error": str(exc)}, echo)
            except FileNotFoundError as exc:
                self._reply(400, {"error": str(exc)}, echo)
            except Exception as exc:
                self._reply(500, {"error": f"{type(exc).__name__}: {exc}"},
                            echo)
            else:
                self._reply(200, dict(info, ok=True), echo)

        def _profilez(self, service, echo) -> None:
            """Arm an on-demand capture. 409 — not a second start — when
            one is already armed/active: ``jax.profiler`` traces cannot
            nest, and the controller's refusal is what keeps two POSTs
            from stacking two ``start_trace`` calls."""
            if service.capture is None:
                self._reply(404, {
                    "error": "profiling disabled: the service has no "
                             "capture controller"}, echo)
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                if length > MAX_BODY_BYTES:
                    self._reply(413, {"error": "payload too large"}, echo)
                    return
                body = json.loads(
                    self.rfile.read(length).decode("utf-8") or "{}")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
            except ValueError as exc:
                self._reply(400, {"error": f"bad JSON payload: {exc}"},
                            echo)
                return
            kwargs = {k: body[k] for k in (
                "duration_s", "sample_interval_s", "max_samples",
                "top_k", "trigger") if k in body}
            ok, payload = service.capture.arm(**kwargs)
            # Busy (the payload names the blocking phase) is 409; a
            # refused parameter is the caller's fault, 400.
            code = 200 if ok else (409 if "phase" in payload else 400)
            self._reply(code, payload, echo)

    return Handler


def make_server(service: ServingService, host: str = "127.0.0.1",
                port: int = 8000,
                request_timeout_s: float = 30.0) -> ServeHTTPServer:
    """Build (but do not start) the HTTP server; ``port=0`` binds an
    ephemeral port (tests read ``server.server_address``)."""
    server = ServeHTTPServer((host, port), _make_handler())
    server.service = service
    server.request_timeout_s = request_timeout_s
    return server
