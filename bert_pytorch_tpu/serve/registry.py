"""Versioned on-disk model registry (docs/serving.md "Model registry &
canary rollouts").

The deployment plane's source of truth: every model version the fleet
can serve is one directory under the registry root holding a single
manifest written tmp+rename — the same torn-write discipline as
checkpoints (utils/checkpoint.py), so a publisher SIGKILLed mid-write
leaves either the previous manifest or none, never half of one::

    <root>/<version>/manifest.json
        {"schema": "model-registry-v1", "version": "vB",
         "task": "classify", "checkpoint": "/abs/ckpt.msgpack",
         "sha256": "...", "size_bytes": N, "quantize": "none",
         "geometry": {"hidden_size": 128, ...},
         "state": "staged", "history": [...]}

The manifest binds a version name to the EXACT checkpoint bytes it was
published from (``sha256`` over the blob, ``utils/integrity.py``) and
the geometry it was built for — ``tools/verify_checkpoint.py
--registry`` re-checks both offline, and the rollout controller refuses
to swap a version whose digest no longer matches.

**State machine.** A version is published ``staged`` and moves only
along the edges ``telemetry/schema.py REGISTRY_TRANSITIONS`` defines
(the registry imports the same tuples the schema lint checks, so the
two cannot drift)::

    staged ──► canary ──► live ──► retired
      │           │
      ▼           ▼ (rollback: reason required)
    retired     staged

Every publish and transition emits one schema-v1 ``registry_event``
record, so an artifact stream replays the full deployment history.

Stdlib-only and **dual-loadable** like the supervisor/router: imported
normally it is part of the serve package; loaded by file path
(tools/_bootstrap.py) it pulls ``utils/integrity.py`` and
``telemetry/schema.py`` the same way — the jax-free registry CLI and
chaos/fleet parents never execute the package ``__init__`` chain.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional


def _load_pkg_module(subpkg: str, modname: str):
    """See serve/supervisor.py — package import normally, file-path
    import when this module itself was loaded by path (jax-free)."""
    if __package__:
        import importlib

        return importlib.import_module(
            f"bert_pytorch_tpu.{subpkg}.{modname}")
    import importlib.util

    alias = f"_fleet_{subpkg}_{modname}"
    module = sys.modules.get(alias)
    if module is not None:
        return module
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), subpkg, f"{modname}.py")
    spec = importlib.util.spec_from_file_location(alias, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[alias] = module
    spec.loader.exec_module(module)
    return module


integrity = _load_pkg_module("utils", "integrity")
_schema = _load_pkg_module("telemetry", "schema")

REGISTRY_SCHEMA = "model-registry-v1"
MANIFEST_NAME = "manifest.json"

# The lifecycle vocabulary is the SCHEMA's: the registry enforces
# exactly the edges the offline lint accepts.
STATES = _schema.REGISTRY_STATES
TRANSITIONS = _schema.REGISTRY_TRANSITIONS
STAGED, CANARY, LIVE, RETIRED = STATES

# Geometry keys a publish records (the ones that determine every param
# shape — a checkpoint with different values cannot load into the
# serving model, and a SAME-geometry swap recompiles nothing because
# the stable forward names hit the persistent compile cache).
GEOMETRY_KEYS = ("hidden_size", "num_hidden_layers",
                 "num_attention_heads", "intermediate_size",
                 "vocab_size", "max_position_embeddings")


class RegistryError(RuntimeError):
    """A registry operation refused: unknown version, illegal state
    transition, duplicate publish, or a corrupt/missing checkpoint."""


class ModelRegistry:
    def __init__(self, root: str,
                 emit: Optional[Callable[[dict], None]] = None,
                 clock: Callable[[], float] = time.time):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self._emit_fn = emit
        self._clock = clock
        # One lock guards the manifest cache AND serializes writers:
        # the rollout controller's promote/rollback races /swapz-driving
        # control threads and the CLI's reads (concurrency registry,
        # analysis/concurrency.py).
        self._lock = threading.Lock()
        self._cache: Dict[str, dict] = {}

    # -- paths ------------------------------------------------------------

    def _dir(self, version: str) -> str:
        safe = str(version)
        if not safe or os.sep in safe or safe in (".", ".."):
            raise RegistryError(f"bad version name {version!r}")
        return os.path.join(self.root, safe)

    def manifest_path(self, version: str) -> str:
        return os.path.join(self._dir(version), MANIFEST_NAME)

    # -- telemetry --------------------------------------------------------

    def _emit(self, event: str, manifest: dict,
              from_state: Optional[str] = None,
              reason: Optional[str] = None) -> None:
        record = {
            "kind": "registry_event", "tag": "registry",
            "version": manifest["version"], "event": event,
            "state": manifest["state"], "task": manifest.get("task"),
            "digest": manifest.get("sha256"),
        }
        if from_state is not None:
            record["from_state"] = from_state
            record["to_state"] = manifest["state"]
        if reason is not None:
            record["reason"] = reason
        if self._emit_fn is not None:
            try:
                self._emit_fn(record)
            except Exception:
                pass

    # -- reads ------------------------------------------------------------

    def _read_locked(self, version: str) -> dict:
        cached = self._cache.get(version)
        if cached is not None:
            return cached
        try:
            with open(self.manifest_path(version)) as f:
                manifest = json.load(f)
        except OSError:
            raise RegistryError(f"unknown version {version!r} "
                                f"(no manifest under {self.root})")
        except ValueError as exc:
            raise RegistryError(
                f"version {version!r} manifest unreadable: {exc}")
        if not isinstance(manifest, dict) or \
                manifest.get("schema") != REGISTRY_SCHEMA:
            raise RegistryError(
                f"version {version!r} manifest has unknown schema "
                f"{manifest.get('schema') if isinstance(manifest, dict) else manifest!r}")
        self._cache[version] = manifest
        return manifest

    def get(self, version: str) -> dict:
        """The version's manifest (a copy — mutate via set_state)."""
        with self._lock:
            return dict(self._read_locked(version))

    def list_versions(self) -> List[dict]:
        """Every version's manifest, oldest publish first."""
        with self._lock:
            manifests = []
            for name in sorted(os.listdir(self.root)):
                if not os.path.isfile(
                        os.path.join(self.root, name, MANIFEST_NAME)):
                    continue
                try:
                    manifests.append(dict(self._read_locked(name)))
                except RegistryError:
                    continue
            manifests.sort(key=lambda m: m.get("published_ts", 0.0))
            return manifests

    def live_version(self, task: str) -> Optional[dict]:
        """The manifest currently ``live`` for ``task`` (None if no
        version has been promoted yet)."""
        for manifest in self.list_versions():
            if manifest.get("task") == task and \
                    manifest.get("state") == LIVE:
                return manifest
        return None

    # -- writes -----------------------------------------------------------

    def _write_locked(self, manifest: dict) -> None:
        """tmp + rename into the version directory — the checkpoint
        torn-write discipline (utils/integrity.py write_manifest)."""
        directory = self._dir(manifest["version"])
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(manifest, f, sort_keys=True, indent=1)
            os.replace(tmp, os.path.join(directory, MANIFEST_NAME))
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        self._cache[manifest["version"]] = manifest

    def publish(self, version: str, task: str, checkpoint: str,
                quantize: Optional[str] = None,
                geometry: Optional[dict] = None) -> dict:
        """Register ``checkpoint`` as ``version`` in state ``staged``.

        The blob is digested NOW (sha256 over the bytes on disk) and,
        when it carries an integrity sidecar, verified first — the
        registry must never bind a version name to bytes that are
        already torn."""
        checkpoint = os.path.abspath(checkpoint)
        if not os.path.isfile(checkpoint):
            raise RegistryError(f"checkpoint missing: {checkpoint}")
        status, detail = integrity.verify_checkpoint(checkpoint)
        if status == integrity.CORRUPT:
            raise RegistryError(
                f"refusing to publish corrupt checkpoint "
                f"{checkpoint}: {detail}")
        manifest = {
            "schema": REGISTRY_SCHEMA,
            "version": str(version),
            "task": str(task),
            "checkpoint": checkpoint,
            "sha256": integrity.sha256_file(checkpoint),
            "size_bytes": os.path.getsize(checkpoint),
            "quantize": str(quantize) if quantize else "none",
            "geometry": dict(geometry or {}),
            "state": STAGED,
            "published_ts": round(float(self._clock()), 3),
            "history": [],
        }
        with self._lock:
            if version in self._cache or \
                    os.path.exists(self.manifest_path(version)):
                raise RegistryError(
                    f"version {version!r} already published "
                    f"(versions are immutable; pick a new name)")
            self._write_locked(manifest)
        self._emit("published", manifest)
        return dict(manifest)

    def set_state(self, version: str, state: str,
                  reason: Optional[str] = None) -> dict:
        """One state-machine transition; raises on an illegal edge.
        A rollback (canary -> staged) must carry ``reason``."""
        with self._lock:
            manifest = dict(self._read_locked(version))
            from_state = manifest.get("state")
            if (from_state, state) not in TRANSITIONS:
                raise RegistryError(
                    f"illegal transition {from_state!r} -> {state!r} "
                    f"for version {version!r} (legal edges: "
                    f"{TRANSITIONS})")
            if (from_state, state) == (CANARY, STAGED) and not reason:
                raise RegistryError(
                    "a rollback (canary -> staged) requires a reason")
            manifest["state"] = state
            manifest["history"] = list(manifest.get("history", ())) + [{
                "from": from_state, "to": state,
                "reason": reason,
                "ts": round(float(self._clock()), 3),
            }]
            self._write_locked(manifest)
        self._emit("state_change", manifest, from_state=from_state,
                   reason=reason)
        return dict(manifest)

    def begin_canary(self, version: str) -> dict:
        return self.set_state(version, CANARY)

    def promote(self, version: str) -> dict:
        """canary -> live; any other version of the same task that was
        live retires (exactly one live version per task)."""
        promoted = self.set_state(version, LIVE)
        for other in self.list_versions():
            if other["version"] != promoted["version"] and \
                    other.get("task") == promoted.get("task") and \
                    other.get("state") == LIVE:
                self.set_state(other["version"], RETIRED)
        return promoted

    def rollback(self, version: str, reason: str) -> dict:
        return self.set_state(version, STAGED, reason=reason)

    # -- verification -----------------------------------------------------

    def verify(self, version: str) -> (bool, str):
        """Does the version's checkpoint still match its manifest?
        (ok, detail) — missing bytes, a size change, or a digest
        mismatch all fail; the rollout controller refuses to swap a
        version that does not verify."""
        manifest = self.get(version)
        checkpoint = manifest.get("checkpoint", "")
        if not os.path.isfile(checkpoint):
            return False, f"checkpoint missing: {checkpoint}"
        size = os.path.getsize(checkpoint)
        if size != manifest.get("size_bytes"):
            return False, (f"size mismatch: manifest says "
                           f"{manifest.get('size_bytes')} bytes, "
                           f"file is {size}")
        digest = integrity.sha256_file(checkpoint)
        if digest != manifest.get("sha256"):
            return False, (f"sha256 mismatch: manifest "
                           f"{str(manifest.get('sha256'))[:12]}..., "
                           f"file {digest[:12]}...")
        return True, "sha256 verified"

    def verify_geometry(self, version: str, config: dict) -> (bool, str):
        """Does the version's recorded geometry still match ``config``
        (a model-config dict)? Only keys the manifest recorded are
        compared — a version published without geometry passes with a
        note (nothing to check against)."""
        manifest = self.get(version)
        geometry = manifest.get("geometry") or {}
        if not geometry:
            return True, "no geometry recorded"
        drifted = {k: (v, config.get(k)) for k, v in geometry.items()
                   if k in config and config[k] != v}
        if drifted:
            detail = ", ".join(
                f"{k}: manifest {v[0]!r} != config {v[1]!r}"
                for k, v in sorted(drifted.items()))
        return (not drifted,
                detail if drifted else "geometry matches config")


def geometry_from_config(config: dict) -> dict:
    """The shape-determining subset of a model config — what publish
    records and ``verify_checkpoint --registry`` compares."""
    return {k: config[k] for k in GEOMETRY_KEYS if k in config}
