"""SLO-gated canary rollout controller (docs/serving.md "Model
registry & canary rollouts").

One :class:`RolloutController` drives one version of one task from
``staged`` to ``live`` through a staircase of traffic shares
(default 1% -> 50% -> 100%) using the router's deterministic
request-hash split (serve/router.py ``set_split``). The loop per
observation window:

1. read the router's per-cohort outcome window
   (``Router.split_window()`` — requests/ok/errors/sheds + latency
   percentiles for canary and control separately);
2. once the canary cohort has seen at least ``min_window_requests``,
   compute the SLO verdict: error share within the error budget, p95
   within the latency SLO (when configured), and ZERO torn-model
   serves (the zero-tolerance structural invariant —
   serve/engine.py's atomic flip makes it structurally impossible, and
   the rollout still checks the counter because "structurally
   impossible" is a claim telemetry must be able to falsify);
3. act: **hold** (not enough evidence, or green but not yet enough
   consecutive green windows), **advance** (enough consecutive greens
   at this stage -> widen the split to the next share), **promote**
   (greens at the final 100% stage -> registry promote, swap every
   remaining replica via ``on_promote``, clear the split), or
   **rollback** (ANY breach -> clear the split instantly so canary
   traffic snaps back to the old version, ``on_rollback``, registry
   canary -> staged with the breach reason).

Every observation emits one schema-v1 ``rollout_window`` record
(telemetry/schema.py): the report's "rollout canary SLO" and "rollout
torn-model serves" gates read them, and the schema's cross-record lint
holds canary_share monotone per (task, version) unless a rollback
intervenes — the emitted share is the share DURING the observed
window, so an advance in the same record keeps the sequence legal.

Rollback is deliberately instant and unconditional on first breach: a
canary exists to bound blast radius, and the cheapest safe action is
always "old version everywhere, human decides later". There is no
re-try staircase here — a rolled-back controller is terminal; publish
a fixed version and run a new rollout.

Stdlib-only and dual-loadable by file path like the router and
supervisor (tools/chaos_serve.py drives rollouts from a jax-free
parent process).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Sequence


class RolloutError(RuntimeError):
    """Misuse of the rollout state machine (started twice, observed
    after a terminal action, bad stage list)."""


class RolloutController:
    """Drive one canary rollout; all mutable stage state lives under
    ``_lock`` (observe() may be called from a scheduler thread while
    status() is read from an HTTP handler — concurrency registry,
    analysis/concurrency.py).

    Parameters
    ----------
    router:
        The serve/router.py Router (or any object with ``set_split`` /
        ``clear_split`` / ``split_window``).
    registry:
        serve/registry.py ModelRegistry holding ``version``.
    task, version:
        What is being rolled out.
    stages:
        Ascending traffic shares, last one 1.0 (full shift).
    min_window_requests:
        Canary-cohort requests an observation window must contain
        before its verdict counts — a 1% canary at low traffic must
        not advance on three requests' worth of evidence.
    green_windows_to_advance:
        Consecutive green verdicts required per stage.
    slo_p95_ms:
        Canary p95 latency bound; None disables the latency gate
        (error budget still applies).
    error_budget:
        Max tolerated canary error share per window (errors /
        requests), e.g. 0.01.
    emit:
        Telemetry sink for ``rollout_window`` records.
    on_promote / on_rollback:
        Fleet-side effects (swap remaining replicas / re-swap canary
        replicas back). Called OUTSIDE the controller lock, after the
        router split has already been updated — the router never routes
        on a stale split while the fleet converges.
    scrape_torn:
        Zero-arg callable returning the fleet's current torn-serve
        count (sum of replica /statsz ``torn_serves``); None -> 0.
    """

    def __init__(
        self,
        router,
        registry,
        task: str,
        version: str,
        stages: Sequence[float] = (0.01, 0.50, 1.0),
        min_window_requests: int = 20,
        green_windows_to_advance: int = 2,
        slo_p95_ms: Optional[float] = None,
        error_budget: float = 0.01,
        emit: Optional[Callable[[dict], None]] = None,
        on_promote: Optional[Callable[[], None]] = None,
        on_rollback: Optional[Callable[[str], None]] = None,
        scrape_torn: Optional[Callable[[], int]] = None,
        clock: Callable[[], float] = time.time,
    ):
        stages = tuple(float(s) for s in stages)
        if not stages or any(not 0.0 < s <= 1.0 for s in stages):
            raise RolloutError(
                f"stages must be shares in (0, 1], got {stages}")
        if list(stages) != sorted(stages):
            raise RolloutError(
                f"stages must ascend (the monotone-share contract "
                f"the schema lint enforces), got {stages}")
        if stages[-1] != 1.0:
            raise RolloutError(
                f"final stage must be 1.0 (full shift), got {stages[-1]}")
        if not 0.0 <= float(error_budget) <= 1.0:
            raise RolloutError(
                f"error_budget must be in [0, 1], got {error_budget}")
        self.router = router
        self.registry = registry
        self.task = str(task)
        self.version = str(version)
        self.stages = stages
        self.min_window_requests = max(1, int(min_window_requests))
        self.green_windows_to_advance = max(
            1, int(green_windows_to_advance))
        self.slo_p95_ms = (float(slo_p95_ms)
                           if slo_p95_ms is not None else None)
        self.error_budget = float(error_budget)
        self._emit_fn = emit
        self._on_promote = on_promote
        self._on_rollback = on_rollback
        self._scrape_torn = scrape_torn
        self._clock = clock
        # Stage state: _stage indexes ``stages``; _greens counts
        # consecutive green windows AT this stage; _state is
        # "idle" | "canary" | "promoted" | "rolled_back" (terminal two).
        self._lock = threading.Lock()
        self._stage = 0
        self._greens = 0
        self._state = "idle"
        self._windows = 0

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        """Begin the rollout: registry staged -> canary, install the
        first-stage split. The registry transition runs FIRST — if the
        version is not publishable (bad state, failed verify), no
        traffic ever shifts."""
        with self._lock:
            if self._state != "idle":
                raise RolloutError(
                    f"rollout already {self._state}; controllers are "
                    "single-use")
            self._state = "canary"
        self.registry.begin_canary(self.version)
        self.router.set_split(self.task, self.version, self.stages[0])

    def status(self) -> dict:
        with self._lock:
            return {
                "task": self.task, "version": self.version,
                "state": self._state, "stage": self._stage,
                "share": self.stages[min(self._stage,
                                         len(self.stages) - 1)],
                "greens": self._greens, "windows": self._windows,
            }

    # -- the observation loop ---------------------------------------------

    def observe(self, window: Optional[dict] = None) -> dict:
        """Evaluate one observation window and act on it; returns the
        emitted ``rollout_window`` record (with the action taken).
        ``window`` overrides the router read for tests; normally the
        controller pulls-and-resets ``router.split_window()``."""
        with self._lock:
            if self._state != "canary":
                raise RolloutError(
                    f"cannot observe a rollout in state {self._state}")
            stage = self._stage
            share = self.stages[stage]
        if window is None:
            window = self.router.split_window(reset=True)
        if window is None:
            raise RolloutError(
                "router has no active split (cleared externally?)")
        canary = window.get("canary") or {}
        requests = int(canary.get("requests", 0))
        ok = int(canary.get("ok", 0))
        errors = int(canary.get("errors", 0))
        torn = int(self._scrape_torn()) if self._scrape_torn else 0

        # -- verdict -------------------------------------------------------
        enough = requests >= self.min_window_requests
        breach_reason: Optional[str] = None
        if torn > 0:
            # Zero tolerance, checked before anything else and even on
            # thin evidence: one torn serve means the atomic-flip
            # invariant broke, and no amount of green latency excuses it.
            breach_reason = (f"torn-model serves detected ({torn}); "
                            "atomic-flip invariant violated")
        elif enough:
            error_share = errors / requests if requests else 0.0
            if error_share > self.error_budget:
                breach_reason = (
                    f"canary error share {error_share:.4f} exceeds "
                    f"budget {self.error_budget:.4f} "
                    f"({errors}/{requests})")
            elif (self.slo_p95_ms is not None
                  and canary.get("latency_p95_ms") is not None
                  and float(canary["latency_p95_ms"]) > self.slo_p95_ms):
                breach_reason = (
                    f"canary p95 {canary['latency_p95_ms']:.1f}ms "
                    f"exceeds SLO {self.slo_p95_ms:.1f}ms")
        slo_ok = breach_reason is None

        # -- act -----------------------------------------------------------
        action = "hold"
        if breach_reason is not None:
            action = "rollback"
        elif enough:
            with self._lock:
                self._greens += 1
                greens = self._greens
            if greens >= self.green_windows_to_advance:
                action = ("promote" if stage == len(self.stages) - 1
                          else "advance")

        if action == "rollback":
            # Order matters: clear the split FIRST so the very next
            # request routes away from the canary, then unwind the
            # fleet, then record the registry transition (which carries
            # the reason for the audit trail).
            self.router.clear_split()
            with self._lock:
                self._state = "rolled_back"
            if self._on_rollback is not None:
                self._on_rollback(breach_reason)
            self.registry.rollback(self.version, breach_reason)
        elif action == "advance":
            with self._lock:
                self._stage = stage + 1
                self._greens = 0
                next_share = self.stages[self._stage]
            self.router.set_split(self.task, self.version, next_share)
        elif action == "promote":
            # Registry first (live is the source of truth), then the
            # fleet converges (remaining replicas swap), then the split
            # drops — while replicas are still converging the split
            # keeps steering traffic to already-swapped replicas.
            self.registry.promote(self.version)
            with self._lock:
                self._state = "promoted"
            if self._on_promote is not None:
                self._on_promote()
            self.router.clear_split()

        record = self._window_record(
            stage=stage, share=share, window=window, requests=requests,
            ok=ok, errors=errors, slo_ok=slo_ok, action=action,
            reason=breach_reason, torn=torn)
        with self._lock:
            self._windows += 1
        self._emit(record)
        return record

    # -- record building --------------------------------------------------

    def _window_record(self, stage: int, share: float, window: dict,
                       requests: int, ok: int, errors: int,
                       slo_ok: bool, action: str,
                       reason: Optional[str], torn: int) -> dict:
        canary = window.get("canary") or {}
        record = {
            "kind": "rollout_window", "tag": "rollout",
            "task": self.task, "version": self.version,
            "stage": int(stage),
            # The share DURING the observed window (pre-advance): the
            # schema's cross-record lint holds shares monotone per
            # (task, version), and emitting the next stage's share here
            # would double-report the advance.
            "canary_share": float(share),
            "window_requests": int(requests),
            "ok": int(ok), "errors": int(errors),
            "slo_ok": bool(slo_ok), "action": str(action),
            "torn_serves": int(torn),
        }
        if requests:
            record["budget_burn"] = round(
                (errors / requests) / self.error_budget
                if self.error_budget > 0 else float(errors), 4)
        for key in ("latency_p50_ms", "latency_p95_ms",
                    "latency_p99_ms"):
            if canary.get(key) is not None:
                record[key] = float(canary[key])
        if int(window.get("fallbacks", 0)):
            record["fallbacks"] = int(window["fallbacks"])
        control = window.get("control") or {}
        if control.get("requests"):
            record["control_requests"] = int(control["requests"])
            record["control_errors"] = int(control.get("errors", 0))
            if control.get("latency_p95_ms") is not None:
                record["control_p95_ms"] = float(
                    control["latency_p95_ms"])
        if reason:
            record["reason"] = str(reason)
        return record

    def _emit(self, record: dict) -> None:
        if self._emit_fn is None:
            return
        try:
            self._emit_fn(record)
        except Exception:
            pass
