"""Fleet router: health-gated balancing, bounded failover, hedging,
brownout (docs/serving.md "Fleet tier", The Tail at Scale §"Within
Request Short-Term Adaptations").

The front tier in front of N serving replicas (serve/supervisor.py).
One :class:`Router` owns a replica table fed by a background scrape of
each replica's ``/metricsz`` gauges (queue depth, draining,
dispatch-thread liveness — the signal surface PR 9 built) and serves
the SAME JSON API the replicas do (``POST /v1/<task>``), so a client
cannot tell one engine from a fleet. Per request it applies, in order:

* **admission** — only replicas that are healthy (scrape fresh, dispatch
  alive, not draining) are candidates; if every candidate's queue depth
  is at the brownout threshold (or none are healthy at all) the request
  is SHED with 503 + ``Retry-After`` rather than queued into a latency
  cliff;
* **least-queue-depth balancing** — among candidates, route to the
  smallest (scraped queue depth + router-local in-flight);
* **per-request deadline** — every dispatch, backoff, and hedge wait is
  bounded by one deadline; when it passes the client gets a definite
  answer, never a hang;
* **bounded retry on a DIFFERENT replica** — a transport failure or
  5xx puts the replica on the request's exclude list and the request on
  the next-best candidate after a full-jitter backoff
  (``utils/retry.py``), bounded by both an attempt count and the
  deadline. 4xx answers are returned as-is (a bad payload is bad on
  every replica; retrying it would triple the error load);
* **hedged requests** — once enough latency history exists, a dispatch
  that has outlived the configured percentile of recent latencies fires
  ONE duplicate on the next-best replica and takes whichever answers
  first — the tail-at-scale hedge, budgeted (one hedge per request,
  only past the percentile) so added load stays a few percent;
* **canary traffic splitting** — with a split installed
  (:meth:`Router.set_split`, driven by serve/rollout.py), a salted
  deterministic hash of the request sequence sends ``share`` of the
  task's traffic to replicas serving the canary version and the rest
  away from them — a SOFT preference (availability beats cohort
  purity; fallbacks are counted). Per-cohort outcome windows
  (:meth:`Router.split_window`) are the rollout controller's SLO
  evidence, and per-version routed counters export as
  ``bert_router_version_requests``.

Every ``window`` completed requests emit one schema-v1 ``router_window``
record (ok/shed/error decomposition, retry/hedge/failover counters,
latency and failover percentiles) — telemetry-report's "router
failover" gate reads them.

Stdlib-only and dual-loadable by file path (tools/chaos_serve.py) like
the supervisor: the router process must never need an accelerator
runtime.
"""

from __future__ import annotations

import collections
import http.client
import http.server
import inspect
import json
import os
import queue
import sys
import threading
import time
import urllib.parse
import uuid
from typing import Callable, Dict, List, Optional, Sequence, Tuple


def _load_util(modname: str):
    """See serve/supervisor.py — package import normally, file-path
    import when this module itself was loaded by path (jax-free)."""
    if __package__:
        import importlib

        return importlib.import_module(f"bert_pytorch_tpu.utils.{modname}")
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "utils", f"{modname}.py")
    spec = importlib.util.spec_from_file_location(
        f"_router_{modname}", path)
    module = sys.modules.get(f"_router_{modname}")
    if module is not None:
        return module
    module = importlib.util.module_from_spec(spec)
    sys.modules[f"_router_{modname}"] = module
    spec.loader.exec_module(module)
    return module


RetryPolicy = _load_util("retry").RetryPolicy


def _load_telemetry(modname: str):
    """Same dual-load trick for telemetry modules (all stdlib-only):
    Heartbeat lives in telemetry/sentinels.py, and the router writes the
    SAME resumable liveness file the training loop and serve replicas
    write, so one watchdog contract covers every tier."""
    if __package__:
        import importlib

        return importlib.import_module(
            f"bert_pytorch_tpu.telemetry.{modname}")
    import importlib.util

    name = f"_router_tel_{modname}"
    module = sys.modules.get(name)
    if module is not None:
        return module
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "telemetry", f"{modname}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    spec.loader.exec_module(module)
    return module

# Statuses worth trying on another replica: server-side trouble that is
# plausibly replica-local (a draining or saturated or crashed replica).
# Everything else — 2xx, 4xx — is final: the answer would be the same
# fleet-wide, and retrying a client error only multiplies it.
RETRYABLE_STATUSES = frozenset((500, 502, 503, 504))

_SAMPLE_CAP = 512  # recent-latency history for the hedge threshold

# -- cross-tier trace propagation (ISSUE 16) --------------------------------
# The router mints one fleet-unique trace id + head-sampling decision per
# client request and forwards them on EVERY dispatch attempt; the replica
# adopts the decision (serve/tracing.py parse_trace_header) so sampling
# is consistent fleet-wide, and both tiers echo the id back so clients
# correlate without relying on sampling. The wire format is duplicated
# from serve/tracing.py on purpose: this module stays stdlib-only and
# dual-loadable by file path (no serve-package imports); the round-trip
# is pinned by tests/test_fleet_tracing.py.
TRACE_HEADER = "X-Bert-Trace"
TRACE_ID_RESPONSE_HEADER = "X-Bert-Trace-Id"


def format_trace_header(trace_id: str, attempt: int,
                        sampled: bool) -> str:
    """``X-Bert-Trace`` request-header value for one dispatch attempt
    (serve/tracing.py parse_trace_header is the inverse)."""
    return f"{trace_id};attempt={int(attempt)};sampled={1 if sampled else 0}"


def _sample_hash(seq: int) -> float:
    """Deterministic [0, 1) hash of the request sequence number (the
    Knuth multiplicative hash serve/tracing.py uses, duplicated for the
    same dual-load reason as the wire format)."""
    return ((int(seq) * 2654435761) & 0xFFFFFFFF) / float(1 << 32)


# Golden-ratio salt decorrelating the canary-cohort hash from the
# trace-sampling hash of the SAME sequence number — without it the
# canary cohort and the sampled-trace set would be the same requests
# whenever the rates matched, and the canary's latency evidence would
# carry the tracing overhead as a confound.
_SPLIT_SALT = 0x9E3779B9


def _split_hash(seq: int) -> float:
    """Deterministic [0, 1) cohort hash for canary traffic splitting
    (serve/rollout.py): the same request sequence number always lands
    in the same cohort at a given share, and growing the share only
    ADDS members (hash < share is monotone in share) — a request never
    flaps between versions as the rollout advances."""
    return _sample_hash(int(seq) ^ _SPLIT_SALT)


def _transport_takes_headers(transport) -> bool:
    """Does the injected transport accept the trace-propagation
    ``headers`` kwarg? Tests and older harnesses inject 4-arg
    transports; the router must keep working with them (trace headers
    are then simply not forwarded on that path)."""
    try:
        sig = inspect.signature(transport)
    except (TypeError, ValueError):
        return False
    params = sig.parameters.values()
    if any(p.kind == p.VAR_KEYWORD for p in params):
        return True
    return "headers" in sig.parameters


def _pctl(sorted_vals: List[float], frac: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              int(frac * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


class ReplicaState:
    """Router-side view of one replica (every field is read/written
    under ``Router._lock``)."""

    def __init__(self, url: str, index: int):
        self.url = url.rstrip("/")
        self.index = index
        self.healthy = False        # never routed to until a good scrape
        self.draining = False
        self.dispatch_alive = False
        self.queue_depth = 0
        # Pending + in-flight across the replica's whole dispatch plane
        # (bert_serve_unfinished): the honest load signal — queue_depth
        # reads 0 the instant a batch pops, so a replica mid-batch (or
        # mid-pipeline) scraped as idle and soaked up traffic it could
        # not absorb. None when the replica predates the gauge.
        self.unfinished: Optional[int] = None
        self.inflight = 0           # router-local outstanding dispatches
        self.scrape_failures = 0
        self.requests = 0           # routed to this replica (run total)
        # Serving model version the replica last reported (its
        # bert_serve_serving_version label / healthz "version" field).
        # None until a scrape carries one — canary preference treats an
        # unknown version as NOT the canary (control traffic may land
        # there; canary traffic will not).
        self.version: Optional[str] = None

    def eligible(self) -> bool:
        return self.healthy and self.dispatch_alive and not self.draining

    def load(self) -> int:
        """Scraped load for balancing and brownout admission: prefer
        ``unfinished`` (pending + in-flight), fall back to the bare
        queue depth for replicas that do not export it."""
        return (self.unfinished if self.unfinished is not None
                else self.queue_depth)


class RouterShed(RuntimeError):
    """Request shed by admission control (brownout / no healthy
    replica); carries the Retry-After hint."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


def default_transport(url: str, task: str, payload: dict,
                      timeout_s: float,
                      headers: Optional[Dict[str, str]] = None
                      ) -> Tuple[int, dict]:
    """POST ``payload`` to ``url``/v1/``task``; returns (status, body).
    Raises OSError-family errors on transport failure (connection
    refused/reset, timeout) — the retry-on-another-replica signal.
    ``headers`` are extra request headers (the router's ``X-Bert-Trace``
    propagation rides here)."""
    parsed = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(
        parsed.hostname, parsed.port, timeout=max(0.05, timeout_s))
    try:
        body = json.dumps(payload).encode("utf-8")
        send_headers = {"Content-Type": "application/json"}
        send_headers.update(headers or {})
        conn.request("POST", f"/v1/{task}", body=body,
                     headers=send_headers)
        resp = conn.getresponse()
        data = resp.read()
        try:
            decoded = json.loads(data.decode("utf-8")) if data else {}
        except ValueError:
            decoded = {"error": data.decode("utf-8", "replace")[:200]}
        return resp.status, decoded
    finally:
        conn.close()


def default_scrape(url: str, timeout_s: float = 2.0) -> Optional[dict]:
    """One replica health sample: the ``/metricsz`` gauges (queue depth,
    draining, dispatch liveness) when the replica exports them, else the
    ``/healthz`` JSON. None = unreachable."""
    parsed = urllib.parse.urlsplit(url)
    conn = http.client.HTTPConnection(
        parsed.hostname, parsed.port, timeout=timeout_s)
    try:
        try:
            conn.request("GET", "/metricsz")
            resp = conn.getresponse()
            text = resp.read().decode("utf-8", "replace")
        except OSError:
            return None
        if resp.status == 200:
            gauges: Dict[str, float] = {}
            version: Optional[str] = None
            for line in text.splitlines():
                if line.startswith("bert_serve_serving_version{"):
                    # Info-style gauge: the version rides the label, the
                    # value is a constant 1 (serve/service.py).
                    start = line.find('version="')
                    if start >= 0:
                        start += len('version="')
                        end = line.find('"', start)
                        if end > start:
                            version = line[start:end]
                    continue
                if line.startswith("bert_serve_") and " " in line:
                    name, _, value = line.partition(" ")
                    try:
                        gauges[name] = float(value)
                    except ValueError:
                        continue
            if "bert_serve_dispatch_alive" in gauges:
                health = {
                    "dispatch_alive":
                        gauges["bert_serve_dispatch_alive"] >= 1.0,
                    "draining": gauges.get("bert_serve_draining", 0) >= 1.0,
                    "queue_depth":
                        int(gauges.get("bert_serve_queue_depth", 0)),
                }
                if "bert_serve_unfinished" in gauges:
                    # Pending + in-flight: the load signal balancing and
                    # brownout prefer (a mid-batch replica's queue_depth
                    # reads 0; its unfinished does not).
                    health["unfinished"] = int(
                        gauges["bert_serve_unfinished"])
                if version is not None:
                    health["version"] = version
                return health
        # No tracer on the replica (404) or gauges missing: /healthz
        # carries the same liveness/drain/queue facts as JSON.
        try:
            conn.close()
            conn.connect()
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            health = json.loads(resp.read().decode("utf-8", "replace"))
        except (OSError, ValueError):
            return None
        result = {
            "dispatch_alive": bool(health.get("dispatch_alive")),
            "draining": bool(health.get("draining")),
            "queue_depth": int(health.get("queue_depth", 0)),
        }
        if health.get("unfinished") is not None:
            result["unfinished"] = int(health["unfinished"])
        if health.get("version"):
            # Chaos replicas run without a tracer; /healthz carries the
            # serving version so canary routing still works there.
            result["version"] = str(health["version"])
        return result
    finally:
        conn.close()


class Router:
    def __init__(
        self,
        replica_urls: Sequence[str],
        emit: Optional[Callable[[dict], None]] = None,
        window: int = 64,
        transport: Callable[[str, str, dict, float],
                            Tuple[int, dict]] = default_transport,
        scrape: Callable[[str], Optional[dict]] = default_scrape,
        scrape_interval_s: float = 0.5,
        scrape_failures_unhealthy: int = 2,
        deadline_s: float = 15.0,
        retry_policy: Optional[RetryPolicy] = None,
        hedge_pctl: float = 0.95,
        hedge_min_ms: float = 20.0,
        hedge_min_samples: int = 32,
        brownout_queue_depth: int = 128,
        shed_retry_after_s: float = 1.0,
        trace_sample_rate: float = 0.0,
        heartbeat_file: Optional[str] = None,
        heartbeat_interval_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if not replica_urls:
            raise ValueError("need at least one replica URL")
        if not 0.0 <= float(trace_sample_rate) <= 1.0:
            raise ValueError(
                f"trace_sample_rate must be in [0, 1], got "
                f"{trace_sample_rate}")
        self._emit_fn = emit
        self.window = max(1, int(window))
        self._transport = transport
        self._transport_headers = _transport_takes_headers(transport)
        # Fleet-wide head sampling (ISSUE 16): the router's decision per
        # client request wins over every replica's local rate, so one
        # sampled request yields spans on BOTH tiers (the stitchable
        # tree) instead of two uncorrelated coin flips.
        self.trace_sample_rate = float(trace_sample_rate)
        self._trace_token = uuid.uuid4().hex[:8]
        self._trace_seq = 0
        self._scrape = scrape
        self.scrape_interval_s = float(scrape_interval_s)
        self.scrape_failures_unhealthy = int(scrape_failures_unhealthy)
        self.deadline_s = float(deadline_s)
        # Full jitter + short base: a dead replica fails dozens of
        # requests at the same instant, and their retries must not land
        # on the survivor in one synchronized wave.
        self.retry_policy = retry_policy or RetryPolicy(
            attempts=3, base_delay_s=0.05, max_delay_s=1.0,
            full_jitter=True)
        self.hedge_pctl = float(hedge_pctl)
        self.hedge_min_ms = float(hedge_min_ms)
        self.hedge_min_samples = int(hedge_min_samples)
        self.brownout_queue_depth = int(brownout_queue_depth)
        self.shed_retry_after_s = float(shed_retry_after_s)
        self._clock = clock
        self._sleep = sleep
        # One lock guards the replica table, the latency history, and
        # the window/run counters: scrape thread + every router worker
        # thread mutate them (concurrency registry,
        # analysis/concurrency.py).
        self._lock = threading.Lock()
        self._replicas = [ReplicaState(url, i)
                          for i, url in enumerate(replica_urls)]
        # Monotone target-index mint for add_target (under _lock): a
        # removed slot's index stays retired, so per-target telemetry
        # is never conflated across membership generations.
        self._next_target_index = len(self._replicas)
        self._latencies = collections.deque(maxlen=_SAMPLE_CAP)
        self._win = self._zero_window()
        self._run = self._zero_window()
        # Canary traffic split (serve/rollout.py drives it): one split
        # at a time, {task, version, share, fallbacks, canary, control}
        # with a per-cohort outcome accumulator. Per-version routed
        # counters live for the whole run (the
        # bert_router_version_requests export). Both under _lock — the
        # request threads book outcomes while the rollout controller
        # reads/resets windows (concurrency registry,
        # analysis/concurrency.py).
        self._split: Optional[dict] = None
        self._version_requests: Dict[str, int] = {}
        self._stop_event = threading.Event()
        self._scrape_thread: Optional[threading.Thread] = None
        # Router heartbeat: the same resumable liveness file the trainer
        # and replicas write (telemetry/sentinels.py), step = routed
        # requests — counter resumes across restarts, so "did the router
        # route anything lately" is one file read for any watchdog.
        # Beaten only from the scrape thread (Heartbeat.beat is
        # single-owner by design) plus one final flush in stop() after
        # that thread is joined; the binding itself is frozen
        # (concurrency registry, analysis/concurrency.py).
        self._heartbeat = (
            _load_telemetry("sentinels").Heartbeat(heartbeat_file)
            if heartbeat_file else None)
        self._heartbeat_interval_s = float(heartbeat_interval_s)

    @staticmethod
    def _zero_window() -> dict:
        return {"requests": 0, "ok": 0, "sheds": 0, "errors": 0,
                "retries": 0, "hedges": 0, "hedge_wins": 0,
                "hedge_wasted_ms": 0.0,
                "failovers": 0, "latency_ms": [], "failover_ms": []}

    @staticmethod
    def _zero_cohort() -> dict:
        return {"requests": 0, "ok": 0, "sheds": 0, "errors": 0,
                "latency_ms": []}

    def _mint_trace(self) -> Tuple[str, bool, int]:
        """One fleet-unique trace id + head-sampling decision per client
        request (plus the raw sequence number — the canary cohort hash
        reuses it). The run token namespaces ids across router restarts
        (serve/tracing.py discipline); the sequence hash keeps sampling
        deterministic for replayed bursts."""
        with self._lock:
            seq = self._trace_seq
            self._trace_seq += 1
        sampled = (self.trace_sample_rate > 0.0
                   and _sample_hash(seq) < self.trace_sample_rate)
        return f"rt-{self._trace_token}-{seq:x}", sampled, seq

    # -- canary traffic split (serve/rollout.py) --------------------------

    def set_split(self, task: str, version: str, share: float) -> None:
        """Install or widen the canary split: ``share`` of ``task``
        traffic (by deterministic request hash) PREFERS replicas serving
        ``version``; the rest avoids them. One split at a time — a
        second (task, version) must wait for :meth:`clear_split`."""
        share = float(share)
        if not 0.0 <= share <= 1.0:
            raise ValueError(f"share must be in [0, 1], got {share}")
        with self._lock:
            if self._split is not None and (
                    self._split["task"] != task
                    or self._split["version"] != version):
                raise RuntimeError(
                    "a different split is already active "
                    f"({self._split['task']}/{self._split['version']}); "
                    "clear_split() first")
            if self._split is None:
                self._split = {
                    "task": str(task), "version": str(version),
                    "share": share, "fallbacks": 0,
                    "canary": self._zero_cohort(),
                    "control": self._zero_cohort(),
                }
            else:
                self._split["share"] = share

    def clear_split(self) -> None:
        """Drop the canary split (rollout promoted or rolled back);
        routing goes back to pure least-loaded."""
        with self._lock:
            self._split = None

    def split_window(self, reset: bool = True) -> Optional[dict]:
        """Per-cohort outcome window since the last reset — the rollout
        controller's SLO evidence. None when no split is active."""
        with self._lock:
            if self._split is None:
                return None
            out = {"task": self._split["task"],
                   "version": self._split["version"],
                   "share": self._split["share"],
                   "fallbacks": self._split["fallbacks"]}
            for cohort in ("canary", "control"):
                acc = self._split[cohort]
                summary = {"requests": acc["requests"], "ok": acc["ok"],
                           "errors": acc["errors"], "sheds": acc["sheds"]}
                lat = sorted(acc["latency_ms"])
                if lat:
                    summary.update(
                        latency_p50_ms=round(_pctl(lat, 0.50), 3),
                        latency_p95_ms=round(_pctl(lat, 0.95), 3),
                        latency_p99_ms=round(_pctl(lat, 0.99), 3))
                out[cohort] = summary
                if reset:
                    self._split[cohort] = self._zero_cohort()
            if reset:
                self._split["fallbacks"] = 0
            return out

    # -- health scraping --------------------------------------------------

    def start(self) -> None:
        """Start the background health scrape; do one synchronous pass
        first so the router is immediately routable when replicas are
        already up."""
        self.scrape_once()
        self._stop_event.clear()
        self._scrape_thread = threading.Thread(
            target=self._scrape_loop, name="router-scrape", daemon=True)
        self._scrape_thread.start()

    def _scrape_loop(self) -> None:
        # last_beat stays a local: beat cadence state is owned by this
        # thread alone (same discipline as serve/service.py's loops).
        last_beat = 0.0
        while not self._stop_event.is_set():
            self.scrape_once()
            last_beat = self._maybe_beat(last_beat)
            self._sleep(self.scrape_interval_s)

    def _maybe_beat(self, last_beat: float) -> float:
        """Beat the liveness file with step = routed requests; called
        only from the scrape thread (and once from stop() after that
        thread is joined — ownership passes to the stopping thread)."""
        if self._heartbeat is None:
            return last_beat
        now = self._clock()
        if now - last_beat < self._heartbeat_interval_s:
            return last_beat
        with self._lock:
            routed = self._run["requests"]
        self._heartbeat.beat(routed)
        return now

    def scrape_once(self) -> None:
        """One health pass over every replica (public so tests and the
        chaos harness can drive deterministic scrapes)."""
        with self._lock:
            targets = list(self._replicas)
        # One thread per replica: each probe is bounded by the scrape
        # transport's own timeout, and probing CONCURRENTLY makes the
        # pass cost max(per-replica) instead of sum — one black-holed
        # replica must not stale every other replica's gauges for its
        # full timeout (the balancing and brownout decisions read them).
        results: list = [None] * len(targets)

        def probe(i: int, rep: ReplicaState) -> None:
            try:
                results[i] = (rep, self._scrape(rep.url))
            except Exception:
                results[i] = (rep, None)

        threads = [threading.Thread(target=probe, args=(i, rep),
                                    name="router-scrape-probe", daemon=True)
                   for i, rep in enumerate(targets)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with self._lock:
            for rep, health in results:
                if health is None:
                    rep.scrape_failures += 1
                    if rep.scrape_failures >= \
                            self.scrape_failures_unhealthy:
                        rep.healthy = False
                    continue
                rep.scrape_failures = 0
                rep.healthy = True
                rep.dispatch_alive = bool(health.get("dispatch_alive"))
                rep.draining = bool(health.get("draining"))
                rep.queue_depth = int(health.get("queue_depth", 0))
                unfinished = health.get("unfinished")
                rep.unfinished = (int(unfinished)
                                  if unfinished is not None else None)
                if health.get("version"):
                    rep.version = str(health["version"])

    # -- balancing / admission -------------------------------------------

    def _admit(self, exclude: frozenset,
               prefer_version: Optional[str] = None,
               avoid_version: Optional[str] = None) -> ReplicaState:
        """Least-loaded eligible replica, or raise :class:`RouterShed`
        (brownout: every eligible replica saturated; outage: none
        eligible at all). Load is ``ReplicaState.load()`` — unfinished
        (pending + in-flight) when the replica exports it, else queue
        depth — so a replica mid-batch no longer scrapes as idle.

        ``prefer_version`` / ``avoid_version`` are the canary split's
        SOFT version preference: when the preferred sub-pool is empty or
        fully saturated the request falls back to the whole candidate
        set (counted into the split's ``fallbacks``) — availability
        always beats cohort purity; a rollout that could strand traffic
        behind a dead canary would turn every canary crash into a client
        outage."""
        with self._lock:
            candidates = [rep for rep in self._replicas
                          if rep.eligible() and rep.url not in exclude]
            if not candidates:
                raise RouterShed(
                    "no healthy replica available", self.shed_retry_after_s)
            if all(rep.load() >= self.brownout_queue_depth
                   for rep in candidates):
                raise RouterShed(
                    "every healthy replica is saturated "
                    f"(unfinished >= {self.brownout_queue_depth}); "
                    "brownout shed", self.shed_retry_after_s)
            pool = candidates
            if prefer_version is not None or avoid_version is not None:
                if prefer_version is not None:
                    preferred = [rep for rep in candidates
                                 if rep.version == prefer_version]
                else:
                    preferred = [rep for rep in candidates
                                 if rep.version != avoid_version]
                preferred = [rep for rep in preferred
                             if rep.load() < self.brownout_queue_depth]
                if preferred:
                    pool = preferred
                elif self._split is not None:
                    self._split["fallbacks"] += 1
            chosen = min(pool,
                         key=lambda r: (r.load() + r.inflight,
                                        r.inflight, r.index))
            chosen.inflight += 1
            chosen.requests += 1
            self._version_requests[chosen.version or "unknown"] = \
                self._version_requests.get(chosen.version or "unknown",
                                           0) + 1
            return chosen

    def _release(self, rep: ReplicaState, failed: bool) -> None:
        with self._lock:
            rep.inflight = max(0, rep.inflight - 1)
            if failed:
                # Fast feedback: don't route more requests here until a
                # scrape proves it back; the scrape thread re-heals it.
                rep.healthy = False

    def _hedge_delay_s(self) -> Optional[float]:
        """Seconds a dispatch may run before its hedge fires: the
        configured percentile of recent latencies, floored at
        ``hedge_min_ms``. None = hedging disabled (pctl <= 0) or not
        enough history to know what 'slow' means yet."""
        if self.hedge_pctl <= 0:
            return None
        with self._lock:
            if len(self._latencies) < self.hedge_min_samples:
                return None
            samples = sorted(self._latencies)
        return max(self.hedge_min_ms / 1000.0,
                   _pctl(samples, self.hedge_pctl))

    def note_latency(self, seconds: float) -> None:
        """Feed one successful-dispatch latency into the hedge-threshold
        history (also called internally on every success)."""
        with self._lock:
            self._latencies.append(float(seconds))

    # -- the request path -------------------------------------------------

    def handle(self, task: str, payload: dict
               ) -> Tuple[int, dict, Dict[str, str]]:
        """Route one request end to end: (status, body, extra headers).
        Never raises — every outcome is an HTTP answer, including the
        deadline (503), brownout (503 + Retry-After), and exhausted
        retries (502). Every answer carries ``X-Bert-Trace-Id`` (sampled
        or not), and a head-sampled request additionally emits ONE
        ``router_trace`` record: admission / per-attempt dispatch /
        backoff spans plus the hedge win/waste accounting — the
        router-tier half of the stitched end-to-end tree
        (telemetry/collector.py)."""
        t0 = self._clock()
        trace_id, sampled, seq = self._mint_trace()
        # Canary cohort (serve/rollout.py): a salted hash of the SAME
        # sequence number splits traffic deterministically — same
        # request number, same cohort, and growing the share only adds
        # members. Computed once here; the preference rides every
        # admission and hedge pick for this request.
        with self._lock:
            split = (dict(self._split)
                     if self._split is not None else None)
        cohort: Optional[str] = None
        prefer_version: Optional[str] = None
        avoid_version: Optional[str] = None
        if split is not None and split["task"] == task:
            if _split_hash(seq) < split["share"]:
                cohort = "canary"
                prefer_version = split["version"]
            else:
                cohort = "control"
                avoid_version = split["version"]
        deadline = t0 + self.deadline_s
        exclude: set = set()
        rounds = 0
        failed_rounds = 0
        hedges_fired = 0
        hedge_wasted_s = 0.0
        spans: List[dict] = []
        attempt_base = 1

        def finish(status: int, body: dict, extra: Optional[dict] = None,
                   ok: bool = False, shed: bool = False,
                   hedge_won: bool = False, failover: bool = False,
                   winning_attempt: Optional[int] = None
                   ) -> Tuple[int, dict, Dict[str, str]]:
            self._observe(ok=ok, shed=shed, t0=t0, retries=failed_rounds,
                          hedges=hedges_fired, hedge_won=hedge_won,
                          failover=failover,
                          hedge_wasted_ms=hedge_wasted_s * 1000.0,
                          cohort=cohort)
            if sampled:
                self._emit_trace(
                    trace_id, task, status, t0, spans,
                    hedges=hedges_fired, hedge_won=hedge_won,
                    hedge_wasted_s=hedge_wasted_s,
                    winning_attempt=winning_attempt)
            headers = {TRACE_ID_RESPONSE_HEADER: trace_id}
            headers.update(extra or {})
            return status, body, headers

        while True:
            t_admit = self._clock()
            try:
                replica = self._admit(frozenset(exclude),
                                      prefer_version=prefer_version,
                                      avoid_version=avoid_version)
            except RouterShed as shed:
                spans.append(self._span("admission", t0, t_admit))
                return finish(503, {"error": str(shed)},
                              {"Retry-After": f"{shed.retry_after_s:g}"},
                              shed=True)
            spans.append(self._span("admission", t0, t_admit))
            remaining = deadline - self._clock()
            if remaining <= 0:
                self._release(replica, failed=False)
                return finish(503, {"error": "router deadline exceeded "
                                             f"({self.deadline_s:g}s)"})
            rounds += 1
            status, body, hedged, hedge_won, failed_urls, attempts = \
                self._dispatch_hedged(
                    replica, task, payload, remaining, exclude,
                    trace_id=trace_id, trace_sampled=sampled,
                    attempt_base=attempt_base,
                    prefer_version=prefer_version,
                    avoid_version=avoid_version)
            attempt_base += len(attempts)
            hedges_fired += 1 if hedged else 0
            winner = None
            for att in attempts:
                spans.append(self._attempt_span(att, t0))
                if att["won"]:
                    winner = att["attempt"]
                if hedged and not att["won"]:
                    # Hedge-loser waste (The Tail at Scale): in a round
                    # where a duplicate was racing, every non-winning
                    # attempt's latency was spent for an answer nobody
                    # used.
                    hedge_wasted_s += att["dur_s"]
            final = (status is not None
                     and status not in RETRYABLE_STATUSES)
            if final:
                # A final 4xx is the router WORKING: a definitive answer
                # relayed once (the same answer every replica would
                # give). Only 5xx-class outcomes — and the deadline/
                # exhausted-retry paths below — count into ``errors``,
                # the zero-tolerance "client saw a server failure" gate
                # (telemetry/report.py).
                return finish(status, body, ok=status < 500,
                              hedge_won=hedge_won,
                              failover=(failed_rounds > 0),
                              winning_attempt=winner)
            # Retryable failure: this replica (and any hedge target that
            # also failed) is out of the running for THIS request.
            failed_rounds += 1
            exclude.add(replica.url)
            exclude.update(failed_urls)
            policy = self.retry_policy
            if rounds >= policy.attempts:
                return finish(502, {
                    "error": f"request failed on {rounds} replica(s) "
                             f"(last status {status})"})
            backoff = policy.backoff_s(rounds - 1)
            if self._clock() + backoff >= deadline:
                return finish(503, {
                    "error": "router deadline exceeded during "
                             "failover backoff"})
            t_backoff = self._clock()
            self._sleep(backoff)
            spans.append(self._span("backoff", t0, t_backoff))

    def _span(self, name: str, t0: float, start_abs: float) -> dict:
        """One closed router span ending NOW, relative to the request
        start (start/dur in ms like serve_trace spans)."""
        now = self._clock()
        return {"name": name,
                "start_ms": round(max(0.0, (start_abs - t0)) * 1000.0, 3),
                "dur_ms": round(max(0.0, (now - start_abs)) * 1000.0, 3)}

    @staticmethod
    def _attempt_span(att: dict, t0: float) -> dict:
        span = {"name": "attempt",
                "start_ms": round(
                    max(0.0, (att["start"] - t0)) * 1000.0, 3),
                "dur_ms": round(max(0.0, att["dur_s"]) * 1000.0, 3),
                "attempt": att["attempt"],
                "replica": att["replica"],
                "outcome": att["outcome"],
                "hedge": att["hedge"]}
        if att["status"] is not None:
            span["status"] = att["status"]
        return span

    def _emit_trace(self, trace_id: str, task: str, status: int,
                    t0: float, spans: List[dict], hedges: int,
                    hedge_won: bool, hedge_wasted_s: float,
                    winning_attempt: Optional[int]) -> None:
        if self._emit_fn is None:
            return
        total_ms = (self._clock() - t0) * 1000.0
        # Round the total UP to the latest span end at the same
        # precision so the lint's per-span sub-interval bound survives
        # rounding (serve/tracing.py discipline).
        span_end = max((s["start_ms"] + s["dur_ms"] for s in spans),
                       default=0.0)
        record = {
            "kind": "router_trace", "tag": "router",
            "trace_id": trace_id, "task": task, "status": int(status),
            "total_ms": round(max(total_ms, span_end), 3),
            "sampled": True,
            "attempts": sum(1 for s in spans if s["name"] == "attempt"),
            "hedges": int(hedges),
            "hedge_won": bool(hedge_won),
            "hedge_wasted_ms": round(max(0.0, hedge_wasted_s) * 1000.0, 3),
            "spans": spans,
        }
        if winning_attempt is not None:
            record["winning_attempt"] = int(winning_attempt)
        try:
            self._emit_fn(record)
        except Exception:
            pass

    def _dispatch_hedged(self, primary: ReplicaState, task: str,
                         payload: dict, timeout_s: float, exclude: set,
                         trace_id: str, trace_sampled: bool,
                         attempt_base: int,
                         prefer_version: Optional[str] = None,
                         avoid_version: Optional[str] = None
                         ) -> Tuple[Optional[int], dict, bool, bool, set,
                                    List[dict]]:
        """One dispatch round, possibly hedged: (status, body, hedged,
        hedge_won, failed_urls, attempts). ``status`` None =
        transport-level failure; ``failed_urls`` is every replica that
        failed in this round (the caller's exclude list for the retry).
        ``attempts`` is one accounting dict per launched attempt —
        attempt index (numbered from ``attempt_base`` across the whole
        request), target replica, outcome, latency — closed out at the
        round's decision instant so a still-in-flight loser is measured
        by the time the race actually cost, not a latency nobody waited
        for. Each attempt propagates the request's trace context via
        ``X-Bert-Trace`` (when the transport takes headers)."""
        results: "queue.Queue" = queue.Queue()
        launched_urls = {primary.url}
        failed_urls: set = set()
        attempts: List[dict] = []

        def launch(rep: ReplicaState, is_hedge: bool) -> dict:
            att = {"attempt": attempt_base + len(attempts),
                   "replica": rep.url, "hedge": is_hedge,
                   "start": self._clock(), "end": None, "status": None,
                   "outcome": "pending", "won": False}
            attempts.append(att)
            threading.Thread(
                target=worker, args=(rep, is_hedge, att),
                name="router-hedge" if is_hedge else "router-dispatch",
                daemon=True).start()
            return att

        def worker(rep: ReplicaState, is_hedge: bool, att: dict) -> None:
            start = self._clock()
            try:
                if self._transport_headers:
                    status, body = self._transport(
                        rep.url, task, payload, timeout_s,
                        headers={TRACE_HEADER: format_trace_header(
                            trace_id, att["attempt"], trace_sampled)})
                else:
                    status, body = self._transport(
                        rep.url, task, payload, timeout_s)
            except Exception as exc:
                self._release(rep, failed=True)
                att["end"] = self._clock()
                att["outcome"] = "transport_error"
                results.put((None, {"error": f"{type(exc).__name__}: "
                                             f"{exc}"}, rep, is_hedge,
                             att))
                return
            retryable = status in RETRYABLE_STATUSES
            # A 503 is the replica ALIVE and telling us it is draining
            # or saturated — exclude it for this request, but only the
            # health scrape decides whether it stays routable.
            self._release(rep, failed=(retryable and status != 503))
            if not retryable:
                self.note_latency(self._clock() - start)
            att["status"] = status
            att["end"] = self._clock()
            att["outcome"] = "error" if retryable else "final"
            results.put((status, body, rep, is_hedge, att))

        def close_round(winner: Optional[dict]) -> None:
            """Stamp every attempt's decision-time latency and loser
            disposition (the hedge-waste basis)."""
            now = self._clock()
            for att in attempts:
                end = att["end"] if att["end"] is not None else now
                att["dur_s"] = max(0.0, end - att["start"])
                if att["outcome"] == "pending":
                    att["outcome"] = ("lost" if winner is not None
                                      else "abandoned")
                if winner is att:
                    att["won"] = True

        launch(primary, False)
        start = self._clock()
        deadline = start + timeout_s
        hedge_delay = self._hedge_delay_s()
        hedged = False
        hedge_tried = False
        failures = 0
        first_failure: Optional[Tuple[Optional[int], dict]] = None
        while True:
            now = self._clock()
            if now >= deadline:
                break
            wait = deadline - now
            if not hedge_tried and hedge_delay is not None:
                hedge_in = start + hedge_delay - now
                if hedge_in <= 0:
                    # The dispatch has outlived the configured
                    # percentile: fire ONE hedge on the next-best
                    # replica (if any remains) and race them. One
                    # attempt per round whether or not a target exists
                    # (hedge_tried) — ``hedged`` reports only a hedge
                    # actually LAUNCHED, and is counted by the caller at
                    # request completion (_observe), in the same lock
                    # acquisition as a potential hedge_win: counting the
                    # launch here let a window flush land between the
                    # two and emit hedge_wins > hedges, a
                    # schema-invalid record on a healthy run.
                    hedge_tried = True
                    hedge_rep = self._pick_hedge(
                        exclude | launched_urls,
                        prefer_version=prefer_version,
                        avoid_version=avoid_version)
                    if hedge_rep is not None:
                        hedged = True
                        launched_urls.add(hedge_rep.url)
                        launch(hedge_rep, True)
                    continue
                wait = min(wait, hedge_in)
            try:
                status, body, rep, is_hedge, att = results.get(
                    timeout=max(0.001, wait))
            except queue.Empty:
                continue
            if status is not None and status not in RETRYABLE_STATUSES:
                close_round(att)
                return status, body, hedged, is_hedge, failed_urls, \
                    attempts
            failures += 1
            failed_urls.add(rep.url)
            if first_failure is None:
                first_failure = (status, body)
            if failures >= len(attempts):
                # Everything launched has failed; a not-yet-fired hedge
                # would only duplicate a request the retry path is
                # about to place better.
                break
        close_round(None)
        if first_failure is not None:
            status, body = first_failure
        else:
            status, body = None, {
                "error": f"dispatch timed out after {timeout_s:.3f}s"}
            failed_urls.add(primary.url)
        return status, body, hedged, False, failed_urls, attempts

    def _pick_hedge(self, exclude: set,
                    prefer_version: Optional[str] = None,
                    avoid_version: Optional[str] = None
                    ) -> Optional[ReplicaState]:
        with self._lock:
            candidates = [rep for rep in self._replicas
                          if rep.eligible() and rep.url not in exclude]
            if not candidates:
                return None
            # Same soft version preference as _admit: a canary request's
            # hedge should race the SAME version (its latency evidence
            # must not mix versions), but a no-target hedge falls back
            # rather than not firing — tail rescue beats cohort purity.
            pool = candidates
            if prefer_version is not None or avoid_version is not None:
                if prefer_version is not None:
                    preferred = [rep for rep in candidates
                                 if rep.version == prefer_version]
                else:
                    preferred = [rep for rep in candidates
                                 if rep.version != avoid_version]
                if preferred:
                    pool = preferred
                elif self._split is not None:
                    self._split["fallbacks"] += 1
            chosen = min(pool,
                         key=lambda r: (r.load() + r.inflight,
                                        r.inflight, r.index))
            chosen.inflight += 1
            chosen.requests += 1
            self._version_requests[chosen.version or "unknown"] = \
                self._version_requests.get(chosen.version or "unknown",
                                           0) + 1
            return chosen

    # -- telemetry --------------------------------------------------------

    def _observe(self, ok: bool, shed: bool, t0: float, retries: int = 0,
                 hedges: int = 0, hedge_won: bool = False,
                 failover: bool = False,
                 hedge_wasted_ms: float = 0.0,
                 cohort: Optional[str] = None) -> None:
        latency_ms = (self._clock() - t0) * 1000.0
        with self._lock:
            # Cohort booking rides the same acquisition as the window
            # counters: the rollout controller's split_window() read can
            # never see a request half-booked.
            if cohort is not None and self._split is not None:
                acc = self._split.get(cohort)
                if acc is not None:
                    acc["requests"] += 1
                    if shed:
                        acc["sheds"] += 1
                    elif ok:
                        acc["ok"] += 1
                        acc["latency_ms"].append(latency_ms)
                    else:
                        acc["errors"] += 1
            for acc in (self._win, self._run):
                acc["requests"] += 1
                acc["retries"] += retries
                # Hedges launched by this request, folded in at the same
                # instant as its potential hedge_win so hedge_wins <=
                # hedges holds within EVERY window (schema invariant).
                acc["hedges"] += hedges
                # Hedge-loser waste rides the SAME acquisition: a window
                # flush can never see waste without the hedge that
                # produced it (the PR 11 flush-race discipline; the
                # schema lint rejects wasted>0 with hedges==0).
                acc["hedge_wasted_ms"] += max(0.0, hedge_wasted_ms)
                if shed:
                    acc["sheds"] += 1
                elif ok:
                    acc["ok"] += 1
                    acc["latency_ms"].append(latency_ms)
                    if failover:
                        acc["failovers"] += 1
                        acc["failover_ms"].append(latency_ms)
                else:
                    acc["errors"] += 1
                if hedge_won:
                    acc["hedge_wins"] += 1
            due = self._win["requests"] >= self.window
        if due:
            self.flush_window()

    def _window_record_locked(self, acc: dict) -> dict:
        healthy = sum(1 for rep in self._replicas if rep.eligible())
        record = {
            "kind": "router_window", "tag": "router",
            "window_requests": acc["requests"],
            "ok": acc["ok"], "sheds": acc["sheds"],
            "errors": acc["errors"], "retries": acc["retries"],
            "hedges": acc["hedges"], "hedge_wins": acc["hedge_wins"],
            "hedge_wasted_ms": round(acc["hedge_wasted_ms"], 3),
            "failovers": acc["failovers"],
            "healthy_replicas": healthy,
            "replicas": len(self._replicas),
        }
        lat = sorted(acc["latency_ms"])
        if lat:
            record.update(
                latency_p50_ms=round(_pctl(lat, 0.50), 3),
                latency_p95_ms=round(_pctl(lat, 0.95), 3),
                latency_p99_ms=round(_pctl(lat, 0.99), 3))
        fo = sorted(acc["failover_ms"])
        if fo:
            record.update(
                failover_p50_ms=round(_pctl(fo, 0.50), 3),
                failover_p95_ms=round(_pctl(fo, 0.95), 3))
        return record

    def flush_window(self) -> Optional[dict]:
        """Emit (and return) the current router_window record; None when
        the window is empty."""
        with self._lock:
            if not self._win["requests"]:
                return None
            record = self._window_record_locked(self._win)
            self._win = self._zero_window()
        if self._emit_fn is not None:
            try:
                self._emit_fn(record)
            except Exception:
                pass
        return record

    def snapshot(self) -> dict:
        """Run-level rollup for the router's /statsz."""
        with self._lock:
            record = self._window_record_locked(self._run)
            record["kind"] = "router_summary"
            record.pop("window_requests")
            record["requests"] = self._run["requests"]
            record["replica_states"] = [{
                "url": rep.url, "healthy": rep.healthy,
                "draining": rep.draining, "queue_depth": rep.queue_depth,
                "unfinished": rep.unfinished,
                "inflight": rep.inflight, "requests": rep.requests,
                "version": rep.version,
            } for rep in self._replicas]
            record["version_requests"] = dict(self._version_requests)
            if self._split is not None:
                record["split"] = {
                    "task": self._split["task"],
                    "version": self._split["version"],
                    "share": self._split["share"],
                    "fallbacks": self._split["fallbacks"],
                    "canary_requests":
                        self._split["canary"]["requests"],
                    "control_requests":
                        self._split["control"]["requests"],
                }
        return record

    def metrics_text(self, prefix: str = "bert_router") -> str:
        """Prometheus text-exposition rendering of the run-level
        counters and per-replica health gauges — the same numbers
        /statsz serves as JSON, in the one format the fleet collector
        (telemetry/collector.py) scrapes everywhere. Rendered from
        :meth:`snapshot`, so the two surfaces cannot drift."""
        snap = self.snapshot()
        lines: List[str] = []

        def render(value) -> str:
            # Full precision, not %g: a run-level counter past ~1e6
            # would otherwise export rounded and drift from /statsz —
            # delta-computing scrapers would see it advance in jumps.
            v = float(value)
            return str(int(v)) if v.is_integer() else repr(v)

        def metric(name, value, kind, help_text=""):
            if value is None:
                return
            if help_text:
                lines.append(f"# HELP {prefix}_{name} {help_text}")
            lines.append(f"# TYPE {prefix}_{name} {kind}")
            lines.append(f"{prefix}_{name} {render(value)}")

        metric("requests_total", snap.get("requests"), "counter",
               "Requests routed (ok + sheds + errors).")
        for key in ("ok", "sheds", "errors", "retries", "hedges",
                    "hedge_wins", "failovers"):
            metric(f"{key}_total", snap.get(key), "counter")
        metric("hedge_wasted_ms_total", snap.get("hedge_wasted_ms"),
               "counter", "Hedge-loser latency burned (ms, run total).")
        metric("healthy_replicas", snap.get("healthy_replicas"), "gauge",
               "Replicas currently eligible for routing.")
        metric("replicas", snap.get("replicas"), "gauge")
        for key in ("latency_p50_ms", "latency_p95_ms", "latency_p99_ms",
                    "failover_p50_ms", "failover_p95_ms"):
            metric(key, snap.get(key), "gauge")
        name = f"{prefix}_replica_state"
        lines.append(f"# HELP {name} Per-replica router view "
                     "(healthy/draining/queue_depth/inflight).")
        lines.append(f"# TYPE {name} gauge")
        for i, rep in enumerate(snap.get("replica_states", ())):
            for field in ("healthy", "draining"):
                lines.append(
                    f'{name}{{replica="{i}",field="{field}"}} '
                    f"{1 if rep.get(field) else 0}")
            for field in ("queue_depth", "inflight"):
                lines.append(
                    f'{name}{{replica="{i}",field="{field}"}} '
                    f"{render(rep.get(field, 0))}")
            if rep.get("unfinished") is not None:
                lines.append(
                    f'{name}{{replica="{i}",field="unfinished"}} '
                    f"{render(rep['unfinished'])}")
        # Per-version routed counters (the rollout's traffic-shift
        # evidence): rendered from the SAME snapshot as /statsz, so the
        # two surfaces cannot drift. "unknown" = routed before the first
        # scrape carried a version.
        version_requests = snap.get("version_requests") or {}
        if version_requests:
            vname = f"{prefix}_version_requests"
            lines.append(f"# HELP {vname} Requests routed per serving "
                         "model version (run total).")
            lines.append(f"# TYPE {vname} counter")
            for version in sorted(version_requests):
                lines.append(
                    f'{vname}{{version="{version}"}} '
                    f"{render(version_requests[version])}")
        return "\n".join(lines) + "\n"

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for rep in self._replicas if rep.eligible())

    def replica_count(self) -> int:
        with self._lock:
            return len(self._replicas)

    # -- elastic membership (serve/autoscaler.py, docs/serving.md
    # "Elastic fleet") ----------------------------------------------------

    def add_target(self, url: str) -> int:
        """Add a replica URL to the routing table. The new target
        enters UNHEALTHY (``ReplicaState``'s construction default) — no
        request routes to it until its first clean scrape proves the
        replica up, so a still-warming replica never absorbs traffic it
        cannot answer yet. Returns the target's router-local index
        (minted monotonically; never reused)."""
        url = url.rstrip("/")
        with self._lock:
            if any(rep.url == url for rep in self._replicas):
                raise ValueError(f"target already routed: {url}")
            index = self._next_target_index
            self._next_target_index += 1
            self._replicas.append(ReplicaState(url, index))
        return index

    def remove_target(self, url: str) -> bool:
        """Drop a replica URL from the routing table. The caller's
        contract (serve/autoscaler.py) is to remove only AFTER the
        supervisor confirms the drain — the replica answered its last
        in-flight request — so removal never strands a dispatch. A
        scrape probe already in flight writes back into the detached
        ``ReplicaState`` (harmless: no request thread can reach it
        through the table anymore). Refuses to empty the table (the
        constructor's own invariant). Returns whether the URL was
        routed at all."""
        url = url.rstrip("/")
        with self._lock:
            keep = [rep for rep in self._replicas if rep.url != url]
            if len(keep) == len(self._replicas):
                return False
            if not keep:
                raise ValueError("refusing to remove the last target")
            self._replicas = keep
        return True

    def split_active(self) -> bool:
        """Whether a canary traffic split is live — one of the
        autoscaler's hard scale-down holds (shrinking the fleet under
        an active cohort split would skew the rollout's per-window
        evidence mid-verdict)."""
        with self._lock:
            return self._split is not None

    def stop(self) -> None:
        """Stop the scrape thread, flush the partial window, and emit
        the run-level ``router_summary`` rollup (the exact-percentile
        record telemetry-report prefers over re-aggregating windows)."""
        self._stop_event.set()
        if self._scrape_thread is not None:
            self._scrape_thread.join(timeout=5.0)
            self._scrape_thread = None
        if self._heartbeat is not None:
            # Final flush so the file records the closing request count;
            # the scrape thread is joined, so this thread is the sole
            # owner of the beat now.
            with self._lock:
                routed = self._run["requests"]
            self._heartbeat.beat(routed)
        self.flush_window()
        with self._lock:
            routed_any = self._run["requests"] > 0
        if self._emit_fn is not None and routed_any:
            try:
                self._emit_fn(self.snapshot())
            except Exception:
                pass


# -- HTTP front end ---------------------------------------------------------
# Deliberately self-contained (not serve/http.py, which imports the
# engine stack): the router process never needs jax.

MAX_BODY_BYTES = 1 << 20


class RouterHTTPServer(http.server.ThreadingHTTPServer):
    daemon_threads = True
    # The fleet front door: a client connect burst overflows the stdlib
    # listen backlog of 5 and the kernel RSTs the excess mid-handshake.
    request_queue_size = 128
    router: Router = None


def _make_router_handler():
    class Handler(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # telemetry is the log
            pass

        def _reply(self, code: int, payload: dict,
                   headers: Optional[Dict[str, str]] = None) -> None:
            body = json.dumps(payload).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for name, value in (headers or {}).items():
                self.send_header(name, value)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            router = self.server.router
            if self.path == "/healthz":
                healthy = router.healthy_count()
                total = router.replica_count()
                ok = healthy > 0
                self._reply(200 if ok else 503, {
                    "status": "ok" if ok else "no_healthy_replica",
                    "healthy_replicas": healthy,
                    "replicas": total,
                })
            elif self.path == "/statsz":
                self._reply(200, router.snapshot())
            elif self.path == "/metricsz":
                # Prometheus text format (version 0.0.4), so the fleet
                # collector and standard scrapers consume ONE format
                # across replicas, trainers, and the router; /statsz
                # stays the human JSON surface.
                body = router.metrics_text().encode("utf-8")
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._reply(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            router = self.server.router
            if not self.path.startswith("/v1/"):
                self._reply(404, {"error": f"no route {self.path}"})
                return
            task = self.path[len("/v1/"):].strip("/")
            try:
                length = int(self.headers.get("Content-Length", 0))
                if length > MAX_BODY_BYTES:
                    self._reply(413, {"error": "payload too large"})
                    return
                payload = json.loads(
                    self.rfile.read(length).decode("utf-8") or "{}")
                if not isinstance(payload, dict):
                    raise ValueError("payload must be a JSON object")
            except ValueError as exc:
                self._reply(400, {"error": f"bad JSON payload: {exc}"})
                return
            status, body, headers = router.handle(task, payload)
            self._reply(status, body, headers)

    return Handler


def make_router_server(router: Router, host: str = "127.0.0.1",
                       port: int = 8100) -> RouterHTTPServer:
    """Build (but do not start) the router's HTTP server; ``port=0``
    binds an ephemeral port (tests read ``server.server_address``)."""
    server = RouterHTTPServer((host, port), _make_router_handler())
    server.router = router
    return server
