"""ServingService — the dispatch loop tying Batcher, InferenceEngine, and
ServeTelemetry together (docs/serving.md).

HTTP worker threads (or the offline batch scorer) call :meth:`submit`:
the payload is preprocessed on the calling thread (tokenization
parallelizes across workers — the tokenizers are thread-safe, see
data/tokenization.py), enqueued, and the caller blocks until the single
dispatch thread fulfils the request. The dispatch thread drains the
batcher, plans each flushed group onto the smallest bucket (packing when
enabled), runs the jitted forward, demultiplexes, postprocesses, and
records one telemetry observation per batch.

One dispatch thread is deliberate: JAX dispatch is not thread-safe-fast,
and a single consumer keeps batches maximal. Concurrency lives in the
HTTP layer (many blocked submitters) and on the device (the batch).

Shutdown is a graceful DRAIN (docs/fault_tolerance.md): :meth:`stop`
first flips the service to draining — new submissions shed with
:class:`ServiceDraining` (the HTTP layer's 503, so load balancers stop
routing on the next health probe) — then lets the dispatch thread flush
every already-accepted request before stopping it and flushing the
serve-telemetry summary. In-flight clients get answers, not resets.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from bert_pytorch_tpu.serve.batcher import Batcher, Request
from bert_pytorch_tpu.serve.engine import InferenceEngine
from bert_pytorch_tpu.serve.stats import ServeTelemetry
from bert_pytorch_tpu.serve.tracing import TraceCollector
from bert_pytorch_tpu.testing import faults


class ServiceDraining(RuntimeError):
    """Submission rejected: the service is draining for shutdown (the
    HTTP layer maps this to 503, like :class:`~bert_pytorch_tpu.serve.
    batcher.BatcherFull` overload shedding)."""


class ServingService:
    def __init__(
        self,
        engine: InferenceEngine,
        batcher: Batcher,
        telemetry: Optional[ServeTelemetry] = None,
        tracer: Optional[TraceCollector] = None,
        heartbeat=None,
        heartbeat_interval_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        """``tracer`` enables request-level span tracing + the /metricsz
        export (serve/tracing.py); None skips all trace bookkeeping (the
        overhead guard's baseline). Note one deliberate measurement
        change vs the pre-tracing dispatch loop, tracer or not: each
        request's completion is now stamped AFTER its own postprocess
        (previously one batch-wide timestamp taken before any
        postprocess), so e2e latency honestly includes the decode the
        client actually waited for — a few ms per request at most, but
        visible against pre-PR-9 serve baselines. NOTE: phase spans subtract
        timestamps the batcher stamped, so a tracer-carrying service and
        its batcher must share one ``clock`` (both default to
        ``time.monotonic``). ``heartbeat`` is an optional
        :class:`~bert_pytorch_tpu.telemetry.sentinels.Heartbeat` the
        dispatch loop beats at most every ``heartbeat_interval_s`` — the
        same resumable liveness file the training runners write, so the
        capture harness covers serving processes too."""
        self.engine = engine
        self.batcher = batcher
        self.telemetry = telemetry or ServeTelemetry()
        self.tracer = tracer
        if tracer is not None:
            # /statsz then carries the run-level phase rollup, keeping
            # one scrape surface consistent with /metricsz.
            self.telemetry.attach_tracer(tracer)
        self._heartbeat = heartbeat
        self._heartbeat_interval_s = float(heartbeat_interval_s)
        self._clock = clock
        # Guards _thread and _draining (the concurrency registry,
        # analysis/concurrency.py, enforced by jaxlint LK501): begin_drain
        # runs on a signal-handling/main thread while every HTTP worker
        # reads _draining in submit and /healthz reads _thread liveness.
        self._state_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._draining = False

    # -- request side ----------------------------------------------------

    def submit(self, task: str, payload: dict,
               timeout: Optional[float] = 30.0) -> dict:
        """Prepare, enqueue, and wait for one request; returns the task
        handler's JSON-able result. Raises ValueError for bad payloads /
        unknown tasks, TimeoutError when the deadline passes,
        ServiceDraining once shutdown has begun."""
        with self._state_lock:
            draining = self._draining
        if draining:
            raise ServiceDraining(
                "service is draining for shutdown; not accepting requests")
        spec = self.engine.tasks.get(task)
        if spec is None:
            raise ValueError(
                f"unknown task {task!r}; serving: "
                f"{sorted(self.engine.tasks)}")
        t_prep0 = self._clock()
        features = spec.handler.prepare(payload, self.engine.max_len())
        request = Request(task, features, payload)
        request.prepare_s = self._clock() - t_prep0
        self.batcher.submit(request)
        if not request.wait(timeout):
            # Nobody will read the result: let the dispatch thread skip
            # the forward instead of spending device time on it.
            request.abandoned = True
            self.telemetry.observe_error()
            if self.tracer is not None:
                self.tracer.observe_error(task)
            raise TimeoutError(f"request timed out after {timeout}s")
        if request.error is not None:
            raise RuntimeError(request.error)
        return request.result

    # -- dispatch side ---------------------------------------------------

    def process_batch(self, batch: List[Request]) -> None:
        """Plan, execute, demultiplex, postprocess, observe one flushed
        group (callable directly for deterministic tests and offline
        scoring — the background thread just loops it).

        With a tracer attached, each completed request is decomposed
        into the serve/tracing.py span taxonomy: ``queue`` (enqueue ->
        batcher pop), ``assembly`` (pop -> device dispatch: planning,
        bucket choice, packing/padding, plus the demux host conversion),
        ``execute`` (the batch's jitted forward incl. device sync,
        shared), and ``postprocess`` (the request's own handler decode).
        """
        popped = len(batch)
        requeued = 0
        try:
            requeued = self._process_batch(batch)
        finally:
            # Everything popped that was not requeued is finished
            # (result, error, or abandoned-and-skipped) — the batcher's
            # in-flight accounting is what a graceful drain waits on
            # (Batcher.unfinished; the requeue-during-drain fix).
            self.batcher.done(popped - requeued)

    def _process_batch(self, batch: List[Request]) -> int:
        """The dispatch body; returns how many requests were requeued as
        plan leftovers (the in-flight bookkeeping in the wrapper)."""
        batch = [r for r in batch if not r.abandoned]
        if not batch:
            return 0
        entry = self._clock()
        for req in batch:
            if req.enqueued_at is None:
                # Directly-constructed requests (offline scoring, tests)
                # never passed through Batcher.submit/pop — anchor their
                # life at batch entry so e2e latency and trace spans
                # measure this call, not clock-origin process uptime
                # (which would also force-trace every one as over-SLO).
                req.enqueued_at = req.dequeued_at = entry
        task = batch[0].task
        spec = self.engine.tasks[task]
        plan = self.engine.plan_batch(batch)
        requeued = len(plan.leftover)
        if plan.leftover:
            self.batcher.requeue_front(plan.leftover)
        try:
            outputs, info = self.engine.execute(task, plan)
        except Exception as exc:  # fulfil waiters; the server stays up
            now = self._clock()
            for req in plan.requests:
                req.set_error(f"{type(exc).__name__}: {exc}", now)
                self.telemetry.observe_error()
                if self.tracer is not None:
                    self.tracer.observe_error(task)
            return requeued
        exec_done = self._clock()
        device_s = info["device_s"]
        budget = info["rows"] * info["bucket"]
        occupancy = (info["real_tokens"] / budget) if budget else None
        e2e = []
        now = exec_done
        for req, out in zip(plan.requests, outputs):
            # Fresh read, not the previous iteration's `now`: the prior
            # request's tracer emit happens between iterations and must
            # not be attributed to THIS request's postprocess span.
            pp_start = self._clock()
            try:
                result = spec.handler.postprocess(
                    req.features, out, req.payload)
                now = self._clock()
                req.device_s = device_s
                req.set_result(result, now)
                total_s = now - req.enqueued_at
                e2e.append(total_s)
            except Exception as exc:
                now = self._clock()
                req.set_error(f"{type(exc).__name__}: {exc}", now)
                self.telemetry.observe_error()
                if self.tracer is not None:
                    self.tracer.observe_error(task)
                continue
            if self.tracer is None:
                continue
            try:
                # Outside the fulfilment try: the result is already
                # delivered, and a telemetry emit failure (sink closed
                # mid-shutdown, disk full) must not flip a fulfilled
                # request into the error path.
                queue_s = max(0.0, req.dequeued_at - req.enqueued_at)
                self.tracer.observe(
                    task, req.id,
                    phases_s={
                        "queue": queue_s,
                        # Everything between the pop and the forward
                        # returning that was not device time.
                        "assembly": max(
                            0.0, exec_done - req.dequeued_at - device_s),
                        "execute": device_s,
                        "postprocess": now - pp_start,
                    },
                    total_s=total_s,
                    bucket=info["bucket"],
                    packed=info["packed"],
                    batch_requests=len(plan.requests),
                    occupancy=occupancy,
                    prepare_s=req.prepare_s,
                    pack_s=info.get("pack_s"),
                )
            except Exception:
                pass  # observability must never break serving
        if e2e:
            self.telemetry.observe_batch(
                e2e_s=e2e,
                device_s=device_s,
                rows=info["rows"],
                bucket=info["bucket"],
                real_tokens=info["real_tokens"],
                queue_depth=self.batcher.depth(),
                compiles=info["compiles"],
            )
        return requeued

    def _loop(self) -> None:
        # last_beat stays a local: heartbeat cadence state is owned by
        # this thread alone (the Heartbeat binding itself is frozen
        # after __init__ — concurrency registry).
        last_beat = 0.0
        while not self._stop.is_set():
            batch = self.batcher.next_batch(timeout=0.1)
            if batch:
                self.process_batch(batch)
                # Chaos hook (testing/faults.py `wedge@N`): after N
                # served requests this call never returns — the
                # dispatch thread hangs with /healthz still answering
                # 200, which is exactly the failure only the
                # supervisor's heartbeat watchdog can catch. Inert
                # (one dict lookup) unless a fault spec is armed.
                faults.get_plan().serve_wedge_check(
                    self.telemetry.request_count(),
                    emit=self.telemetry.emit)
            if self._heartbeat is not None:
                now = self._clock()
                if now - last_beat >= self._heartbeat_interval_s:
                    last_beat = now
                    # step = requests served so far: the serving analog
                    # of the training step counter the harness reads.
                    self._heartbeat.beat(self.telemetry.request_count())

    def start(self) -> None:
        if not self.engine.warmed:
            self.engine.warmup()
        # One serve_cold_start record per engine start: how long warmup
        # took and how many compiles were real vs persistent-cache hits
        # (docs/serving.md "Inference fast path"); also lands in /statsz.
        # getattr: test fakes (and pre-warmed engines from older callers)
        # may not carry startup stats — a missing record beats a crash.
        self.telemetry.observe_cold_start(
            getattr(self.engine, "startup", None))
        self.telemetry.reset_clock()  # rps measures serving, not warmup
        if self._heartbeat is not None:
            # First beat before any traffic: liveness is visible the
            # moment the dispatch thread exists, not after the first
            # request (the training runners beat from step 1 onward).
            self._heartbeat.beat(self.telemetry.request_count())
        self._stop.clear()
        thread = threading.Thread(
            target=self._loop, name="serve-dispatch", daemon=True)
        with self._state_lock:
            self._draining = False
            self._thread = thread
        thread.start()

    # -- health / drain ----------------------------------------------------

    @property
    def draining(self) -> bool:
        with self._state_lock:
            return self._draining

    @property
    def dispatch_alive(self) -> bool:
        """True while the dispatch thread exists and is running — the
        liveness /healthz must report (an HTTP thread answering proves
        nothing about the thread that actually serves results)."""
        with self._state_lock:
            thread = self._thread
        return thread is not None and thread.is_alive()

    def health(self) -> dict:
        """Liveness snapshot for /healthz (serve/http.py): ``ok`` only
        when the dispatch thread is alive and not draining — anything
        else is a 503 so load balancers stop routing here. One lock
        acquisition reads a CONSISTENT (draining, thread) pair — the
        status string and the boolean fields must not disagree mid-drain.
        """
        with self._state_lock:
            draining = self._draining
            thread = self._thread
        alive = thread is not None and thread.is_alive()
        if draining:
            status = "draining"
        elif alive:
            status = "ok"
        else:
            status = "not_serving"  # never started, or dispatch died
        return {
            "status": status,
            "dispatch_alive": alive,
            "draining": draining,
            "queue_depth": self.batcher.depth(),
        }

    def begin_drain(self) -> None:
        """Flip to draining: new submissions shed with ServiceDraining /
        HTTP 503; already-accepted requests keep being served. Called at
        the start of :meth:`stop` (or earlier, by a signal handler that
        wants health probes failing before the HTTP listener closes)."""
        with self._state_lock:
            self._draining = True

    def stop(self, drain_s: float = 2.0) -> None:
        """Graceful drain: stop accepting, flush already-queued requests
        for up to ``drain_s`` seconds, stop the dispatch thread, flush the
        serve telemetry summary.

        The drain waits on :meth:`Batcher.unfinished` (pending PLUS
        in-flight), not queue depth: depth reads 0 the moment a batch is
        popped, and stopping in that window used to close the batcher
        under a dispatch thread about to requeue plan leftovers —
        stranding accepted requests with blocked waiters until their
        client-side timeout. Any request still unserved when the drain
        deadline passes (or when dispatch is dead) is now failed
        DETERMINISTICALLY instead."""
        self.begin_drain()
        deadline = self._clock() + drain_s
        while self.batcher.unfinished() and self._clock() < deadline:
            if not self.dispatch_alive:
                break  # nobody is left to finish them; fail them below
            time.sleep(0.01)
        self._stop.set()
        self.batcher.close()
        # Detach under the lock, join OUTSIDE it: holding _state_lock
        # through a 5s join would block every /healthz probe mid-shutdown.
        with self._state_lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
        # Deterministic drain flush: whatever the dispatch thread never
        # got to (drain deadline passed, or dispatch died) gets an
        # explicit error NOW — a blocked submitter wakes immediately
        # with a 500-class answer instead of timing out.
        stranded = self.batcher.drain_remaining()
        if stranded:
            now = self._clock()
            for req in stranded:
                req.set_error(
                    "service stopped before this request was dispatched "
                    "(drain deadline)", now)
                self.telemetry.observe_error()
                if self.tracer is not None:
                    self.tracer.observe_error(req.task)
        self.telemetry.finish()  # also flushes the attached tracer
        if self._heartbeat is not None and (
                thread is None or not thread.is_alive()):
            # Final beat only once the loop thread is provably gone:
            # Heartbeat.beat is not thread-safe (it relies on the thread
            # lifecycle for serialization), and a join that timed out
            # would leave the loop free to beat concurrently — skipping
            # one last beat beats tearing the liveness file.
            self._heartbeat.beat(self.telemetry.request_count())

    # -- metrics export ---------------------------------------------------

    def metrics_text(self) -> Optional[str]:
        """The full /metricsz payload (Prometheus text format): the
        tracer's per-task counters + phase histograms, then the
        service-level gauges a router wants in the same scrape — queue
        depth, dispatch liveness, run occupancy, cold-start cost. None
        when no tracer is attached (the HTTP layer 404s)."""
        if self.tracer is None:
            return None
        lines = [self.tracer.metrics_text().rstrip("\n")]
        # Base gauges only: the phases sub-object would recompute the
        # tracer's whole percentile rollup per scrape and be discarded.
        snap = self.telemetry.snapshot(include_phases=False)

        def gauge(name, value, help_text):
            if value is None:
                return
            lines.append(f"# HELP bert_serve_{name} {help_text}")
            lines.append(f"# TYPE bert_serve_{name} gauge")
            lines.append(f"bert_serve_{name} {float(value):g}")

        gauge("queue_depth", self.batcher.depth(),
              "Requests pending in the batcher queue.")
        gauge("dispatch_alive", 1.0 if self.dispatch_alive else 0.0,
              "1 while the dispatch thread is running.")
        gauge("draining", 1.0 if self.draining else 0.0,
              "1 once shutdown drain has begun.")
        gauge("batch_occupancy", snap.get("batch_occupancy"),
              "Run-level real tokens / dispatched slot budget.")
        gauge("cold_start_seconds", snap.get("cold_start_s"),
              "Engine AOT warmup wall time (serve_cold_start record).")
        gauge("warmup_compiles_cold", snap.get("warmup_compiles_cold"),
              "Real XLA compiles during warmup (0 = warm restart).")
        return "\n".join(lines) + "\n"
