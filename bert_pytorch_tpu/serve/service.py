"""ServingService — the dispatch plane tying Batcher, InferenceEngine,
and ServeTelemetry together (docs/serving.md "Continuous batching").

HTTP worker threads (or the offline batch scorer) call :meth:`submit`:
the payload is preprocessed on the calling thread (tokenization
parallelizes across workers — the tokenizers are thread-safe, see
data/tokenization.py), enqueued, and the caller blocks until the
dispatch plane fulfils the request.

Two dispatch modes (``--dispatch_mode``):

* **pipelined** (default) — continuous batching in the Orca
  iteration-level-scheduling lineage (Yu et al., OSDI 2022), adapted to
  the one-shot encoder workload. Three stages, each its own thread:

  - the **assembler** does host-only work: it pops flushed groups, plans
    them (bucket choice, FFD packing), stages the fixed-shape arrays,
    and — while the executor is busy and the staged handoff is full —
    keeps the batch it is FORMING open to late admission
    (:meth:`Batcher.admit_into_forming`): requests that arrive while
    batch N executes join batch N+1's plan up to the bucket/pack budget
    instead of waiting for the next flush;
  - the **executor** is the ONLY thread that touches the device (the
    one-device-thread invariant; the serving mirror of PR 6's
    DevicePrefetcher discipline): it consumes fully-staged plans from a
    depth-1 handoff, so back-to-back jitted forwards run with no
    assembly gap — the executor-gap (device-idle) share is measured and
    exported;
  - the **completion** stage demultiplexes (host conversion) and runs
    handler postprocess, so client decode never blocks the next device
    step.

* **serial** — the pre-pipeline flush-then-wait loop (one thread plans,
  packs, executes, and postprocesses in strict sequence), kept for A/B
  measurement and offline scoring via :meth:`process_batch`.

One device thread is deliberate in both modes: JAX dispatch is not
thread-safe-fast, and a single consumer keeps batches maximal.
Concurrency lives in the HTTP layer (many blocked submitters), the host
pipeline stages, and on the device (the batch).

Shutdown is a graceful DRAIN (docs/fault_tolerance.md): :meth:`stop`
first flips the service to draining — new submissions shed with
:class:`ServiceDraining` (the HTTP layer's 503, so load balancers stop
routing on the next health probe) — then waits on
:meth:`Batcher.unfinished` (pending + in-flight across EVERY pipeline
stage) before stopping the stage threads. Whatever is still inside the
plane then gets a deterministic outcome: batches the executor already
finished are FLUSHED (the answers exist — demux + postprocess run on
the stopping thread), everything staged-but-unexecuted, still forming,
or still pending is FAILED immediately. In-flight clients get answers,
not resets.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Callable, Dict, List, Optional

from bert_pytorch_tpu.serve.batcher import Batcher, Request
# One source of truth for the mode names: the CLI surface (argparse
# choices) and this constructor's validation must never drift.
from bert_pytorch_tpu.serve.cli import DISPATCH_MODES
from bert_pytorch_tpu.serve.engine import InferenceEngine
from bert_pytorch_tpu.serve.stats import ServeTelemetry
from bert_pytorch_tpu.serve.tracing import TraceCollector
from bert_pytorch_tpu.testing import faults


class ServiceDraining(RuntimeError):
    """Submission rejected: the service is draining for shutdown (the
    HTTP layer maps this to 503, like :class:`~bert_pytorch_tpu.serve.
    batcher.BatcherFull` overload shedding)."""


class _Executed:
    """One executed batch in flight between the executor and completion
    stages: the staged batch, its device output (or the execute error),
    and the executor's timing — ``gap_s`` is the device-idle gap since
    the previous forward ended (None for the first batch)."""

    def __init__(self, staged, out, info, error, exec_start, exec_done,
                 gap_s):
        self.staged = staged
        self.out = out
        self.info = info
        self.error = error
        self.exec_start = exec_start
        self.exec_done = exec_done
        self.gap_s = gap_s


class ServingService:
    def __init__(
        self,
        engine: InferenceEngine,
        batcher: Batcher,
        telemetry: Optional[ServeTelemetry] = None,
        tracer: Optional[TraceCollector] = None,
        heartbeat=None,
        heartbeat_interval_s: float = 1.0,
        capture=None,
        clock: Callable[[], float] = time.monotonic,
        dispatch_mode: str = "pipelined",
    ):
        """``tracer`` enables request-level span tracing + the /metricsz
        export (serve/tracing.py); None skips all trace bookkeeping (the
        overhead guard's baseline). NOTE: phase spans subtract
        timestamps the batcher stamped, so a tracer-carrying service and
        its batcher must share one ``clock`` (both default to
        ``time.monotonic``). ``heartbeat`` is an optional
        :class:`~bert_pytorch_tpu.telemetry.sentinels.Heartbeat` the
        dispatch plane beats at most every ``heartbeat_interval_s`` (the
        completion stage in pipelined mode — the thread whose progress
        means clients are getting answers) — the same resumable liveness
        file the training runners write, so the capture harness covers
        serving processes too. ``capture`` is an optional
        :class:`~bert_pytorch_tpu.telemetry.sampler.CaptureController`
        (``POST /profilez`` arms it via serve/http.py; the dispatch
        plane ticks it at the same boundary the heartbeat rides, with
        position = requests served). ``dispatch_mode`` selects the pipelined
        continuous-batching plane (default) or the serial
        flush-then-wait loop (module docstring)."""
        if dispatch_mode not in DISPATCH_MODES:
            raise ValueError(
                f"dispatch_mode must be one of {DISPATCH_MODES}, got "
                f"{dispatch_mode!r}")
        self.engine = engine
        self.batcher = batcher
        self.telemetry = telemetry or ServeTelemetry()
        self.tracer = tracer
        self.dispatch_mode = dispatch_mode
        if tracer is not None:
            # /statsz then carries the run-level phase rollup, keeping
            # one scrape surface consistent with /metricsz.
            self.telemetry.attach_tracer(tracer)
        self._heartbeat = heartbeat
        self._heartbeat_interval_s = float(heartbeat_interval_s)
        # Frozen binding (concurrency registry): HTTP workers arm it,
        # the dispatch plane ticks it; the controller locks itself.
        self.capture = capture
        self._clock = clock
        # Guards _threads, _draining, _forming, and _stage_inflight (the
        # concurrency registry, analysis/concurrency.py, enforced by
        # jaxlint LK501): begin_drain runs on a signal-handling/main
        # thread, every HTTP worker reads _draining in submit and thread
        # liveness in /healthz, the stage threads update their in-flight
        # markers, and /metricsz reads the forming-depth gauge.
        self._state_lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._draining = False
        # Pipelined-plane state. The queues and the hunger event are
        # bound once and never rebound (frozen; Queue/Event lock
        # themselves). The depth-1 handoff plus the executor's hunger
        # signal are what make the admission window real: the assembler
        # keeps its forming batch OPEN to late admission until the
        # executor is actually waiting (or the batch is full), so a
        # batch is never frozen partial while the device is busy.
        self._handoff: "queue_mod.Queue" = queue_mod.Queue(maxsize=1)
        self._completed_q: "queue_mod.Queue" = queue_mod.Queue()
        self._hungry = threading.Event()
        self._forming = 0                    # forming-batch depth gauge
        self._stage_inflight: Dict[str, object] = {}
        # How long a blocked handoff put waits before re-checking the
        # admission window for newly-arrived requests.
        self._admit_poll_s = 0.002
        # Confined to the assembler thread: the admit_hold chaos hook's
        # batch counter (testing/faults.py).
        self._batches_assembled = 0
        # Confined to the single device-calling thread (serial dispatch
        # thread; the pipelined executor keeps its own local): when the
        # previous forward ended, for the executor-gap measurement.
        self._last_exec_end: Optional[float] = None

    # -- request side ----------------------------------------------------

    def submit(self, task: str, payload: dict,
               timeout: Optional[float] = 30.0,
               trace_ctx: Optional[dict] = None) -> dict:
        """Prepare, enqueue, and wait for one request; returns the task
        handler's JSON-able result. Raises ValueError for bad payloads /
        unknown tasks, TimeoutError when the deadline passes,
        ServiceDraining once shutdown has begun. ``trace_ctx`` is the
        inbound router trace context (serve/http.py parses the
        ``X-Bert-Trace`` header) forwarded to the tracer so fleet-wide
        sampling stays consistent."""
        with self._state_lock:
            draining = self._draining
        if draining:
            raise ServiceDraining(
                "service is draining for shutdown; not accepting requests")
        spec = self.engine.tasks.get(task)
        if spec is None:
            raise ValueError(
                f"unknown task {task!r}; serving: "
                f"{sorted(self.engine.tasks)}")
        t_prep0 = self._clock()
        features = spec.handler.prepare(payload, self.engine.max_len())
        request = Request(task, features, payload)
        request.prepare_s = self._clock() - t_prep0
        request.trace_ctx = trace_ctx
        self.batcher.submit(request)
        if not request.wait(timeout):
            # Nobody will read the result: let the dispatch plane skip
            # the forward instead of spending device time on it.
            request.abandoned = True
            self.telemetry.observe_error()
            if self.tracer is not None:
                self.tracer.observe_error(task)
            raise TimeoutError(f"request timed out after {timeout}s")
        if request.error is not None:
            raise RuntimeError(request.error)
        return request.result

    # -- serial dispatch (A/B baseline, offline scoring, tests) ----------

    def process_batch(self, batch: List[Request]) -> None:
        """Plan, execute, demultiplex, postprocess, observe one flushed
        group in strict sequence (callable directly for deterministic
        tests and offline scoring — the serial background thread just
        loops it).

        With a tracer attached, each completed request is decomposed
        into the serve/tracing.py span taxonomy: ``queue`` (enqueue ->
        batcher pop), ``assembly`` (pop -> device dispatch: planning,
        bucket choice, packing/padding, plus the demux host conversion),
        ``execute`` (the batch's jitted forward incl. device sync,
        shared), and ``postprocess`` (the request's own handler decode).
        (Pipelined dispatch measures the same taxonomy per stage — see
        :meth:`_complete` for its assembly semantics.)
        """
        popped = len(batch)
        requeued = 0
        try:
            requeued = self._process_batch(batch)
        finally:
            # Everything popped that was not requeued is finished
            # (result, error, or abandoned-and-skipped) — the batcher's
            # in-flight accounting is what a graceful drain waits on
            # (Batcher.unfinished; the requeue-during-drain fix).
            self.batcher.done(popped - requeued)

    def _process_batch(self, batch: List[Request]) -> int:
        """The serial dispatch body; returns how many requests were
        requeued as plan leftovers (the in-flight bookkeeping in the
        wrapper)."""
        batch = [r for r in batch if not r.abandoned]
        if not batch:
            return 0
        entry = self._clock()
        for req in batch:
            if req.enqueued_at is None:
                # Directly-constructed requests (offline scoring, tests)
                # never passed through Batcher.submit/pop — anchor their
                # life at batch entry so e2e latency and trace spans
                # measure this call, not clock-origin process uptime
                # (which would also force-trace every one as over-SLO).
                req.enqueued_at = req.dequeued_at = entry
        task = batch[0].task
        spec = self.engine.tasks[task]
        plan = self.engine.plan_batch(batch)
        requeued = len(plan.leftover)
        if plan.leftover:
            self.batcher.requeue_front(plan.leftover)
        try:
            staged = self.engine.stage(task, plan)
            exec_start = self._clock()
            out, info = self.engine.execute_staged(staged)
            exec_end = self._clock()
            outputs = self.engine.demux(staged, out)
        except Exception as exc:  # fulfil waiters; the server stays up
            now = self._clock()
            for req in plan.requests:
                req.set_error(f"{type(exc).__name__}: {exc}", now)
                self.telemetry.observe_error()
                if self.tracer is not None:
                    self.tracer.observe_error(task)
            return requeued
        # Executor-gap measurement, serial flavor: the device idles from
        # the end of the previous forward to the start of this one
        # (assembly, demux, and postprocess all sit in that gap — the
        # idle the pipelined plane exists to squeeze out).
        gap_s = (exec_start - self._last_exec_end
                 if self._last_exec_end is not None else None)
        self._last_exec_end = exec_end
        exec_done = self._clock()
        device_s = info["device_s"]
        budget = info["rows"] * info["bucket"]
        occupancy = (info["real_tokens"] / budget) if budget else None
        e2e = []
        now = exec_done
        for req, out in zip(plan.requests, outputs):
            # Fresh read, not the previous iteration's `now`: the prior
            # request's tracer emit happens between iterations and must
            # not be attributed to THIS request's postprocess span.
            pp_start = self._clock()
            try:
                result = spec.handler.postprocess(
                    req.features, out, req.payload)
                now = self._clock()
                req.device_s = device_s
                req.set_result(result, now)
                total_s = now - req.enqueued_at
                e2e.append(total_s)
            except Exception as exc:
                now = self._clock()
                req.set_error(f"{type(exc).__name__}: {exc}", now)
                self.telemetry.observe_error()
                if self.tracer is not None:
                    self.tracer.observe_error(task)
                continue
            if self.tracer is None:
                continue
            try:
                # Outside the fulfilment try: the result is already
                # delivered, and a telemetry emit failure (sink closed
                # mid-shutdown, disk full) must not flip a fulfilled
                # request into the error path.
                queue_s = max(0.0, req.dequeued_at - req.enqueued_at)
                self.tracer.observe(
                    task, req.id,
                    phases_s={
                        "queue": queue_s,
                        # Everything between the pop and the forward
                        # returning that was not device time.
                        "assembly": max(
                            0.0, exec_done - req.dequeued_at - device_s),
                        "execute": device_s,
                        "postprocess": now - pp_start,
                    },
                    total_s=total_s,
                    bucket=info["bucket"],
                    packed=info["packed"],
                    batch_requests=len(plan.requests),
                    occupancy=occupancy,
                    prepare_s=req.prepare_s,
                    pack_s=info.get("pack_s"),
                    admitted_late=req.admitted_late,
                    trace_ctx=req.trace_ctx,
                )
            except Exception:
                pass  # observability must never break serving
        if e2e:
            self.telemetry.observe_batch(
                e2e_s=e2e,
                device_s=device_s,
                rows=info["rows"],
                bucket=info["bucket"],
                real_tokens=info["real_tokens"],
                queue_depth=self.batcher.depth(),
                compiles=info["compiles"],
                exec_gap_s=gap_s,
            )
        return requeued

    def _loop(self) -> None:
        # last_beat stays a local: heartbeat cadence state is owned by
        # this thread alone (the Heartbeat binding itself is frozen
        # after __init__ — concurrency registry).
        last_beat = 0.0
        while not self._stop.is_set():
            batch = self.batcher.next_batch(timeout=0.1)
            if batch:
                self.process_batch(batch)
                # Chaos hook (testing/faults.py `wedge@N`): after N
                # served requests this call never returns — the
                # dispatch thread hangs with /healthz still answering
                # 200, which is exactly the failure only the
                # supervisor's heartbeat watchdog can catch. Inert
                # (one dict lookup) unless a fault spec is armed.
                faults.get_plan().serve_wedge_check(
                    self.telemetry.request_count(),
                    emit=self.telemetry.emit)
            last_beat = self._maybe_beat(last_beat)
            self._capture_tick()

    # -- pipelined dispatch: assembler / executor / completion -----------

    def _set_forming(self, depth: int) -> None:
        with self._state_lock:
            self._forming = int(depth)

    def _note_stage_inflight(self, stage: str, item) -> None:
        """Track the batch a stage thread is currently holding so a
        drain that outlives the join grace can fail its requests
        deterministically (stop -> _drain_pipeline)."""
        with self._state_lock:
            if item is None:
                self._stage_inflight.pop(stage, None)
            else:
                self._stage_inflight[stage] = item

    def _assemble_loop(self) -> None:
        """Assembler stage: pop -> plan -> stage, host-only. The batch
        being formed stays OPEN to late admission for as long as the
        executor is busy: newly arrived same-task requests are admitted
        into it — up to the bucket/pack budget — and the plan is
        re-staged (host work, overlapped with the running forward). The
        batch is handed off only when the executor signals hunger (it
        is waiting RIGHT NOW, so the pre-staged arrays cross the
        depth-1 handoff with no assembly gap) or when it reaches the
        flush budget (a full batch parks in the handoff early — it
        cannot grow anyway, and parking frees this stage to form the
        next one). That window is continuous batching's whole point: a
        request that lands mid-execute rides the NEXT device step, not
        the one after — and no partial batch is ever frozen while the
        device is busy (a frozen partial batch still costs a full
        fixed-shape forward)."""
        while not self._stop.is_set():
            group = self.batcher.next_batch(timeout=0.05)
            if not group:
                continue
            live = [r for r in group if not r.abandoned]
            if len(live) < len(group):
                self.batcher.done(len(group) - len(live))
            if not live:
                continue
            self._form_and_hand_off(live)
            self._set_forming(0)

    def _form_and_hand_off(self, live: List[Request]) -> None:
        """The admission window for one popped group: plan, stage,
        admit, re-stage, and hand off on executor hunger or a full
        budget. Owns every outcome for the group's requests: handed to
        the executor, requeued when stop() closes the window first, or
        failed deterministically when planning/staging raises (the
        serial loop fails the batch and keeps serving — so does this
        stage; a dead assembler would strand requests in in-flight
        accounting with no queue to sweep them from)."""
        task = live[0].task
        plan = None
        # Admitted requests the re-plan has not absorbed yet: if the
        # re-plan itself raises, these are in-flight (their submitters
        # are blocked, the batcher counted them) but in NO plan — the
        # exception handler must fail them too or they leak until the
        # client-side timeout and permanently inflate unfinished().
        admitted_unmerged: List[Request] = []
        try:
            plan = self.engine.plan_batch(live)
            if plan.leftover:
                self.batcher.requeue_front(plan.leftover)
            self._set_forming(len(plan.requests))
            self._batches_assembled += 1
            # Chaos hook (testing/faults.py `admit_hold@N`): hold the
            # admission window open on the Nth formed batch so the
            # chaos harness can SIGKILL this replica with requests
            # provably inside the forming batch. Inert unless armed.
            faults.get_plan().serve_admit_check(
                self._batches_assembled, emit=self.telemetry.emit)
            staged = None
            admit_open = True
            while not self._stop.is_set():
                if staged is None:
                    staged = self.engine.stage(task, plan)
                    staged.staged_at = self._clock()
                full = len(plan.requests) >= self.batcher.flush_size()
                if self._hungry.is_set() or full:
                    try:
                        self._handoff.put(staged,
                                          timeout=self._admit_poll_s)
                        return
                    except queue_mod.Full:
                        # A full batch is already parked and the
                        # executor has not taken it yet; fall through
                        # to the admission window below.
                        pass
                if not admit_open:
                    self._hungry.wait(timeout=self._admit_poll_s)
                    continue
                # Admission window: the executor is busy — anything
                # arriving NOW joins THIS forming plan instead of
                # waiting for its own flush.
                room = self.batcher.flush_size() - len(plan.requests)
                admitted = self.batcher.admit_into_forming(task, room)
                if not admitted:
                    # Nothing new: hold the window open a beat — waking
                    # INSTANTLY if the executor goes hungry, so the
                    # pre-staged batch crosses the handoff with no
                    # assembly gap.
                    self._hungry.wait(timeout=self._admit_poll_s)
                    continue
                fresh = [r for r in admitted if not r.abandoned]
                if len(fresh) < len(admitted):
                    self.batcher.done(len(admitted) - len(fresh))
                if not fresh:
                    continue
                admitted_unmerged = fresh
                replanned = self.engine.plan_batch(plan.requests + fresh)
                if replanned.leftover:
                    # The re-plan could not place everything (packed
                    # rows full below the request budget): give the
                    # overflow back and CLOSE the window — admitting
                    # again would just pop the same requests into the
                    # same leftover, a re-stage spin that burns the
                    # assembler until the executor goes hungry
                    # (requeue_front clears their admitted_late marker;
                    # a future flush serves them).
                    self.batcher.requeue_front(replanned.leftover)
                    admit_open = False
                if replanned.requests != plan.requests:
                    staged = None  # re-stage with the admitted requests
                plan = replanned
                admitted_unmerged = []
                self._set_forming(len(plan.requests))
            # stop() raced the handoff: give the forming batch back so
            # the drain path fails (or a restart serves) it
            # deterministically instead of dropping it on the floor.
            self.batcher.requeue_front(plan.requests)
        except Exception as exc:
            self._fail_batch(
                (plan.requests if plan is not None else live)
                + admitted_unmerged,
                f"{type(exc).__name__}: {exc}")

    def _execute_loop(self) -> None:
        """Executor stage: the ONLY thread that touches the device.
        Consumes fully-staged plans from the depth-1 handoff so
        back-to-back jitted forwards run with no assembly gap; the gap
        that remains (handoff empty — the assembler could not keep up,
        or there was no traffic) is measured and exported as the
        device-idle share."""
        last_end: Optional[float] = None
        while True:
            # Hunger signal: tells the assembler "hand me your forming
            # batch NOW" — admission closes for that batch the moment
            # the device is actually ready for it, not a deadline
            # earlier (cleared below while a forward runs).
            self._hungry.set()
            try:
                staged = self._handoff.get(timeout=0.05)
            except queue_mod.Empty:
                if self._stop.is_set():
                    return
                continue
            self._hungry.clear()
            self._note_stage_inflight("executor", staged)
            exec_start = self._clock()
            gap_s = (exec_start - last_end) if last_end is not None else None
            try:
                out, info = self.engine.execute_staged(staged)
                error = None
            except Exception as exc:
                out, info = None, None
                error = f"{type(exc).__name__}: {exc}"
            exec_done = self._clock()
            last_end = exec_done
            self._completed_q.put(_Executed(
                staged, out, info, error, exec_start, exec_done, gap_s))
            self._note_stage_inflight("executor", None)

    def _complete_loop(self) -> None:
        """Completion stage: demux (host conversion) + handler
        postprocess + fulfilment + telemetry, off the device thread.
        Beats the heartbeat (progress here means clients are getting
        answers) and carries the wedge chaos hook the serial loop had —
        a wedged completion stage is exactly the healthz-still-200
        failure only the supervisor's watchdog can catch."""
        last_beat = 0.0
        while True:
            try:
                done = self._completed_q.get(timeout=0.05)
            except queue_mod.Empty:
                if self._stop.is_set():
                    return
                last_beat = self._maybe_beat(last_beat)
                self._capture_tick()
                continue
            self._note_stage_inflight("completion", done)
            self._complete(done)
            self._note_stage_inflight("completion", None)
            faults.get_plan().serve_wedge_check(
                self.telemetry.request_count(),
                emit=self.telemetry.emit)
            last_beat = self._maybe_beat(last_beat)
            self._capture_tick()

    def _complete(self, done: _Executed) -> None:
        """Finish one executed batch: demux, postprocess, fulfil,
        observe. Pipelined span semantics (docs/serving.md): ``queue``
        = enqueue -> pop/admission, ``assembly`` = pop -> staging
        complete (planning + packing + padding, the host work actually
        done FOR this batch), ``execute`` = the jitted forward incl.
        sync, ``postprocess`` = the request's own handler decode. The
        staged-handoff wait (staging complete -> executor pickup) and
        the completion-queue wait are pipeline buffering, not work —
        they ride sampled traces as ``staged_wait_ms`` context, and the
        span-sum invariant (sum <= total) holds by construction because
        every span is a disjoint sub-interval of the request's life."""
        staged, info = done.staged, done.info
        plan = staged.plan
        task = staged.task
        if done.error is not None:
            now = self._clock()
            for req in plan.requests:
                req.set_error(done.error, now)
                self.telemetry.observe_error()
                if self.tracer is not None:
                    self.tracer.observe_error(task)
            self.batcher.done(len(plan.requests))
            return
        spec = self.engine.tasks[task]
        try:
            # Same contract as the serial loop's execute try: a demux
            # failure (host conversion of a malformed device output)
            # fails THIS batch's requests and keeps the stage serving —
            # it must never kill the completion thread.
            outputs = self.engine.demux(staged, done.out)
        except Exception as exc:
            self._fail_batch(plan.requests,
                             f"{type(exc).__name__}: {exc}")
            return
        device_s = info["device_s"]
        budget = info["rows"] * info["bucket"]
        occupancy = (info["real_tokens"] / budget) if budget else None
        staged_at = staged.staged_at if staged.staged_at is not None \
            else done.exec_start
        staged_wait_s = max(0.0, done.exec_start - staged_at)
        # Late-admission count over the requests that actually produce
        # an e2e sample: observe_batch's window_requests basis excludes
        # postprocess failures, and the schema lint holds
        # admitted_late <= window_requests.
        n_late = 0
        e2e = []
        now = done.exec_done
        for req, out in zip(plan.requests, outputs):
            pp_start = self._clock()
            try:
                result = spec.handler.postprocess(
                    req.features, out, req.payload)
                now = self._clock()
                req.device_s = device_s
                req.set_result(result, now)
                total_s = now - req.enqueued_at
                e2e.append(total_s)
                if req.admitted_late:
                    n_late += 1
            except Exception as exc:
                now = self._clock()
                req.set_error(f"{type(exc).__name__}: {exc}", now)
                self.telemetry.observe_error()
                if self.tracer is not None:
                    self.tracer.observe_error(task)
                continue
            if self.tracer is None:
                continue
            try:
                queue_s = max(0.0, req.dequeued_at - req.enqueued_at)
                self.tracer.observe(
                    task, req.id,
                    phases_s={
                        "queue": queue_s,
                        # Host work done for this batch after this
                        # request joined it (plan + pack + pad; a
                        # late-admitted request only pays the re-stage).
                        "assembly": max(0.0, staged_at - req.dequeued_at),
                        "execute": device_s,
                        "postprocess": now - pp_start,
                    },
                    total_s=total_s,
                    bucket=info["bucket"],
                    packed=info["packed"],
                    batch_requests=len(plan.requests),
                    occupancy=occupancy,
                    prepare_s=req.prepare_s,
                    pack_s=info.get("pack_s"),
                    admitted_late=req.admitted_late,
                    staged_wait_s=staged_wait_s,
                    trace_ctx=req.trace_ctx,
                )
            except Exception:
                pass  # observability must never break serving
        if e2e:
            self.telemetry.observe_batch(
                e2e_s=e2e,
                device_s=device_s,
                rows=info["rows"],
                bucket=info["bucket"],
                real_tokens=info["real_tokens"],
                queue_depth=self.batcher.depth(),
                compiles=info["compiles"],
                admitted_late=n_late,
                exec_gap_s=done.gap_s,
            )
        self.batcher.done(len(plan.requests))

    def _capture_tick(self) -> None:
        """On-demand capture boundary (telemetry/sampler.py): starts an
        armed capture, collects an expired one. Rides the same
        single-owner position as the heartbeat — the serial dispatch
        thread, or the completion stage in pipelined mode — with
        position = requests served (``covered_unit: "requests"``)."""
        if self.capture is not None:
            self.capture.tick(self.telemetry.request_count())

    def _maybe_beat(self, last_beat: float) -> float:
        if self._heartbeat is None:
            return last_beat
        now = self._clock()
        if now - last_beat >= self._heartbeat_interval_s:
            # step = requests served so far: the serving analog of the
            # training step counter the harness reads.
            self._heartbeat.beat(self.telemetry.request_count())
            return now
        return last_beat

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if not self.engine.warmed:
            self.engine.warmup()
        # One serve_cold_start record per engine start: how long warmup
        # took and how many compiles were real vs persistent-cache hits
        # (docs/serving.md "Inference fast path"); also lands in /statsz.
        # getattr: test fakes (and pre-warmed engines from older callers)
        # may not carry startup stats — a missing record beats a crash.
        self.telemetry.observe_cold_start(
            getattr(self.engine, "startup", None))
        self.telemetry.reset_clock()  # rps measures serving, not warmup
        if self._heartbeat is not None:
            # First beat before any traffic: liveness is visible the
            # moment the dispatch plane exists, not after the first
            # request (the training runners beat from step 1 onward).
            self._heartbeat.beat(self.telemetry.request_count())
        self._stop.clear()
        if self.dispatch_mode == "pipelined":
            targets = (("serve-assembler", self._assemble_loop),
                       ("serve-executor", self._execute_loop),
                       ("serve-completion", self._complete_loop))
        else:
            targets = (("serve-dispatch", self._loop),)
        threads = [threading.Thread(target=fn, name=name, daemon=True)
                   for name, fn in targets]
        with self._state_lock:
            self._draining = False
            self._threads = threads
        for thread in threads:
            thread.start()

    # -- hot swap ----------------------------------------------------------

    def serving_version(self) -> Optional[str]:
        """The engine's serving model version (getattr: test fakes may
        not carry the swap plumbing — None then, and the version fields
        simply stay off the surfaces)."""
        version = getattr(self.engine, "version", None)
        return version() if callable(version) else None

    def swap(self, task: str, checkpoint: str, version: str) -> dict:
        """Hot-swap one task to ``checkpoint`` as ``version`` (the
        /swapz control endpoint, docs/serving.md "Model registry &
        canary rollouts"). Runs on the calling (HTTP control) thread —
        the load happens off the dispatch path and only the atomic flip
        touches state the executor reads; in-flight batches complete
        against the old version. Raises engine.SwapBusy when a swap is
        already in flight (HTTP 409)."""
        return self.engine.swap_params(
            task, checkpoint, version, emit=self.telemetry.emit)

    # -- health / drain ----------------------------------------------------

    @property
    def draining(self) -> bool:
        with self._state_lock:
            return self._draining

    @property
    def dispatch_alive(self) -> bool:
        """True while EVERY stage thread of the dispatch plane exists
        and is running — the liveness /healthz must report (an HTTP
        thread answering proves nothing about the threads that actually
        serve results, and a dead executor with a live assembler is
        still a dead replica)."""
        with self._state_lock:
            threads = list(self._threads)
        return bool(threads) and all(t.is_alive() for t in threads)

    def health(self) -> dict:
        """Liveness snapshot for /healthz (serve/http.py): ``ok`` only
        when every stage thread is alive and not draining — anything
        else is a 503 so load balancers stop routing here. One lock
        acquisition reads a CONSISTENT (draining, threads) set — the
        status string and the boolean fields must not disagree
        mid-drain. ``unfinished`` (pending + in-flight across every
        stage) rides along so a scraper without /metricsz still sees
        the honest load signal (queue_depth alone reads 0 the instant a
        batch pops)."""
        with self._state_lock:
            draining = self._draining
            threads = list(self._threads)
            forming = self._forming
        alive = bool(threads) and all(t.is_alive() for t in threads)
        if draining:
            status = "draining"
        elif alive:
            status = "ok"
        else:
            status = "not_serving"  # never started, or a stage died
        health = {
            "status": status,
            "dispatch_alive": alive,
            "draining": draining,
            "dispatch_mode": self.dispatch_mode,
            "queue_depth": self.batcher.depth(),
            "unfinished": self.batcher.unfinished(),
        }
        version = self.serving_version()
        if version is not None:
            # The serving model version rides /healthz too: chaos
            # replicas run without a tracer (no /metricsz), and the
            # router's scrape fallback must still learn the version
            # (serve/router.py default_scrape).
            health["version"] = version
        if self.dispatch_mode == "pipelined":
            health["stages"] = {
                t.name.replace("serve-", "", 1): t.is_alive()
                for t in threads}
            health["forming_depth"] = forming
        return health

    def begin_drain(self) -> None:
        """Flip to draining: new submissions shed with ServiceDraining /
        HTTP 503; already-accepted requests keep being served. Called at
        the start of :meth:`stop` (or earlier, by a signal handler that
        wants health probes failing before the HTTP listener closes)."""
        with self._state_lock:
            self._draining = True

    def stop(self, drain_s: float = 2.0, join_s: float = 5.0) -> None:
        """Graceful drain: stop accepting, flush already-accepted
        requests for up to ``drain_s`` seconds, stop the stage threads
        (each given ``join_s`` to exit), fail-or-flush whatever is still
        inside the pipeline, flush the serve telemetry summary.

        The drain waits on :meth:`Batcher.unfinished` (pending PLUS
        in-flight across EVERY stage — forming batch, staged handoff,
        executing batch, completion queue), not queue depth: depth reads
        0 the moment a batch is popped. Any request still unserved when
        the drain deadline passes (or when a stage is dead/stuck) is
        failed DETERMINISTICALLY; batches the executor already finished
        are flushed — their answers exist, so their clients get them."""
        self.begin_drain()
        deadline = self._clock() + drain_s
        while self.batcher.unfinished() and self._clock() < deadline:
            if not self.dispatch_alive:
                break  # nobody is left to finish them; fail them below
            time.sleep(0.01)
        self._stop.set()
        self.batcher.close()
        # Detach under the lock, join OUTSIDE it: holding _state_lock
        # through the joins would block every /healthz probe mid-shutdown.
        with self._state_lock:
            threads, self._threads = self._threads, []
        for thread in threads:
            thread.join(timeout=join_s)
        # Deterministic fail-or-flush for everything the stage threads
        # never got to (drain deadline passed, a stage died, or a stage
        # is wedged past the join grace): blocked submitters wake NOW
        # with a definite answer instead of timing out client-side.
        self._drain_pipeline()
        stranded = self.batcher.drain_remaining()
        if stranded:
            self._fail_requests(
                stranded,
                "service stopped before this request was dispatched "
                "(drain deadline)")
        self.telemetry.finish()  # also flushes the attached tracer
        if self._heartbeat is not None and all(
                not t.is_alive() for t in threads):
            # Final beat only once the stage threads are provably gone:
            # Heartbeat.beat is not thread-safe (it relies on the thread
            # lifecycle for serialization), and a join that timed out
            # would leave a loop free to beat concurrently — skipping
            # one last beat beats tearing the liveness file.
            self._heartbeat.beat(self.telemetry.request_count())

    def _drain_pipeline(self) -> None:
        """Stop-time sweep of the pipelined plane (a no-op in serial
        mode — both queues are empty). Executed-but-undelivered batches
        are FLUSHED (demux + postprocess on this thread); batches a
        wedged stage still holds, and staged-but-unexecuted batches, are
        FAILED. Ordering matters: the stage in-flight MARKERS are swept
        FIRST — the executor puts its result into the completed queue
        BEFORE clearing its marker, so any batch absent from the
        markers is either fully retired or already visible in the
        queue, and draining the queue last closes the window where an
        executor running past the join grace slips a finished batch
        between the two sweeps. A wedged stage waking later and
        double-finishing is harmless: fulfilment events are already
        set, and the batcher's in-flight counter clamps at zero."""
        with self._state_lock:
            inflight = dict(self._stage_inflight)
            self._stage_inflight.clear()
        comp = inflight.get("completion")
        if comp is not None:
            self._fail_batch(
                comp.staged.plan.requests,
                "service stopped while this request was in the "
                "completion stage (drain deadline)")
        executing = inflight.get("executor")
        if executing is not None:
            self._fail_batch(
                executing.plan.requests,
                "service stopped while this request's batch was "
                "executing (drain deadline)")
        while True:
            try:
                done = self._completed_q.get_nowait()
            except queue_mod.Empty:
                break
            if done is comp or (executing is not None
                                and done.staged is executing):
                # This batch was in the queue AND still marked (the
                # executor put it, then wedged before clearing): the
                # marker sweep above already failed and retired it.
                continue
            self._complete(done)
        while True:
            try:
                staged = self._handoff.get_nowait()
            except queue_mod.Empty:
                break
            self._fail_batch(
                staged.plan.requests,
                "service stopped with this request staged but "
                "unexecuted (drain deadline)")

    def _fail_batch(self, requests: List[Request], message: str) -> None:
        """Fail every still-unanswered request of one stranded batch and
        retire the whole batch from the in-flight accounting."""
        self._fail_requests(
            [r for r in requests if r.completed_at is None], message)
        self.batcher.done(len(requests))

    def _fail_requests(self, requests: List[Request], message: str) -> None:
        now = self._clock()
        for req in requests:
            req.set_error(message, now)
            self.telemetry.observe_error()
            if self.tracer is not None:
                self.tracer.observe_error(req.task)

    # -- metrics export ---------------------------------------------------

    def metrics_text(self) -> Optional[str]:
        """The full /metricsz payload (Prometheus text format): the
        tracer's per-task counters + phase histograms, then the
        service-level gauges a router wants in the same scrape — queue
        depth, the unfinished (pending + in-flight) load signal,
        forming-batch depth, dispatch liveness, device-idle share, run
        occupancy, cold-start cost. None when no tracer is attached
        (the HTTP layer 404s)."""
        if self.tracer is None:
            return None
        lines = [self.tracer.metrics_text().rstrip("\n")]
        # Base gauges only: the phases sub-object would recompute the
        # tracer's whole percentile rollup per scrape and be discarded.
        snap = self.telemetry.snapshot(include_phases=False)
        with self._state_lock:
            forming = self._forming

        def gauge(name, value, help_text):
            if value is None:
                return
            lines.append(f"# HELP bert_serve_{name} {help_text}")
            lines.append(f"# TYPE bert_serve_{name} gauge")
            lines.append(f"bert_serve_{name} {float(value):g}")

        gauge("queue_depth", self.batcher.depth(),
              "Requests pending in the batcher queue (reads 0 the "
              "instant a batch pops — balance on unfinished).")
        gauge("unfinished", self.batcher.unfinished(),
              "Requests pending + in-flight across every dispatch "
              "stage — the load signal the router balances and "
              "brownouts on.")
        gauge("forming_depth", forming,
              "Requests in the assembler's forming batch (the "
              "admission window).")
        gauge("dispatch_alive", 1.0 if self.dispatch_alive else 0.0,
              "1 while every dispatch-plane stage thread is running.")
        gauge("draining", 1.0 if self.draining else 0.0,
              "1 once shutdown drain has begun.")
        gauge("batch_occupancy", snap.get("batch_occupancy"),
              "Run-level real tokens / dispatched slot budget.")
        gauge("device_idle_share", snap.get("device_idle_share"),
              "Executor gap share: device idle between consecutive "
              "forwards / (idle + busy).")
        gauge("cold_start_seconds", snap.get("cold_start_s"),
              "Engine AOT warmup wall time (serve_cold_start record).")
        gauge("warmup_compiles_cold", snap.get("warmup_compiles_cold"),
              "Real XLA compiles during warmup (0 = warm restart).")
        version = self.serving_version()
        if version is not None:
            # Label-valued gauge (value is always 1; the label carries
            # the version string) — the idiomatic Prometheus "info"
            # metric, and what the router's scrape parses.
            lines.append("# HELP bert_serve_serving_version The model "
                         "version this replica is serving (label).")
            lines.append("# TYPE bert_serve_serving_version gauge")
            lines.append(
                f'bert_serve_serving_version{{version="{version}"}} 1')
        return "\n".join(lines) + "\n"
