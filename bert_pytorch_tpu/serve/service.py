"""ServingService — the dispatch loop tying Batcher, InferenceEngine, and
ServeTelemetry together (docs/serving.md).

HTTP worker threads (or the offline batch scorer) call :meth:`submit`:
the payload is preprocessed on the calling thread (tokenization
parallelizes across workers — the tokenizers are thread-safe, see
data/tokenization.py), enqueued, and the caller blocks until the single
dispatch thread fulfils the request. The dispatch thread drains the
batcher, plans each flushed group onto the smallest bucket (packing when
enabled), runs the jitted forward, demultiplexes, postprocesses, and
records one telemetry observation per batch.

One dispatch thread is deliberate: JAX dispatch is not thread-safe-fast,
and a single consumer keeps batches maximal. Concurrency lives in the
HTTP layer (many blocked submitters) and on the device (the batch).

Shutdown is a graceful DRAIN (docs/fault_tolerance.md): :meth:`stop`
first flips the service to draining — new submissions shed with
:class:`ServiceDraining` (the HTTP layer's 503, so load balancers stop
routing on the next health probe) — then lets the dispatch thread flush
every already-accepted request before stopping it and flushing the
serve-telemetry summary. In-flight clients get answers, not resets.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from bert_pytorch_tpu.serve.batcher import Batcher, Request
from bert_pytorch_tpu.serve.engine import InferenceEngine
from bert_pytorch_tpu.serve.stats import ServeTelemetry


class ServiceDraining(RuntimeError):
    """Submission rejected: the service is draining for shutdown (the
    HTTP layer maps this to 503, like :class:`~bert_pytorch_tpu.serve.
    batcher.BatcherFull` overload shedding)."""


class ServingService:
    def __init__(
        self,
        engine: InferenceEngine,
        batcher: Batcher,
        telemetry: Optional[ServeTelemetry] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.engine = engine
        self.batcher = batcher
        self.telemetry = telemetry or ServeTelemetry()
        self._clock = clock
        # Guards _thread and _draining (the concurrency registry,
        # analysis/concurrency.py, enforced by jaxlint LK501): begin_drain
        # runs on a signal-handling/main thread while every HTTP worker
        # reads _draining in submit and /healthz reads _thread liveness.
        self._state_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._draining = False

    # -- request side ----------------------------------------------------

    def submit(self, task: str, payload: dict,
               timeout: Optional[float] = 30.0) -> dict:
        """Prepare, enqueue, and wait for one request; returns the task
        handler's JSON-able result. Raises ValueError for bad payloads /
        unknown tasks, TimeoutError when the deadline passes,
        ServiceDraining once shutdown has begun."""
        with self._state_lock:
            draining = self._draining
        if draining:
            raise ServiceDraining(
                "service is draining for shutdown; not accepting requests")
        spec = self.engine.tasks.get(task)
        if spec is None:
            raise ValueError(
                f"unknown task {task!r}; serving: "
                f"{sorted(self.engine.tasks)}")
        features = spec.handler.prepare(payload, self.engine.max_len())
        request = Request(task, features, payload)
        self.batcher.submit(request)
        if not request.wait(timeout):
            # Nobody will read the result: let the dispatch thread skip
            # the forward instead of spending device time on it.
            request.abandoned = True
            self.telemetry.observe_error()
            raise TimeoutError(f"request timed out after {timeout}s")
        if request.error is not None:
            raise RuntimeError(request.error)
        return request.result

    # -- dispatch side ---------------------------------------------------

    def process_batch(self, batch: List[Request]) -> None:
        """Plan, execute, demultiplex, postprocess, observe one flushed
        group (callable directly for deterministic tests and offline
        scoring — the background thread just loops it)."""
        batch = [r for r in batch if not r.abandoned]
        if not batch:
            return
        task = batch[0].task
        spec = self.engine.tasks[task]
        plan = self.engine.plan_batch(batch)
        if plan.leftover:
            self.batcher.requeue_front(plan.leftover)
        now = self._clock()
        try:
            outputs, info = self.engine.execute(task, plan)
        except Exception as exc:  # fulfil waiters; the server stays up
            now = self._clock()
            for req in plan.requests:
                req.set_error(f"{type(exc).__name__}: {exc}", now)
                self.telemetry.observe_error()
            return
        now = self._clock()
        e2e = []
        for req, out in zip(plan.requests, outputs):
            try:
                result = spec.handler.postprocess(
                    req.features, out, req.payload)
                req.device_s = info["device_s"]
                req.set_result(result, now)
                e2e.append(now - req.enqueued_at)
            except Exception as exc:
                req.set_error(f"{type(exc).__name__}: {exc}", now)
                self.telemetry.observe_error()
        if e2e:
            self.telemetry.observe_batch(
                e2e_s=e2e,
                device_s=info["device_s"],
                rows=info["rows"],
                bucket=info["bucket"],
                real_tokens=info["real_tokens"],
                queue_depth=self.batcher.depth(),
                compiles=info["compiles"],
            )

    def _loop(self) -> None:
        while not self._stop.is_set():
            batch = self.batcher.next_batch(timeout=0.1)
            if batch:
                self.process_batch(batch)

    def start(self) -> None:
        if not self.engine.warmed:
            self.engine.warmup()
        # One serve_cold_start record per engine start: how long warmup
        # took and how many compiles were real vs persistent-cache hits
        # (docs/serving.md "Inference fast path"); also lands in /statsz.
        # getattr: test fakes (and pre-warmed engines from older callers)
        # may not carry startup stats — a missing record beats a crash.
        self.telemetry.observe_cold_start(
            getattr(self.engine, "startup", None))
        self.telemetry.reset_clock()  # rps measures serving, not warmup
        self._stop.clear()
        thread = threading.Thread(
            target=self._loop, name="serve-dispatch", daemon=True)
        with self._state_lock:
            self._draining = False
            self._thread = thread
        thread.start()

    # -- health / drain ----------------------------------------------------

    @property
    def draining(self) -> bool:
        with self._state_lock:
            return self._draining

    @property
    def dispatch_alive(self) -> bool:
        """True while the dispatch thread exists and is running — the
        liveness /healthz must report (an HTTP thread answering proves
        nothing about the thread that actually serves results)."""
        with self._state_lock:
            thread = self._thread
        return thread is not None and thread.is_alive()

    def health(self) -> dict:
        """Liveness snapshot for /healthz (serve/http.py): ``ok`` only
        when the dispatch thread is alive and not draining — anything
        else is a 503 so load balancers stop routing here. One lock
        acquisition reads a CONSISTENT (draining, thread) pair — the
        status string and the boolean fields must not disagree mid-drain.
        """
        with self._state_lock:
            draining = self._draining
            thread = self._thread
        alive = thread is not None and thread.is_alive()
        if draining:
            status = "draining"
        elif alive:
            status = "ok"
        else:
            status = "not_serving"  # never started, or dispatch died
        return {
            "status": status,
            "dispatch_alive": alive,
            "draining": draining,
            "queue_depth": self.batcher.depth(),
        }

    def begin_drain(self) -> None:
        """Flip to draining: new submissions shed with ServiceDraining /
        HTTP 503; already-accepted requests keep being served. Called at
        the start of :meth:`stop` (or earlier, by a signal handler that
        wants health probes failing before the HTTP listener closes)."""
        with self._state_lock:
            self._draining = True

    def stop(self, drain_s: float = 2.0) -> None:
        """Graceful drain: stop accepting, flush already-queued requests
        for up to ``drain_s`` seconds, stop the dispatch thread, flush the
        serve telemetry summary."""
        self.begin_drain()
        deadline = self._clock() + drain_s
        while self.batcher.depth() and self._clock() < deadline:
            time.sleep(0.01)
        self._stop.set()
        self.batcher.close()
        # Detach under the lock, join OUTSIDE it: holding _state_lock
        # through a 5s join would block every /healthz probe mid-shutdown.
        with self._state_lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
        self.telemetry.finish()
